"""Capacity-unit metering.

Parity: src/server/capacity_unit_calculator.h:50 — every request bills
read/write capacity units: 1 CU per started 4KB of key+value bytes
(min 1 per request), accumulated into per-partition counters.
"""

from __future__ import annotations

from pegasus_tpu.utils.metrics import MetricEntity

CU_SIZE = 4096


def units(size: int) -> int:
    """CU for ONE request of `size` bytes (min 1 — the per-request
    floor the reference bills, capacity_unit_calculator.h:50)."""
    return max(1, (size + CU_SIZE - 1) // CU_SIZE)


class CapacityUnitCalculator:
    def __init__(self, entity: MetricEntity) -> None:
        self._read_cu = entity.counter("recent_read_cu")
        self._write_cu = entity.counter("recent_write_cu")

    def add_read(self, size: int) -> None:
        self._read_cu.increment(units(size))

    def add_read_units(self, cu: int) -> None:
        """Batch accounting: the caller pre-summed units(size) per
        request (hot scan path — one counter touch per batch)."""
        if cu:
            self._read_cu.increment(cu)

    def add_write(self, size: int) -> None:
        self._write_cu.increment(units(size))

    def add_write_units(self, cu: int) -> None:
        """Batch accounting: the caller pre-summed units(size) per
        request (mutation apply — one counter touch per mutation)."""
        if cu:
            self._write_cu.increment(cu)

    @property
    def read_cu(self) -> int:
        return self._read_cu.value()

    @property
    def write_cu(self) -> int:
        return self._write_cu.value()
