"""Server-held scan contexts with expiry.

Parity: src/server/pegasus_scan_context.h:91 — a paged scan saves its
iterator state server-side under a context id; the client continues with
on_scan(context_id) and the server GCs contexts unused for
FLAGS_rocksdb_scanner_expire_time (5 minutes default,
pegasus_server_impl.cpp:1362-1388). Our context stores the resume key
instead of a live iterator (LSM iterators are cheap to re-seek, and this
keeps no snapshot pinned — a deliberate departure noted in SURVEY §7
"scan-context lifetime").
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from pegasus_tpu.server.types import GetScannerRequest


@dataclass
class ScanContext:
    request: GetScannerRequest
    resume_key: bytes            # next full key to seek (exclusive of served)
    stop_key: bytes              # effective exclusive upper bound
    last_used: float = field(default_factory=time.monotonic)
    # aggregate-mode pushdown: the partition's PARTIAL aggregate
    # (ops/pushdown.AggState) accumulated so far, carried server-side
    # across pages so the partial ships exactly once (final page). A
    # lost context loses the partial WITH the pages it counted — the
    # client restarts the partition from its original start key, so
    # nothing double counts
    agg_state: Optional[object] = None


class ScanContextCache:
    def __init__(self, expire_seconds: float = 300.0) -> None:
        self._expire = expire_seconds
        self._contexts: Dict[int, ScanContext] = {}
        self._ids = itertools.count(1)
        self._lock = threading.Lock()

    def put(self, ctx: ScanContext) -> int:
        with self._lock:
            self._gc_locked()
            cid = next(self._ids)
            self._contexts[cid] = ctx
            return cid

    def take(self, context_id: int) -> Optional[ScanContext]:
        """Remove and return; callers re-insert (fresh id) when unfinished —
        same single-use contract as the reference's fetch/store pair."""
        with self._lock:
            ctx = self._contexts.pop(context_id, None)
            if ctx is None:
                return None
            if time.monotonic() - ctx.last_used > self._expire:
                return None
            ctx.last_used = time.monotonic()
            return ctx

    def remove(self, context_id: int) -> None:
        with self._lock:
            self._contexts.pop(context_id, None)

    def _gc_locked(self) -> None:
        now = time.monotonic()
        dead = [cid for cid, ctx in self._contexts.items()
                if now - ctx.last_used > self._expire]
        for cid in dead:
            del self._contexts[cid]

    def __len__(self) -> int:
        return len(self._contexts)
