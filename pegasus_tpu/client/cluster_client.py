"""ClusterClient: the client stack over a replicated cluster.

Parity: the reference client's resolution pipeline —
pegasus_client_impl (pegasus_client_impl.cpp:124 key hash) →
partition_resolver_simple (partition_resolver_simple.h:56: hash → cached
partition_configuration → primary address, re-query meta on error) →
gpid-addressed RPC served through the replica gates
(replica_stub.cpp:1100, replica.cpp:386).

Unlike `PegasusClient` (in-process Table), every op here crosses the
network abstraction: writes go through the primary's full 2PC, reads
through the primary's replica gate. The config cache refreshes on
ERR_INVALID_STATE-class errors and on reply timeouts.

The transport is pluggable: a `pump()` callable drives message delivery
while the client waits for a reply (the deterministic SimNetwork needs
its loop driven; a real socket transport pumps by blocking on the
socket).
"""

from __future__ import annotations

import itertools
import re
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

from pegasus_tpu.base.key_schema import generate_key, key_hash_parts, restore_key
from pegasus_tpu.client.client import ScanOptions
from pegasus_tpu.ops.predicates import host_match_filter
from pegasus_tpu.rpc.codec import (
    OP_CAM,
    OP_CAS,
    OP_INCR,
    OP_MULTI_PUT,
    OP_MULTI_REMOVE,
    OP_PUT,
    OP_REMOVE,
)
from pegasus_tpu.server.types import (
    BatchGetRequest,
    CheckAndMutateRequest,
    CheckAndMutateResponse,
    CheckAndSetRequest,
    CheckAndSetResponse,
    FullKey,
    GetScannerRequest,
    IncrRequest,
    KeyValue,
    MultiGetRequest,
    MultiPutRequest,
    MultiRemoveRequest,
    Mutate,
    SCAN_CONTEXT_ID_COMPLETED,
    SCAN_CONTEXT_ID_NOT_EXIST,
)
from pegasus_tpu.utils import tracing
from pegasus_tpu.utils.errors import ErrorCode, PegasusError, StorageStatus
from pegasus_tpu.utils.flags import FLAGS, define_flag

_RETRYABLE = {
    int(ErrorCode.ERR_INVALID_STATE),
    int(ErrorCode.ERR_INACTIVE_STATE),
    int(ErrorCode.ERR_PARENT_PARTITION_MISUSED),
    int(ErrorCode.ERR_OBJECT_NOT_FOUND),
    int(ErrorCode.ERR_TIMEOUT),
    int(ErrorCode.ERR_SPLITTING),
    # overload shedding (transport dispatcher): BUSY means "come back
    # after a backoff", exactly what the retry loop now does
    int(ErrorCode.ERR_BUSY),
    # storage-integrity failures: the replica quarantined itself and
    # the guardian is repairing via re-learn — the retry's config
    # refresh lands the op on the healed (or newly promoted) primary
    int(ErrorCode.ERR_CHECKSUM_FAILED),
    int(ErrorCode.ERR_DISK_IO_ERROR),
    # duplication failover drill: fenced-for-drain is transient — the
    # backoff (plus its config refresh) carries the op across the flip
    int(ErrorCode.ERR_DUP_FENCED),
    # follower-read bounce: the secondary's lease lapsed or its
    # watermark missed the op's staleness bound. The routing table is
    # still RIGHT — the retry skips the config refresh and re-sends
    # only the bounced ops to the primary (misrouted-subset discipline)
    int(ErrorCode.ERR_STALE_REPLICA),
    # multi-tenant QoS: this client's tenant is over its CU budget —
    # the jittered backoff rides out the bucket refill; like BUSY, no
    # config refresh (the routing table is right, the tenant is hot)
    int(ErrorCode.ERR_CU_OVERBUDGET),
}

_OK = int(ErrorCode.ERR_OK)
_MISROUTED = int(ErrorCode.ERR_PARENT_PARTITION_MISUSED)
_STALE = int(ErrorCode.ERR_STALE_REPLICA)
_OVERBUDGET = int(ErrorCode.ERR_CU_OVERBUDGET)

# codes whose retry must NOT burn a config refresh: the routing table
# is known-correct, the condition is server-side pressure. Re-resolving
# would only convert a read/write storm into a meta query storm.
_NO_REFRESH = {int(ErrorCode.ERR_BUSY), _STALE, _OVERBUDGET}

# the public retryability surface: client/aio.py re-exports these so
# the sync and async clients can never drift on which codes retry (the
# tier-1 retryability matrix test asserts the identity)
RETRYABLE_CODES = frozenset(_RETRYABLE)
NO_REFRESH_CODES = frozenset(_NO_REFRESH)

# tenant-tag sanitation mirrors server/tenancy.TENANT_RE — the tiny
# regex is duplicated here rather than imported so the client package
# never drags the server package (and its storage stack) in. Anything
# that fails the slug check folds to the shared "default" tenant, the
# same fold the server registry applies to unknown wire tags
_TENANT_RE = re.compile(r"^[a-z0-9][a-z0-9_\-]{0,31}$")
DEFAULT_TENANT = "default"


def sanitize_tenant(raw) -> str:
    """Fold an arbitrary tenant tag to a bounded-cardinality slug."""
    if isinstance(raw, str):
        name = raw.strip().lower()
        if _TENANT_RE.match(name):
            return name
    return DEFAULT_TENANT


def bounded_stale(max_lag_ms: float) -> dict:
    """Consistency level: serve at ANY replica whose committed state is
    at most `max_lag_ms` behind the primary's advertised commit point
    (measured on the replica's sync stamps, so the practical floor is
    the group-check cadence). Pass to any read's `consistency=`."""
    return {"level": "bounded_stale", "max_lag_ms": float(max_lag_ms)}


# Consistency level: reads never observe an older prefix than any read
# this client already observed for that partition (per-partition
# high-water committed-decree session tokens carried on every reply).
MONOTONIC = {"level": "monotonic"}

# Default consistency: primary-only reads, unchanged semantics.
LINEARIZABLE = None

define_flag("pegasus.client", "client_op_timeout_ms", 3_600_000,
            "end-to-end deadline for one client op, spanning every "
            "retry; requests carry the absolute deadline so servers "
            "can drop work its client stopped waiting for",
            mutable=True)


class ClusterClient:
    """Full data-plane client resolved through meta.

    `pump` is called repeatedly while waiting for a reply; each call
    should advance message delivery (and, in simulation, virtual time so
    failure detection can progress during retries).
    """

    def __init__(self, net, name: str, meta_addr, app_name: str,
                 pump: Callable[[], None],
                 max_retries: int = 6, pump_rounds: int = 50,
                 auth=None, op_timeout_ms: Optional[float] = None,
                 clock: Optional[Callable[[], float]] = None,
                 sleep: Optional[Callable[[float], None]] = None,
                 backoff_seed: Optional[int] = None,
                 tenant: Optional[str] = None) -> None:
        """`auth`: (user, token) credentials from
        security.make_credentials — required when the cluster enforces
        authentication.

        `op_timeout_ms` overrides the client_op_timeout_ms flag: every
        op gets ONE absolute deadline covering all its retries, stamped
        into each request so servers can fast-fail abandoned work.
        `clock` must be the same timebase the serving stubs read (wall
        time.time for the TCP path — the default; the sim cluster
        passes its epoch-anchored virtual clock). `sleep` is the retry
        backoff's wait (sim passes a virtual-time advance).

        `tenant`: the QoS identity every request from this handle is
        billed to (weighted-fair admission + per-tenant CU budgets,
        server/tenancy.py). When omitted, the table's
        `qos.default_tenant` env (adopted at config refresh) names the
        tenant; failing that, the shared "default" tenant."""
        from pegasus_tpu.utils.backoff import Backoff

        self.net = net
        self.name = name
        self.op_timeout_ms = op_timeout_ms
        self._clock = clock or time.time
        self.backoff = Backoff(seed=backoff_seed,
                               sleep=sleep or time.sleep)
        # one address or the whole meta group (rotated on timeout —
        # parity: the client's meta group_address failover)
        self.meta_addrs = ([meta_addr] if isinstance(meta_addr, str)
                           else list(meta_addr))
        self._meta_i = 0
        self.app_name = app_name
        self._pump = pump
        self._max_retries = max_retries
        self._pump_rounds = pump_rounds
        self._rids = itertools.count(1)
        self._replies: Dict[int, dict] = {}
        self._pending: set = set()
        self.app_id: Optional[int] = None
        self.partition_count = 0
        self._configs: List[dict] = []
        self.auth = tuple(auth) if auth else None
        # QoS identity: explicit ctor tag wins and sticks; otherwise
        # the table's qos.default_tenant env (seen at refresh_config)
        # may rebind the handle's tenant
        self._tenant_explicit = tenant is not None
        self.tenant = sanitize_tenant(tenant) if tenant is not None \
            else DEFAULT_TENANT
        # per-op consistency default for THIS client handle: None =
        # linearizable (primary-only). Set to MONOTONIC or
        # bounded_stale(ms) to opt every read in; any read's
        # `consistency=` kwarg overrides per op
        self.consistency: Optional[dict] = None
        # monotonic session tokens: pidx -> highest committed decree any
        # read reply has shown this client for that partition. Carried
        # as min_decree on monotonic reads so no replica may answer
        # below what this session already observed
        self._session_tokens: Dict[int, int] = {}
        # deterministic round-robin over a partition's secondaries
        self._replica_rr = 0
        # distributed tracing: the op-level root span (one per client
        # API call; nested helpers — batch_get's per-group _read legs —
        # ride the outer op's trace instead of minting their own)
        self._cur_span = None
        net.register(name, self._on_message)

    # ---- transport plumbing -------------------------------------------

    def _on_message(self, src: str, msg_type: str, payload) -> None:
        if isinstance(payload, dict):
            # tail-keep propagation: a reply stamped KEEP by a hop that
            # crossed the slow threshold pins this trace here too —
            # slow traces stay whole at every upstream hop
            tracing.on_inbound_ctx(self.name, payload.get("trace"))
        if msg_type in ("client_read_reply", "client_write_reply",
                        "query_config_reply", "negotiate_reply"):
            rid = payload.get("rid")
            # only requests still being awaited are stored: a reply that
            # straggles in after its _await gave up (e.g. delivered once a
            # partition heals) would otherwise accumulate forever
            if rid in self._pending:
                self._replies[rid] = payload

    def _traced(self, name: str, fn, *args):
        """Run one client op under a sampled root span (or plain when
        sampling says no / an outer op's span already governs)."""
        if self._cur_span is not None or not tracing.maybe_sample():
            return fn(*args)
        span = tracing.ring_for(self.name).start(name)
        self._cur_span = span
        try:
            return fn(*args)
        finally:
            self._cur_span = None
            span.finish()

    def _send_request(self, dst: str, msg_type: str, payload: dict,
                      deadline: Optional[float] = None) -> int:
        rid = next(self._rids)
        payload["rid"] = rid
        # every request carries its tenant tag: the transport's
        # weighted-fair admission and the server's CU budgets classify
        # by this field (untagged traffic folds to "default" serverside)
        payload["tenant"] = self.tenant
        if deadline is not None:
            # absolute, on the cluster's shared timebase: the transport
            # dispatcher and replica gates fast-fail work past it
            payload["deadline"] = deadline
        if self._cur_span is not None:
            # the op's trace context rides every request it issues
            # (explicit — the client never leaves a span ambient, so
            # unrelated timer traffic pumped while we wait stays clean)
            payload["trace"] = self._cur_span.ctx()
        self._pending.add(rid)
        self.net.send(self.name, dst, msg_type, payload)
        return rid

    def _deadline(self) -> float:
        ms = self.op_timeout_ms if self.op_timeout_ms is not None else \
            FLAGS.get("pegasus.client", "client_op_timeout_ms")
        return self._clock() + float(ms) / 1000.0

    def _await(self, rid: int,
               deadline: Optional[float] = None) -> Optional[dict]:
        try:
            for _ in range(self._pump_rounds):
                if rid in self._replies:
                    return self._replies.pop(rid)
                if deadline is not None and self._clock() > deadline:
                    break  # the op's deadline lapsed; stop pumping
                self._pump()
            return self._replies.pop(rid, None)
        finally:
            self._pending.discard(rid)

    def negotiate(self, node: str, user: str, secret: str) -> bool:
        """Run the SASL-style connection handshake with `node`
        (security/negotiation.py; parity negotiation.h:37). On success
        the server binds `user` to this client's address and requests
        to that node may omit per-request credentials."""
        from pegasus_tpu.security.negotiation import NegotiationClient

        nc = NegotiationClient(user, secret)

        def call(payload):
            rid = self._send_request(node, "negotiate", dict(payload))
            return self._await(rid) or {}

        return nc.negotiate(call)

    # ---- config cache (parity: partition_resolver_simple) -------------

    @property
    def meta_addr(self) -> str:
        return self.meta_addrs[self._meta_i % len(self.meta_addrs)]

    def refresh_config(self, deadline: Optional[float] = None) -> None:
        """`deadline`: the CALLING op's remaining end-to-end deadline —
        a refresh inside a retry loop must not mint itself a fresh full
        window (the op would overrun its declared bound by up to 2x)."""
        last = None
        if deadline is None:
            deadline = self._deadline()
        for rotation in range(len(self.meta_addrs)):
            if rotation:
                if self._clock() > deadline:
                    break  # out of time: surface the last rotation error
                # pace the meta-group rotation: hammering the next
                # member the instant the last timed out is how a
                # failover turns into a refresh_config storm
                self.backoff.sleep(rotation)
            rid = self._send_request(self.meta_addr, "query_config", {
                "app_name": self.app_name}, deadline=deadline)
            reply = self._await(rid, deadline)
            if reply is None:
                # this meta is down/partitioned: rotate to the next group
                # member (a follower forwards to the leader)
                self._meta_i += 1
                last = PegasusError(ErrorCode.ERR_TIMEOUT,
                                    f"meta {self.meta_addr} unreachable")
                continue
            if reply["err"] != _OK:
                raise PegasusError(ErrorCode(reply["err"]), self.app_name)
            self.app_id = reply["app_id"]
            self.partition_count = reply["partition_count"]
            self._configs = reply["configs"]
            if not self._tenant_explicit:
                # adopt the table's default tenant env; an explicit
                # ctor tag always wins over the table-wide default
                env = (reply.get("envs") or {}).get("qos.default_tenant")
                if env:
                    self.tenant = sanitize_tenant(env)
            return
        raise last

    def _ensure_config(self) -> None:
        if self.app_id is None:
            self.refresh_config()

    def _primary_of(self, pidx: int) -> str:
        return self._configs[pidx]["primary"]

    def _norm_consistency(self, consistency) -> Optional[dict]:
        """Resolve one read's effective consistency level: the per-op
        kwarg wins, else the client-handle default. Returns None for
        linearizable (primary-only), else the level dict the replica
        gate consumes."""
        c = consistency if consistency is not None else self.consistency
        if c is None or c == "linearizable":
            return None
        if c == "monotonic":
            return MONOTONIC
        if isinstance(c, dict) and c.get("level") in (
                "bounded_stale", "monotonic"):
            return c
        raise ValueError(f"unknown consistency level: {c!r}")

    def _route_read(self, pidx: int, cons: Optional[dict],
                    force_primary: bool = False) -> str:
        """Pick the serving node for one read leg: the primary for
        linearizable ops and for post-bounce retries, otherwise
        round-robin across ALL of the partition's replicas — primary
        included — (meta's routing table already ships the
        secondaries), so a replica group's aggregate read capacity
        scales with replica count instead of pinning every read to one
        node; primary fallback when no secondary exists."""
        cfg = self._configs[pidx]
        if cons is None or force_primary:
            return cfg["primary"]
        members = [n for n in (cfg["primary"],
                               *cfg.get("secondaries", ())) if n]
        if not members:
            return cfg["primary"]
        self._replica_rr += 1
        return members[self._replica_rr % len(members)]

    def _wire_consistency(self, cons: dict, pidx: int) -> dict:
        """Stamp the monotonic session token onto the wire level: the
        replica must not answer below the committed decree this client
        already observed for the partition."""
        if cons.get("level") == "monotonic":
            tok = self._session_tokens.get(pidx, 0)
            if tok:
                return dict(cons, min_decree=tok)
        return cons

    def _note_decree(self, pidx: int, decree) -> None:
        """Fold a reply's committed-decree stamp into the session
        token (monotonic high-water mark, never regresses)."""
        if decree is not None and \
                decree > self._session_tokens.get(pidx, 0):
            self._session_tokens[pidx] = decree

    # ---- request dispatch with refresh-on-error retry ------------------

    def _read(self, op: str, args: Any, pidx: int,
              partition_hash: Optional[int] = None,
              deadline: Optional[float] = None,
              consistency=None,
              prefer_node: Optional[str] = None) -> Any:
        return self._traced(f"client.{op}", self._read_impl, op, args,
                            pidx, partition_hash, deadline, consistency,
                            prefer_node)

    def _read_impl(self, op: str, args: Any, pidx: int,
                   partition_hash: Optional[int] = None,
                   deadline: Optional[float] = None,
                   consistency=None,
                   prefer_node: Optional[str] = None) -> Any:
        """`deadline`: inherited when this read is one leg of a larger
        op (batch_get) — the outer op's single end-to-end bound governs,
        never a freshly minted per-leg window. `prefer_node`: first-
        attempt routing override (scanner paging stickiness — a scan
        context lives on the node that opened it); retries fall back to
        normal routing."""
        self._ensure_config()
        cons = self._norm_consistency(consistency)
        force_primary = False
        last_err = int(ErrorCode.ERR_TIMEOUT)
        if deadline is None:
            deadline = self._deadline()
        for attempt in range(self._max_retries):
            if attempt:
                if self._clock() > deadline:
                    raise PegasusError(ErrorCode.ERR_TIMEOUT,
                                       f"{op} deadline exceeded")
                # backoff BEFORE the refresh: mid-failover zero-sleep
                # retries burn every attempt in microseconds and storm
                # the meta with refresh_config
                self.backoff.sleep(attempt)
                if last_err in _NO_REFRESH:
                    # shed by an overloaded replica, bounced by a stale
                    # secondary, or over CU budget — not misrouted: the
                    # config is still right, so no refresh (see
                    # _NO_REFRESH above)
                    pass
                else:
                    try:
                        self.refresh_config(deadline)
                    except PegasusError as e:
                        # an unreachable meta burns this retry, it
                        # doesn't abort the op: the cached config may
                        # still be right (and the meta may heal before
                        # the next attempt)
                        last_err = int(e.code)
            p = pidx if partition_hash is None else (
                partition_hash % self.partition_count)
            if prefer_node is not None and not attempt \
                    and not force_primary:
                dst = prefer_node
            else:
                dst = self._route_read(p, cons, force_primary)
            if not dst:
                continue  # partition momentarily unowned; refresh + retry
            wire = {"gpid": (self.app_id, p), "op": op,
                    "auth": self.auth, "args": args,
                    "partition_hash": partition_hash}
            if cons is not None:
                wire["consistency"] = self._wire_consistency(cons, p)
            rid = self._send_request(dst, "client_read", wire,
                                     deadline=deadline)
            reply = self._await(rid, deadline)
            if reply is None:
                last_err = int(ErrorCode.ERR_TIMEOUT)
                continue
            if reply["err"] in _RETRYABLE:
                last_err = reply["err"]
                if reply["err"] == _STALE:
                    # bounced by a lapsed-lease / too-stale secondary:
                    # ONLY this op re-flies, and it goes to the primary
                    force_primary = True
                continue
            if reply["err"] != _OK:
                raise PegasusError(ErrorCode(reply["err"]), op)
            self._note_decree(p, reply.get("decree"))
            return reply["result"]
        raise PegasusError(ErrorCode(last_err), f"{op} exhausted retries")

    def _write(self, ops: List[Tuple[int, Any]],
               partition_hash: int) -> List[Any]:
        return self._traced("client.write", self._write_impl, ops,
                            partition_hash)

    def _write_impl(self, ops: List[Tuple[int, Any]],
                    partition_hash: int) -> List[Any]:
        from pegasus_tpu.replica.mutation import ATOMIC_OPS

        self._ensure_config()
        retry_safe = all(op not in ATOMIC_OPS for op, _ in ops)
        last_err = int(ErrorCode.ERR_TIMEOUT)
        deadline = self._deadline()
        for attempt in range(self._max_retries):
            if attempt:
                if self._clock() > deadline:
                    raise PegasusError(ErrorCode.ERR_TIMEOUT,
                                       "write deadline exceeded")
                self.backoff.sleep(attempt)
                if last_err not in _NO_REFRESH:
                    # (BUSY/over-budget = server pressure, config still
                    # right — see _read; back off without re-resolving)
                    try:
                        self.refresh_config(deadline)
                    except PegasusError as e:
                        last_err = int(e.code)
            pidx = partition_hash % self.partition_count
            primary = self._primary_of(pidx)
            if not primary:
                continue
            rid = self._send_request(primary, "client_write", {
                "gpid": (self.app_id, pidx), "ops": ops,
                "auth": self.auth,
                "partition_hash": partition_hash}, deadline=deadline)
            reply = self._await(rid, deadline)
            if reply is None:
                # a LOST REPLY is ambiguous: the write may have committed.
                # Retrying a put/remove is idempotent; retrying incr/cas/
                # cam would double-apply — surface the timeout instead
                # (the reference client does the same for atomic ops)
                if not retry_safe:
                    raise PegasusError(ErrorCode.ERR_TIMEOUT,
                                       "atomic write reply lost")
                last_err = int(ErrorCode.ERR_TIMEOUT)
                continue
            if reply["err"] in _RETRYABLE:
                last_err = reply["err"]
                continue
            if reply["err"] != _OK:
                raise PegasusError(ErrorCode(reply["err"]), "write")
            return reply["results"]
        raise PegasusError(ErrorCode(last_err), "write exhausted retries")

    # ---- single-record ops --------------------------------------------

    def set(self, hash_key: bytes, sort_key: bytes, value: bytes,
            ttl_seconds: int = 0) -> int:
        from pegasus_tpu.base.value_schema import expire_ts_from_ttl

        ph = key_hash_parts(hash_key, sort_key)
        key = generate_key(hash_key, sort_key)
        results = self._write(
            [(OP_PUT, (key, value, expire_ts_from_ttl(ttl_seconds)))], ph)
        return results[0]

    def get(self, hash_key: bytes, sort_key: bytes,
            consistency=None) -> Tuple[int, bytes]:
        ph = key_hash_parts(hash_key, sort_key)
        return self._read("get", generate_key(hash_key, sort_key), -1,
                          ph, consistency=consistency)

    def delete(self, hash_key: bytes, sort_key: bytes) -> int:
        ph = key_hash_parts(hash_key, sort_key)
        results = self._write(
            [(OP_REMOVE, (generate_key(hash_key, sort_key),))], ph)
        return results[0]

    def exist(self, hash_key: bytes, sort_key: bytes) -> bool:
        return self.get(hash_key, sort_key)[0] == int(StorageStatus.OK)

    def ttl(self, hash_key: bytes, sort_key: bytes,
            consistency=None) -> Tuple[int, int]:
        ph = key_hash_parts(hash_key, sort_key)
        return self._read("ttl", generate_key(hash_key, sort_key), -1,
                          ph, consistency=consistency)

    def incr(self, hash_key: bytes, sort_key: bytes, increment: int,
             ttl_seconds: int = 0):
        ph = key_hash_parts(hash_key, sort_key)
        req = IncrRequest(generate_key(hash_key, sort_key), increment,
                          ttl_seconds)
        return self._write([(OP_INCR, req)], ph)[0]

    # ---- multi ops ----------------------------------------------------

    def multi_set(self, hash_key: bytes, kvs, ttl_seconds: int = 0) -> int:
        if not hash_key:
            return int(StorageStatus.INVALID_ARGUMENT)
        items = kvs.items() if isinstance(kvs, dict) else kvs
        req = MultiPutRequest(hash_key,
                              [KeyValue(k, v) for k, v in items],
                              ttl_seconds)
        return self._write([(OP_MULTI_PUT, req)],
                           key_hash_parts(hash_key))[0]

    def multi_get(self, hash_key: bytes,
                  sort_keys: Optional[Sequence[bytes]] = None,
                  consistency=None,
                  **kwargs) -> Tuple[int, Dict[bytes, bytes]]:
        if not hash_key:
            return int(StorageStatus.INVALID_ARGUMENT), {}
        req = MultiGetRequest(hash_key, sort_keys=list(sort_keys or []),
                              **kwargs)
        resp = self._read("multi_get", req, -1, key_hash_parts(hash_key),
                          consistency=consistency)
        return resp.error, {kv.key: kv.value for kv in resp.kvs}

    def multi_del(self, hash_key: bytes, sort_keys: Sequence[bytes]
                  ) -> Tuple[int, int]:
        if not hash_key:
            return int(StorageStatus.INVALID_ARGUMENT), 0
        req = MultiRemoveRequest(hash_key, list(sort_keys))
        return self._write([(OP_MULTI_REMOVE, req)],
                           key_hash_parts(hash_key))[0]

    def multi_get_sortkeys(self, hash_key: bytes
                           ) -> Tuple[int, List[bytes]]:
        """Paginates past the server's one-shot read budget (shared
        paginate_sortkeys driver)."""
        from pegasus_tpu.client.client import paginate_sortkeys

        def fetch(cursor: bytes, inclusive: bool):
            req = MultiGetRequest(hash_key, no_value=True,
                                  start_sortkey=cursor,
                                  start_inclusive=inclusive)
            return self._read("multi_get", req, -1,
                              key_hash_parts(hash_key))

        return paginate_sortkeys(fetch)

    def sortkey_count(self, hash_key: bytes,
                      consistency=None) -> Tuple[int, int]:
        if not hash_key:
            return int(StorageStatus.INVALID_ARGUMENT), 0
        return self._read("sortkey_count", hash_key, -1,
                          key_hash_parts(hash_key),
                          consistency=consistency)

    def batch_get(self, keys: Sequence[Tuple[bytes, bytes]],
                  consistency=None
                  ) -> Tuple[int, List[Tuple[bytes, bytes, bytes]]]:
        return self._traced("client.batch_get", self._batch_get_impl,
                            keys, consistency)

    def _batch_get_impl(self, keys: Sequence[Tuple[bytes, bytes]],
                        consistency=None
                        ) -> Tuple[int, List[Tuple[bytes, bytes, bytes]]]:
        self._ensure_config()
        deadline = self._deadline()
        out: List[Tuple[bytes, bytes, bytes]] = []
        # keys not yet definitively answered; a split racing an attempt
        # bounces only the stale-routed GROUPS (per-key misroute gate on
        # the server), and only those re-resolve under the refreshed
        # count — answered groups keep their results instead of the
        # whole flush replaying
        pending: List[Tuple[bytes, bytes]] = list(keys)
        for attempt in range(self._max_retries):
            if not pending:
                break
            if attempt:
                if self._clock() > deadline:
                    raise PegasusError(ErrorCode.ERR_TIMEOUT,
                                       "batch_get deadline exceeded")
                self.backoff.sleep(attempt)
                try:
                    self.refresh_config(deadline)
                except PegasusError:
                    pass  # meta momentarily down: cached config may
                    # still be right, like _read/_write tolerate
            # regroup under the CURRENT partition count each attempt — a
            # split between attempts changes the stale keys' pidx
            by_pidx: Dict[int, List[Tuple[bytes, bytes]]] = {}
            for hk, sk in pending:
                pidx = key_hash_parts(hk, sk) % self.partition_count
                by_pidx.setdefault(pidx, []).append((hk, sk))
            still: List[Tuple[bytes, bytes]] = []
            for pidx, group in by_pidx.items():
                fks = [FullKey(hk, sk) for hk, sk in group]
                try:
                    resp = self._read("batch_get", BatchGetRequest(fks),
                                      pidx, deadline=deadline,
                                      consistency=consistency)
                except PegasusError as e:
                    if int(e.code) in _RETRYABLE:
                        still.extend(group)
                        continue
                    raise
                if resp.error == int(
                        ErrorCode.ERR_PARENT_PARTITION_MISUSED):
                    still.extend(group)
                    continue
                if resp.error != int(StorageStatus.OK):
                    return resp.error, []
                out.extend((d.hash_key, d.sort_key, d.value)
                           for d in resp.data)
            pending = still
        if pending:
            raise PegasusError(ErrorCode.ERR_TIMEOUT,
                               "batch_get exhausted retries")
        return int(StorageStatus.OK), out

    def check_and_set(self, hash_key: bytes, check_sort_key: bytes,
                      check_type: int, check_operand: bytes,
                      set_sort_key: bytes, set_value: bytes,
                      ttl_seconds: int = 0,
                      return_check_value: bool = False
                      ) -> CheckAndSetResponse:
        if not hash_key:
            resp = CheckAndSetResponse()
            resp.error = int(StorageStatus.INVALID_ARGUMENT)
            return resp
        req = CheckAndSetRequest(
            hash_key, check_sort_key, check_type, check_operand,
            set_diff_sort_key=(set_sort_key != check_sort_key),
            set_sort_key=set_sort_key, set_value=set_value,
            set_expire_ts_seconds=ttl_seconds,
            return_check_value=return_check_value)
        return self._write([(OP_CAS, req)], key_hash_parts(hash_key))[0]

    def check_and_mutate(self, hash_key: bytes, check_sort_key: bytes,
                         check_type: int, check_operand: bytes,
                         mutates: Sequence[Mutate],
                         return_check_value: bool = False
                         ) -> CheckAndMutateResponse:
        if not hash_key:
            resp = CheckAndMutateResponse()
            resp.error = int(StorageStatus.INVALID_ARGUMENT)
            return resp
        req = CheckAndMutateRequest(
            hash_key, check_sort_key, check_type, check_operand,
            mutate_list=list(mutates),
            return_check_value=return_check_value)
        return self._write([(OP_CAM, req)], key_hash_parts(hash_key))[0]

    def scan_multi(self, groups: Dict[int, list], consistency=None):
        """Batched scans for MANY partitions in as few node round-trips
        as possible: partitions group by their serving node, each node
        stacks its partitions' blocks into one device evaluation
        (SURVEY §2.6's partitions-as-batch-dimension model). Returns
        {pidx: [ScanResponse]}. With a non-linearizable `consistency`,
        partitions fan out across secondaries under their read leases;
        a stale-bounced slot re-flies alone to the primary."""
        return self._traced("client.scan_multi", self._scan_multi_impl,
                            groups, consistency)

    def _scan_multi_impl(self, groups: Dict[int, list],
                         consistency=None):
        self._ensure_config()
        cons = self._norm_consistency(consistency)
        out: Dict[int, list] = {}
        force_primary: set = set()  # pidxs bounced ERR_STALE_REPLICA
        need_refresh = False
        deadline = self._deadline()
        for attempt in range(self._max_retries):
            if attempt:
                if self._clock() > deadline:
                    break  # surfaced below as the partitions-missing error
                self.backoff.sleep(attempt)
                if need_refresh:
                    # (stale-replica bounces alone skip this: the
                    # routing table is right, only the replica choice
                    # was — the bounced subset re-flies to the primary)
                    try:
                        self.refresh_config(deadline)
                    except PegasusError:
                        pass  # meta momentarily down: cached config may
                        # still be right, like _read/_write tolerate
            need_refresh = False
            by_node: Dict[str, list] = {}
            for pidx, reqs in groups.items():
                if pidx in out:
                    continue
                node = self._route_read(pidx, cons,
                                        pidx in force_primary)
                if node:
                    by_node.setdefault(node, []).append(
                        ((self.app_id, pidx), reqs))
                else:
                    need_refresh = True  # momentarily unowned
            if not by_node:
                need_refresh = True
                continue  # mid-failover: refresh and retry, like _read
            # send EVERY node's request first, then await — per-attempt
            # latency is the max of node round-trips, not the sum
            rids = []
            for node, node_groups in by_node.items():
                payload = {"groups": node_groups, "auth": self.auth}
                if cons is not None:
                    payload["consistency"] = cons
                    payload["min_decrees"] = [
                        (gp[1], self._session_tokens.get(gp[1], 0))
                        for gp, _reqs in node_groups]
                rids.append(self._send_request(
                    node, "client_scan_multi", payload,
                    deadline=deadline))
            for rid in rids:
                reply = self._await(rid, deadline)
                if reply is None or reply["err"] != _OK:
                    need_refresh = True
                    continue  # retried next attempt for missing pidxs
                for pidx, decree, _role in reply.get("decrees") or []:
                    self._note_decree(pidx, decree)
                for pidx, resps in reply["result"]:
                    if resps and resps[0].error == int(
                            ErrorCode.ERR_ACL_DENY):
                        raise PegasusError(ErrorCode.ERR_ACL_DENY,
                                           "scan_multi")
                    if resps and resps[0].error == _STALE:
                        # only THIS slot re-flies, straight to the
                        # primary — the rest of the flush keeps serving
                        force_primary.add(pidx)
                        continue
                    if resps and resps[0].error == int(
                            ErrorCode.ERR_INVALID_STATE):
                        need_refresh = True
                        continue  # stale primary; re-resolve
                    out[pidx] = resps
            if len(out) == len(groups):
                break
        missing = set(groups) - set(out)
        if missing:
            raise PegasusError(ErrorCode.ERR_TIMEOUT,
                               f"scan_multi: partitions {sorted(missing)} "
                               f"unreachable")
        return out

    @staticmethod
    def _point_result_err(result) -> int:
        """The storage error inside a point-read result (tuple for
        get/ttl, .error for multi_get/batch_get responses)."""
        if isinstance(result, (tuple, list)):
            return result[0]
        return result.error

    def point_read_multi(self, groups: Dict[int, list],
                         consistency=None):
        """Batched point reads (get / ttl / multi_get with sort keys /
        batch_get) for MANY partitions in as few node round-trips as
        possible — the point-read twin of scan_multi: partitions group
        by their primary node, each node serves its whole flush through
        the cross-partition read coordinator. `groups`: {pidx: [(op,
        args, partition_hash)]}. Returns {pidx: [result]} (the caller's
        grouping, original op order) with results byte-identical to the
        solo read ops.

        Ops are re-routed PER ATTEMPT from their partition_hash (like
        _read recomputes `ph % partition_count`), and a
        misrouted-split result coming back in-band
        (ERR_PARENT_PARTITION_MISUSED from the per-op gate) re-resolves
        just that op — matching the solo path's transparent re-resolve
        instead of surfacing the routing error to the application.

        With a non-linearizable `consistency`, each partition's slot
        fans out to one of its secondaries under the read lease; a slot
        bounced ERR_STALE_REPLICA re-flies ONLY its own ops, straight
        to the primary, with no config refresh (the routing table was
        right — only the replica choice was stale)."""
        return self._traced("client.point_read_multi",
                            self._point_read_multi_impl, groups,
                            consistency)

    def _point_read_multi_impl(self, groups: Dict[int, list],
                               consistency=None):
        self._ensure_config()
        cons = self._norm_consistency(consistency)
        items = [(orig_pidx, i, op)
                 for orig_pidx, ops in groups.items()
                 for i, op in enumerate(ops)]
        out: Dict[int, list] = {pidx: [None] * len(ops)
                                for pidx, ops in groups.items()}
        unresolved = set(range(len(items)))
        force_primary: set = set()  # pidxs bounced ERR_STALE_REPLICA
        need_refresh = False
        deadline = self._deadline()
        for attempt in range(self._max_retries):
            if not unresolved:
                break
            if attempt:
                if self._clock() > deadline:
                    break  # surfaced below as partitions-unreachable
                self.backoff.sleep(attempt)
                if need_refresh:
                    # stale-replica bounces alone skip the refresh —
                    # the bounced subset just re-routes to the primary
                    try:
                        self.refresh_config(deadline)
                    except PegasusError:
                        continue  # meta momentarily down; cached config
                        # may still be right on the next pass
            need_refresh = False
            send: Dict[str, Dict[int, list]] = {}
            route: Dict[int, str] = {}  # ONE replica per partition per
            # attempt: splitting a partition's ops across replicas
            # would trade the coalesced batch for extra round-trips
            for idx in sorted(unresolved):
                orig_pidx, _i, op = items[idx]
                ph = op[2] if len(op) > 2 else None
                pidx = (ph % self.partition_count if ph is not None
                        else orig_pidx)
                if pidx not in route:
                    route[pidx] = self._route_read(
                        pidx, cons, pidx in force_primary)
                node = route[pidx]
                if node:
                    send.setdefault(node, {}).setdefault(
                        pidx, []).append((idx, op))
                else:
                    need_refresh = True  # momentarily unowned
            if not send:
                continue  # mid-failover: refresh and retry, like _read
            rids = []
            for node, pmap in send.items():
                payload = {"groups": [((self.app_id, pidx),
                                       [op for _i, op in lst])
                                      for pidx, lst in pmap.items()],
                           "auth": self.auth}
                if cons is not None:
                    payload["consistency"] = cons
                    payload["min_decrees"] = [
                        (pidx, self._session_tokens.get(pidx, 0))
                        for pidx in pmap]
                rids.append((self._send_request(
                    node, "client_read_batch", payload,
                    deadline=deadline), pmap))
            for rid, pmap in rids:
                reply = self._await(rid, deadline)
                if reply is None or reply["err"] != _OK:
                    need_refresh = True
                    continue  # retried next attempt
                for pidx, decree, _role in reply.get("decrees") or []:
                    self._note_decree(pidx, decree)
                for pidx, err, results in reply["result"]:
                    sent = pmap.get(pidx)
                    if sent is None:
                        continue
                    if err == int(ErrorCode.ERR_ACL_DENY):
                        raise PegasusError(ErrorCode.ERR_ACL_DENY,
                                           "point_read_multi")
                    if err == _STALE:
                        # bounced slot: ONLY its ops re-fly, to the
                        # primary, no refresh (subset discipline)
                        force_primary.add(pidx)
                        continue
                    if err in _RETRYABLE:
                        need_refresh = True
                        continue  # stale primary; re-resolve
                    if err != _OK:
                        raise PegasusError(ErrorCode(err),
                                           "point_read_multi")
                    for (idx, _op), result in zip(sent, results):
                        if self._point_result_err(result) == _MISROUTED:
                            # split raced: refresh the (grown) table map
                            # and re-route this op by its hash
                            need_refresh = True
                            continue
                        orig_pidx, i, _o = items[idx]
                        out[orig_pidx][i] = result
                        unresolved.discard(idx)
        if unresolved:
            stuck = sorted({items[i][0] for i in unresolved})
            raise PegasusError(
                ErrorCode.ERR_TIMEOUT,
                f"point_read_multi: partitions {stuck} unreachable")
        return out

    def write_multi(self, groups: Dict[int, list]):
        """Batched writes (set / del / multi_set / multi_del — plus
        atomic ops, which ride alone server-side) for MANY partitions
        in as few node round-trips as possible — the write-side twin of
        point_read_multi: partitions group by their primary node, each
        node replicates its whole flush through per-partition 2PC
        inside one group-commit window. `groups`: {pidx: [(op_code,
        request, partition_hash)]} (op_code/request exactly as the solo
        `_write` sends them). Returns {pidx: [result]} (the caller's
        grouping, original op order) with per-op results identical to
        the solo write handlers.

        Retry machinery mirrors point_read_multi: ops re-route per
        attempt from partition_hash, per-op retryable errors (ERR_BUSY
        overload, per-op deadline fast-fail, split misroute) retry just
        that op. A LOST reply is ambiguous for atomic ops in flight on
        that node (they may have committed) — surfaced as ERR_TIMEOUT
        instead of retried, like the solo path."""
        return self._traced("client.write_multi",
                            self._write_multi_impl, groups)

    def _write_multi_impl(self, groups: Dict[int, list]):
        from pegasus_tpu.replica.mutation import ATOMIC_OPS

        self._ensure_config()
        items = [(orig_pidx, i, op)
                 for orig_pidx, ops in groups.items()
                 for i, op in enumerate(ops)]
        out: Dict[int, list] = {pidx: [None] * len(ops)
                                for pidx, ops in groups.items()}
        unresolved = set(range(len(items)))
        deadline = self._deadline()
        for attempt in range(self._max_retries):
            if not unresolved:
                break
            if attempt:
                if self._clock() > deadline:
                    break  # surfaced below as partitions-unreachable
                self.backoff.sleep(attempt)
                try:
                    self.refresh_config(deadline)
                except PegasusError:
                    continue  # meta momentarily down; cached config may
                    # still be right on the next pass
            send: Dict[str, Dict[int, list]] = {}
            for idx in sorted(unresolved):
                orig_pidx, _i, op = items[idx]
                ph = op[2] if len(op) > 2 else None
                pidx = (ph % self.partition_count if ph is not None
                        else orig_pidx)
                primary = self._primary_of(pidx)
                if primary:
                    send.setdefault(primary, {}).setdefault(
                        pidx, []).append((idx, op))
            if not send:
                continue  # mid-failover: refresh and retry, like _write
            rids = []
            for node, pmap in send.items():
                node_groups = [
                    ((self.app_id, pidx),
                     [([(op[0], op[1])],
                       op[2] if len(op) > 2 else None, deadline)
                      for _i, op in lst])
                    for pidx, lst in pmap.items()]
                rids.append((self._send_request(
                    node, "client_write_batch",
                    {"groups": node_groups, "auth": self.auth},
                    deadline=deadline), pmap))
            for rid, pmap in rids:
                reply = self._await(rid, deadline)
                if reply is None:
                    # ambiguous: the node may have committed some of
                    # the batch. Idempotent ops retry; an atomic op in
                    # flight here must surface the timeout instead
                    for lst in pmap.values():
                        for idx, op in lst:
                            if (idx in unresolved
                                    and op[0] in ATOMIC_OPS):
                                raise PegasusError(
                                    ErrorCode.ERR_TIMEOUT,
                                    "atomic write reply lost")
                    continue
                if reply["err"] != _OK:
                    continue  # retried next attempt
                for pidx, err, item_res in reply["result"]:
                    sent = pmap.get(pidx)
                    if sent is None:
                        continue
                    if err == int(ErrorCode.ERR_ACL_DENY):
                        raise PegasusError(ErrorCode.ERR_ACL_DENY,
                                           "write_multi")
                    if err in _RETRYABLE:
                        continue  # stale primary/splitting; re-resolve
                    if err != _OK:
                        raise PegasusError(ErrorCode(err), "write_multi")
                    for (idx, _op), (op_err, op_results) in zip(
                            sent, item_res):
                        if op_err in _RETRYABLE:
                            # per-op deadline fast-fail / ERR_BUSY shed
                            # / split misroute: nothing ran — safe to
                            # retry even atomic ops
                            continue
                        if op_err != _OK:
                            raise PegasusError(ErrorCode(op_err),
                                               "write_multi")
                        orig_pidx, i, _o = items[idx]
                        out[orig_pidx][i] = op_results[0]
                        unresolved.discard(idx)
        if unresolved:
            stuck = sorted({items[i][0] for i in unresolved})
            raise PegasusError(
                ErrorCode.ERR_TIMEOUT,
                f"write_multi: partitions {stuck} unreachable")
        return out

    def scan_page(self, pidx: int, context_id: int, consistency=None,
                  prefer_node: Optional[str] = None):
        """Continue a server-held scan context (batched-path paging).
        Scan contexts are node-local: a consistency-routed page must
        come back to the replica that opened the context, so callers
        pass `prefer_node` to pin it (a lost pin surfaces as
        SCAN_CONTEXT_ID_NOT_EXIST and the caller restarts)."""
        return self._read("scan", context_id, pidx,
                          consistency=consistency,
                          prefer_node=prefer_node)

    def scan_abort(self, pidx: int, context_id: int, consistency=None,
                   prefer_node: Optional[str] = None) -> None:
        try:
            self._read("clear_scanner", context_id, pidx,
                       consistency=consistency,
                       prefer_node=prefer_node)
        except PegasusError:
            pass

    # ---- scanners ------------------------------------------------------

    def get_scanner(self, hash_key: bytes, start_sortkey: bytes = b"",
                    stop_sortkey: bytes = b"",
                    options: Optional[ScanOptions] = None,
                    consistency=None) -> "ClusterScanner":
        from dataclasses import replace

        from pegasus_tpu.base.key_schema import generate_next_bytes

        if not hash_key:
            raise ValueError("hash key cannot be empty when scan")
        self._ensure_config()
        opts = options or ScanOptions()
        start_key = generate_key(hash_key, start_sortkey)
        if stop_sortkey:
            stop_key = generate_key(hash_key, stop_sortkey)
        else:
            stop_key = generate_next_bytes(hash_key)
            opts = replace(opts, stop_inclusive=False)
        req = self._make_scan_request(start_key, stop_key, opts)
        pidx = key_hash_parts(hash_key) % self.partition_count
        return ClusterScanner(self, [pidx], req,
                              consistency=consistency)

    def get_unordered_scanners(self, max_split_count: int,
                               options: Optional[ScanOptions] = None,
                               consistency=None
                               ) -> List["ClusterScanner"]:
        if max_split_count < 1:
            raise ValueError("max_split_count must be >= 1")
        self._ensure_config()
        opts = options or ScanOptions()
        req = self._make_scan_request(b"", b"", opts, full_scan=True)
        split = min(max_split_count, self.partition_count)
        groups: List[List[int]] = [[] for _ in range(split)]
        for pidx in range(self.partition_count):
            groups[pidx % split].append(pidx)
        return [ClusterScanner(self, g, req, consistency=consistency)
                for g in groups if g]

    @staticmethod
    def _make_scan_request(start_key: bytes, stop_key: bytes,
                           opts: ScanOptions,
                           full_scan: bool = False) -> GetScannerRequest:
        from pegasus_tpu.ops.predicates import FT_NO_FILTER
        from pegasus_tpu.ops.pushdown import PushdownSpec

        pushdown = None
        if opts.value_filter_type != FT_NO_FILTER:
            pushdown = PushdownSpec(
                value_filter_type=opts.value_filter_type,
                value_filter_pattern=opts.value_filter_pattern)
            pushdown.check()
        return GetScannerRequest(
            start_key=start_key, stop_key=stop_key,
            start_inclusive=opts.start_inclusive,
            stop_inclusive=opts.stop_inclusive,
            batch_size=opts.batch_size,
            hash_key_filter_type=opts.hash_key_filter_type,
            hash_key_filter_pattern=opts.hash_key_filter_pattern,
            sort_key_filter_type=opts.sort_key_filter_type,
            sort_key_filter_pattern=opts.sort_key_filter_pattern,
            no_value=opts.no_value,
            return_expire_ts=opts.return_expire_ts,
            only_return_count=opts.only_return_count,
            full_scan=full_scan,
            validate_partition_hash=True,
            pushdown=pushdown)


class ClusterScanner:
    """Pages scan contexts over the cluster read path (parity:
    pegasus_scanner_impl paging via RPC_RRDB_RRDB_SCAN)."""

    def __init__(self, client: ClusterClient, pidxs: List[int],
                 request: GetScannerRequest,
                 consistency=None) -> None:
        self._client = client
        self._pidxs = list(pidxs)
        self._request = request
        self._consistency = client._norm_consistency(consistency)
        # scan contexts are node-local: a follower-read scanner pins
        # the replica that opened each partition's context and pages
        # against it; a lost pin (failover, lease lapse, context
        # expiry) surfaces as SCAN_CONTEXT_ID_NOT_EXIST and the
        # restart re-pins
        self._node: Optional[str] = None
        self._i = 0
        self._context_id: Optional[int] = None
        self._buffer: List[KeyValue] = []
        self._pos = 0
        self._last_key: Optional[bytes] = None
        self.kv_count = 0
        self.shipped_bytes = 0  # wire-size of every response consumed

    def _open(self, req, pidx: int):
        """Open (or reopen) a scan context: pick this partition's
        serving replica under the scanner's consistency level, pin it,
        and issue get_scanner against the pin."""
        self._node = self._client._route_read(pidx, self._consistency)
        return self._client._read("get_scanner", req, pidx,
                                  consistency=self._consistency,
                                  prefer_node=self._node)

    def __iter__(self) -> Iterator[Tuple[bytes, bytes, bytes]]:
        return self

    def __next__(self) -> Tuple[bytes, bytes, bytes]:
        kv = self._next_kv()
        hk, sk = restore_key(kv.key)
        return hk, sk, kv.value

    def next_record(self) -> Tuple[bytes, bytes, bytes, int]:
        """Like next(), plus the record's expire_ts (0 = no TTL);
        meaningful only with GetScannerRequest.return_expire_ts."""
        kv = self._next_kv()
        hk, sk = restore_key(kv.key)
        return hk, sk, kv.value, kv.expire_ts_seconds or 0

    def _next_kv(self):
        while True:
            if self._pos < len(self._buffer):
                kv = self._buffer[self._pos]
                self._pos += 1
                self._last_key = kv.key
                return kv
            if not self._fetch(self._request):
                raise StopIteration

    def _fetch(self, base_req: GetScannerRequest) -> bool:
        from dataclasses import replace

        while self._i < len(self._pidxs):
            pidx = self._pidxs[self._i]
            if self._context_id is None:
                resp = self._open(base_req, pidx)
            else:
                resp = self._client.scan_page(
                    pidx, self._context_id,
                    consistency=self._consistency,
                    prefer_node=self._node)
                if resp.context_id == SCAN_CONTEXT_ID_NOT_EXIST:
                    # context expired server-side (or moved with a
                    # failover / the pinned follower bounced): restart
                    # past the last served key on a fresh pin
                    self._context_id = None
                    restart = base_req
                    if self._last_key is not None:
                        restart = replace(base_req,
                                          start_key=self._last_key + b"\x00",
                                          start_inclusive=True)
                    resp = self._open(restart, pidx)
            if resp.error != int(StorageStatus.OK):
                raise RuntimeError(f"scan failed: error {resp.error}")
            self.shipped_bytes += resp.wire_bytes()
            if resp.kv_count >= 0:
                self.kv_count += resp.kv_count
            buf = resp.kvs
            spec = base_req.pushdown
            vf = spec.value_filter if spec is not None else None
            if vf is not None and not resp.pushdown_applied:
                # pre-pushdown server (or pushdown disabled): spec was
                # ignored, full pages streamed — evaluate locally
                buf = [kv for kv in buf
                       if host_match_filter(kv.value, vf[0], vf[1])]
            self._buffer = buf
            self._pos = 0
            if resp.context_id == SCAN_CONTEXT_ID_COMPLETED:
                self._i += 1
                self._context_id = None
            else:
                self._context_id = resp.context_id
            if self._buffer:
                return True
        return False

    # ---- aggregate pushdown -------------------------------------------

    def count(self) -> int:
        """Matching-row count over this scanner's partitions, evaluated
        server-side where possible — one tiny aggregate partial per
        partition on the wire instead of every row. Respects the
        scanner's value filter; pre-pushdown servers stream rows and the
        count happens here."""
        return self.aggregate("count")

    def aggregate(self, kind: str, k: int = 0, seed: int = 0):
        """Run this scanner's range as ONE aggregate — `count`, `sum`
        (values as u64), `top_k` (by sort key) or `sample` (reservoir) —
        merged across partitions. Independent of the iteration cursor."""
        from dataclasses import replace

        from pegasus_tpu.ops import pushdown as pushdown_ops

        base = self._request.pushdown or pushdown_ops.PushdownSpec()
        spec = replace(base, aggregate=kind, k=int(k), seed=int(seed))
        spec.check()
        req = replace(self._request, pushdown=spec,
                      one_page=False, only_return_count=False)
        parts = [self._aggregate_partition(pidx, req, spec)
                 for pidx in self._pidxs]
        return pushdown_ops.finalize(
            spec, pushdown_ops.merge_partials(spec, parts))

    def _aggregate_partition(self, pidx: int, req, spec):
        from dataclasses import replace

        from pegasus_tpu.ops import pushdown as pushdown_ops

        resp = self._open(req, pidx)
        rows: List[Tuple[bytes, bytes]] = []  # fallback accumulation
        last_key: Optional[bytes] = None
        while True:
            if resp.context_id == SCAN_CONTEXT_ID_NOT_EXIST:
                # context expired server-side (or moved with a failover
                # / split fence bounce). The aggregate partial lives
                # SERVER-side, so the lost context lost every page it
                # folded — restarting from the original start with
                # nothing accumulated client-side cannot double count.
                # The local-fallback path (rows collected here) resumes
                # past the last collected key like a plain scan.
                if rows and last_key is not None:
                    resp = self._open(replace(
                        req, start_key=last_key + b"\x00",
                        start_inclusive=True), pidx)
                else:
                    rows.clear()
                    resp = self._open(req, pidx)
                continue
            if resp.error != int(StorageStatus.OK):
                raise RuntimeError(f"scan failed: error {resp.error}")
            self.shipped_bytes += resp.wire_bytes()
            for kv in resp.kvs:
                rows.append((kv.key, kv.value))
                last_key = kv.key
            if resp.context_id == SCAN_CONTEXT_ID_COMPLETED:
                break
            resp = self._client.scan_page(
                pidx, resp.context_id, consistency=self._consistency,
                prefer_node=self._node)
        if resp.agg is not None:
            return resp.agg
        # pre-pushdown server streamed rows: evaluate the whole spec here
        vf = spec.value_filter
        st = pushdown_ops.AggState(spec)
        for key, value in rows:
            if vf is not None and not host_match_filter(value, vf[0], vf[1]):
                continue
            st.fold_row(key, value)
        return st.to_wire()

    def close(self) -> None:
        if self._context_id is not None and self._i < len(self._pidxs):
            self._client.scan_abort(self._pidxs[self._i],
                                    self._context_id,
                                    consistency=self._consistency,
                                    prefer_node=self._node)
            self._context_id = None
