"""PegasusClient: the user-facing API.

Parity: src/include/pegasus/client.h:42 — set/get/del/exist/ttl,
multi_set/multi_get/multi_get_sortkeys/multi_del, incr, check_and_set,
check_and_mutate, batch_get, sortkey_count, get_scanner (hashkey-scoped)
and get_unordered_scanners (full-table scan fan-out, :1164-1180).

Errors surface as integer status codes matching the server (0 = OK,
1 = NotFound, ...), like the reference's PERR_* mapping.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from pegasus_tpu.base.key_schema import generate_key, restore_key
from pegasus_tpu.client.table import Table
from pegasus_tpu.ops.predicates import FT_NO_FILTER, host_match_filter
from pegasus_tpu.ops.pushdown import PushdownSpec
from pegasus_tpu.ops import pushdown as pushdown_ops
from pegasus_tpu.server.partition_server import PartitionServer
from pegasus_tpu.server.types import (
    BatchGetRequest,
    CheckAndMutateRequest,
    CheckAndMutateResponse,
    CheckAndSetRequest,
    CheckAndSetResponse,
    FullKey,
    GetScannerRequest,
    IncrRequest,
    KeyValue,
    MultiGetRequest,
    MultiPutRequest,
    MultiRemoveRequest,
    Mutate,
    SCAN_CONTEXT_ID_COMPLETED,
    SCAN_CONTEXT_ID_NOT_EXIST,
)
from pegasus_tpu.utils.errors import ErrorCode, StorageStatus

_MISROUTED = int(ErrorCode.ERR_PARENT_PARTITION_MISUSED)


def _err_of(resp) -> int:
    if isinstance(resp, int):
        return resp
    if isinstance(resp, tuple):
        return resp[0]
    return resp.error


def paginate_sortkeys(fetch) -> "Tuple[int, List[bytes]]":
    """Drive `fetch(cursor, inclusive) -> MultiGetResponse` (a no_value
    range multi_get) to exhaustion, paging past the server's one-shot
    read budget. Resumes from the response's resume_sort_key, so even a
    page whose every record was filtered (a long expired run) makes
    progress; if a server provides neither kvs nor a resume point, the
    truncation is reported as INCOMPLETE — never silently as OK. Shared
    by both clients' multi_get_sortkeys."""
    out: List[bytes] = []
    cursor, inclusive = b"", True
    while True:
        resp = fetch(cursor, inclusive)
        out.extend(kv.key for kv in resp.kvs)
        if resp.error != int(StorageStatus.INCOMPLETE):
            return resp.error, sorted(out)
        if resp.resume_sort_key is not None:
            nxt = (resp.resume_sort_key, True)
        elif resp.kvs:
            nxt = (max(kv.key for kv in resp.kvs), False)
        else:
            return int(StorageStatus.INCOMPLETE), sorted(out)
        if nxt == (cursor, inclusive):
            # a server that stops making progress must not spin us
            return int(StorageStatus.INCOMPLETE), sorted(out)
        cursor, inclusive = nxt


def make_hashkey_scan_request(hash_key: bytes, batch_size: int = 1000,
                              validate_partition_hash: bool = True,
                              start_sortkey: bytes = b"",
                              stop_sortkey: bytes = b""):
    """The one place the hashkey-range scan request shape lives (both
    clients' get_scanner and the geo batched path build from here).
    Optional sortkey bounds narrow to [start_sortkey, stop_sortkey)
    within the hashkey (empty stop = to the hashkey's end)."""
    from pegasus_tpu.base.key_schema import generate_next_bytes
    from pegasus_tpu.server.types import GetScannerRequest

    stop_key = (generate_key(hash_key, stop_sortkey) if stop_sortkey
                else generate_next_bytes(hash_key))
    return GetScannerRequest(
        start_key=generate_key(hash_key, start_sortkey),
        stop_key=stop_key,
        stop_inclusive=False, batch_size=batch_size,
        validate_partition_hash=validate_partition_hash)


@dataclass
class ScanOptions:
    """Parity: pegasus_client::scan_options (client.h)."""

    batch_size: int = 100
    start_inclusive: bool = True
    stop_inclusive: bool = False
    hash_key_filter_type: int = FT_NO_FILTER
    hash_key_filter_pattern: bytes = b""
    sort_key_filter_type: int = FT_NO_FILTER
    sort_key_filter_pattern: bytes = b""
    no_value: bool = False
    return_expire_ts: bool = False
    only_return_count: bool = False
    # server-side pushdown: match against the record's USER value bytes
    # (same FT_* match types as the key filters). Old servers ignore the
    # spec; the scanner detects pushdown_applied=False and filters
    # locally, so the option is safe against any server
    value_filter_type: int = FT_NO_FILTER
    value_filter_pattern: bytes = b""


class PegasusScanner:
    """Pages through one or more partitions' scan contexts.

    Parity: pegasus_scanner (client.h:1122) — next() yields
    (hash_key, sort_key, value) until exhausted.
    """

    def __init__(self, partitions: List[PartitionServer],
                 request: GetScannerRequest) -> None:
        self._partitions = list(partitions)
        self._request = request
        self._part_idx = 0
        self._context_id: Optional[int] = None
        self._buffer: List[KeyValue] = []
        self._buf_pos = 0
        self._last_key: Optional[bytes] = None  # for context-loss restart
        self.kv_count = 0  # accumulated when only_return_count
        self.shipped_bytes = 0  # wire-size of every response consumed

    def __iter__(self) -> Iterator[Tuple[bytes, bytes, bytes]]:
        return self

    def __next__(self) -> Tuple[bytes, bytes, bytes]:
        kv = self._next_kv()
        hk, sk = restore_key(kv.key)
        return hk, sk, kv.value

    def next_record(self) -> Tuple[bytes, bytes, bytes, int]:
        """Like next(), plus the record's expire_ts (0 = no TTL).
        Meaningful only when the scan was opened with
        ScanOptions.return_expire_ts."""
        kv = self._next_kv()
        hk, sk = restore_key(kv.key)
        return hk, sk, kv.value, kv.expire_ts_seconds or 0

    def _next_kv(self):
        while True:
            if self._buf_pos < len(self._buffer):
                kv = self._buffer[self._buf_pos]
                self._buf_pos += 1
                self._last_key = kv.key
                return kv
            if not self._fetch_next_batch():
                raise StopIteration

    def _fetch_next_batch(self) -> bool:
        from dataclasses import replace

        while self._part_idx < len(self._partitions):
            server = self._partitions[self._part_idx]
            if self._context_id is None:
                resp = server.on_get_scanner(self._request)
            else:
                resp = server.on_scan(self._context_id)
                if resp.context_id == SCAN_CONTEXT_ID_NOT_EXIST:
                    # server GC'd the context (5-min expiry): restart past
                    # the last served key (parity: pegasus_scanner_impl
                    # reissues get_scanner on context loss)
                    self._context_id = None
                    restart = self._request
                    if self._last_key is not None:
                        restart = replace(self._request,
                                          start_key=self._last_key + b"\x00",
                                          start_inclusive=True)
                    resp = server.on_get_scanner(restart)
            if resp.error != int(StorageStatus.OK):
                raise RuntimeError(f"scan failed: error {resp.error}")
            self.shipped_bytes += resp.wire_bytes()
            if resp.kv_count >= 0:
                self.kv_count += resp.kv_count
            buf = resp.kvs
            spec = self._request.pushdown
            vf = spec.value_filter if spec is not None else None
            if vf is not None and not resp.pushdown_applied:
                # pre-pushdown server (or pushdown disabled): the spec
                # was ignored and full pages streamed — same result,
                # evaluated locally
                buf = [kv for kv in buf
                       if host_match_filter(kv.value, vf[0], vf[1])]
            self._buffer = buf
            self._buf_pos = 0
            if resp.context_id == SCAN_CONTEXT_ID_COMPLETED:
                self._part_idx += 1
                self._context_id = None
            else:
                self._context_id = resp.context_id
            if self._buffer:
                return True
        return False

    # ---- aggregate pushdown -------------------------------------------

    def count(self) -> int:
        """Matching-row count over this scanner's range, evaluated
        server-side where possible (one tiny partial per partition on
        the wire; pre-pushdown servers stream rows and the count happens
        here). Respects the scanner's value filter."""
        return self.aggregate("count")

    def aggregate(self, kind: str, k: int = 0, seed: int = 0):
        """Run this scanner's range as ONE aggregate — `count`,
        `sum` (values as u64), `top_k` (by sort key, k required) or
        `sample` (reservoir, k required) — merged across partitions.
        Consumes the range independently of iteration (does not touch
        the paging cursor)."""
        from dataclasses import replace

        base = self._request.pushdown or PushdownSpec()
        spec = replace(base, aggregate=kind, k=int(k), seed=int(seed))
        spec.check()
        req = replace(self._request, pushdown=spec,
                      one_page=False, only_return_count=False)
        parts = [self._aggregate_partition(server, req, spec)
                 for server in self._partitions]
        return pushdown_ops.finalize(
            spec, pushdown_ops.merge_partials(spec, parts))

    def _aggregate_partition(self, server, req, spec):
        resp = server.on_get_scanner(req)
        rows: List[Tuple[bytes, bytes]] = []  # fallback accumulation
        last_key: Optional[bytes] = None
        while True:
            if resp.context_id == SCAN_CONTEXT_ID_NOT_EXIST:
                # server GC'd the context. In aggregate mode the partial
                # lives SERVER-side, so losing the context lost every
                # page it folded — restart from the original start with
                # nothing accumulated: no double count by construction.
                # The local-fallback path (rows collected here) resumes
                # past the last collected key like a plain scan.
                from dataclasses import replace

                if rows and last_key is not None:
                    resp = server.on_get_scanner(replace(
                        req, start_key=last_key + b"\x00",
                        start_inclusive=True))
                else:
                    rows.clear()
                    resp = server.on_get_scanner(req)
                continue
            if resp.error != int(StorageStatus.OK):
                raise RuntimeError(f"scan failed: error {resp.error}")
            self.shipped_bytes += resp.wire_bytes()
            for kv in resp.kvs:
                rows.append((kv.key, kv.value))
                last_key = kv.key
            if resp.context_id == SCAN_CONTEXT_ID_COMPLETED:
                break
            resp = server.on_scan(resp.context_id)
        if resp.agg is not None:
            return resp.agg
        # pre-pushdown server streamed rows: evaluate the whole spec here
        vf = spec.value_filter
        st = pushdown_ops.AggState(spec)
        for key, value in rows:
            if vf is not None and not host_match_filter(value, vf[0], vf[1]):
                continue
            st.fold_row(key, value)
        return st.to_wire()

    def close(self) -> None:
        if self._context_id is not None and self._part_idx < len(self._partitions):
            self._partitions[self._part_idx].on_clear_scanner(self._context_id)
            self._context_id = None


class PegasusClient:
    def __init__(self, table: Table) -> None:
        self._table = table

    def _dispatch(self, hash_key: bytes, sort_key: bytes, op):
        """Route, dispatch, and re-resolve on a stale-route rejection.

        The server rejects requests whose partition_hash no longer maps to
        it after a split (ERR_PARENT_PARTITION_MISUSED); re-resolving picks
        up the new partition count — parity with partition_resolver's
        config-refresh-on-error loop (partition_resolver_simple.h:56).
        """
        resp = None
        for _ in range(3):
            server, ph = self._table.route(hash_key, sort_key)
            resp = op(server, ph)
            if _err_of(resp) != _MISROUTED:
                return resp
        return resp

    # ---- single-record ops --------------------------------------------

    def set(self, hash_key: bytes, sort_key: bytes, value: bytes,
            ttl_seconds: int = 0) -> int:
        key = generate_key(hash_key, sort_key)
        return self._dispatch(hash_key, sort_key, lambda s, ph: s.on_put(
            key, value, ttl_seconds, partition_hash=ph))

    def get(self, hash_key: bytes, sort_key: bytes) -> Tuple[int, bytes]:
        key = generate_key(hash_key, sort_key)
        return self._dispatch(hash_key, sort_key,
                              lambda s, ph: s.on_get(key, partition_hash=ph))

    def delete(self, hash_key: bytes, sort_key: bytes) -> int:
        key = generate_key(hash_key, sort_key)
        return self._dispatch(hash_key, sort_key, lambda s, ph: s.on_remove(
            key, partition_hash=ph))

    def exist(self, hash_key: bytes, sort_key: bytes) -> bool:
        return self.get(hash_key, sort_key)[0] == int(StorageStatus.OK)

    def ttl(self, hash_key: bytes, sort_key: bytes) -> Tuple[int, int]:
        key = generate_key(hash_key, sort_key)
        return self._dispatch(hash_key, sort_key,
                              lambda s, ph: s.on_ttl(key, partition_hash=ph))

    def incr(self, hash_key: bytes, sort_key: bytes, increment: int,
             ttl_seconds: int = 0):
        req = IncrRequest(generate_key(hash_key, sort_key), increment,
                          ttl_seconds)
        return self._dispatch(hash_key, sort_key, lambda s, ph: s.on_incr(
            req, partition_hash=ph))

    # ---- multi ops ----------------------------------------------------

    def multi_set(self, hash_key: bytes,
                  kvs: Dict[bytes, bytes] | Sequence[Tuple[bytes, bytes]],
                  ttl_seconds: int = 0) -> int:
        if not hash_key:
            # parity: PERR_INVALID_HASH_KEY (pegasus_client_impl.cpp:177) —
            # multi-key records validate by crc64(hash_key); an empty one
            # would be routed and validated inconsistently
            return int(StorageStatus.INVALID_ARGUMENT)
        items = kvs.items() if isinstance(kvs, dict) else kvs
        req = MultiPutRequest(hash_key,
                              [KeyValue(k, v) for k, v in items],
                              ttl_seconds)
        return self._dispatch(hash_key, b"", lambda s, ph: s.on_multi_put(
            req, partition_hash=ph))

    def multi_get(self, hash_key: bytes,
                  sort_keys: Optional[Sequence[bytes]] = None,
                  start_sortkey: bytes = b"", stop_sortkey: bytes = b"",
                  max_kv_count: int = -1, max_kv_size: int = -1,
                  start_inclusive: bool = True, stop_inclusive: bool = False,
                  sort_key_filter_type: int = FT_NO_FILTER,
                  sort_key_filter_pattern: bytes = b"",
                  no_value: bool = False, reverse: bool = False
                  ) -> Tuple[int, Dict[bytes, bytes]]:
        if not hash_key:
            return int(StorageStatus.INVALID_ARGUMENT), {}
        req = MultiGetRequest(
            hash_key, sort_keys=list(sort_keys or []),
            max_kv_count=max_kv_count, max_kv_size=max_kv_size,
            no_value=no_value, start_sortkey=start_sortkey,
            stop_sortkey=stop_sortkey, start_inclusive=start_inclusive,
            stop_inclusive=stop_inclusive,
            sort_key_filter_type=sort_key_filter_type,
            sort_key_filter_pattern=sort_key_filter_pattern, reverse=reverse)
        resp = self._table.resolve(hash_key).on_multi_get(req)
        return resp.error, {kv.key: kv.value for kv in resp.kvs}

    def multi_get_sortkeys(self, hash_key: bytes
                           ) -> Tuple[int, List[bytes]]:
        """All sort keys under a hash key, paginating past the server's
        one-shot read budget (INCOMPLETE pages resume from the server's
        resume_sort_key — without this, large hash keys silently
        truncate)."""

        def fetch(cursor: bytes, inclusive: bool):
            req = MultiGetRequest(hash_key, no_value=True,
                                  start_sortkey=cursor,
                                  start_inclusive=inclusive)
            return self._table.resolve(hash_key).on_multi_get(req)

        return paginate_sortkeys(fetch)

    def multi_del(self, hash_key: bytes, sort_keys: Sequence[bytes]
                  ) -> Tuple[int, int]:
        if not hash_key:
            return int(StorageStatus.INVALID_ARGUMENT), 0
        req = MultiRemoveRequest(hash_key, list(sort_keys))
        return self._dispatch(hash_key, b"", lambda s, ph: s.on_multi_remove(
            req, partition_hash=ph))

    def batch_get(self, keys: Sequence[Tuple[bytes, bytes]]
                  ) -> Tuple[int, List[Tuple[bytes, bytes, bytes]]]:
        """Point-gets across partitions; groups by partition server."""
        by_server: Dict[int, List[FullKey]] = {}
        for hk, sk in keys:
            pidx = self._table.resolve(hk, sk).pidx
            by_server.setdefault(pidx, []).append(FullKey(hk, sk))
        out: List[Tuple[bytes, bytes, bytes]] = []
        for pidx, fks in by_server.items():
            resp = self._table.partitions[pidx].on_batch_get(
                BatchGetRequest(fks))
            if resp.error != int(StorageStatus.OK):
                return resp.error, []
            out.extend((d.hash_key, d.sort_key, d.value) for d in resp.data)
        return int(StorageStatus.OK), out

    def sortkey_count(self, hash_key: bytes) -> Tuple[int, int]:
        if not hash_key:
            return int(StorageStatus.INVALID_ARGUMENT), 0
        return self._table.resolve(hash_key).on_sortkey_count(hash_key)

    def check_and_set(self, hash_key: bytes, check_sort_key: bytes,
                      check_type: int, check_operand: bytes,
                      set_sort_key: bytes, set_value: bytes,
                      ttl_seconds: int = 0,
                      return_check_value: bool = False
                      ) -> CheckAndSetResponse:
        if not hash_key:
            # deviation from the reference (which only rejects oversized
            # hash keys here): with partition-hash validation always on for
            # pow-2 tables, an empty-hashkey cas record could never satisfy
            # the stale-key predicate on its routed partition
            resp = CheckAndSetResponse()
            resp.error = int(StorageStatus.INVALID_ARGUMENT)
            return resp
        req = CheckAndSetRequest(
            hash_key, check_sort_key, check_type, check_operand,
            set_diff_sort_key=(set_sort_key != check_sort_key),
            set_sort_key=set_sort_key, set_value=set_value,
            set_expire_ts_seconds=ttl_seconds,
            return_check_value=return_check_value)
        return self._dispatch(hash_key, b"", lambda s, ph: s.on_check_and_set(
            req, partition_hash=ph))

    def check_and_mutate(self, hash_key: bytes, check_sort_key: bytes,
                         check_type: int, check_operand: bytes,
                         mutates: Sequence[Mutate],
                         return_check_value: bool = False
                         ) -> CheckAndMutateResponse:
        if not hash_key:
            resp = CheckAndMutateResponse()
            resp.error = int(StorageStatus.INVALID_ARGUMENT)
            return resp
        req = CheckAndMutateRequest(
            hash_key, check_sort_key, check_type, check_operand,
            mutate_list=list(mutates),
            return_check_value=return_check_value)
        return self._dispatch(hash_key, b"",
                              lambda s, ph: s.on_check_and_mutate(
                                  req, partition_hash=ph))

    @property
    def partition_count(self) -> int:
        return self._table.partition_count

    def scan_page(self, pidx: int, context_id: int):
        """Continue a server-held scan context (batched-path paging)."""
        return self._table.partitions[pidx].on_scan(context_id)

    def scan_abort(self, pidx: int, context_id: int) -> None:
        self._table.partitions[pidx].on_clear_scanner(context_id)

    def scan_multi(self, groups):
        """Batched scans for many partitions (in-process form): the
        node-level coordinator stacks every partition's blocks into one
        device evaluation — same API shape as the cluster client's."""
        from pegasus_tpu.base.value_schema import epoch_now
        from pegasus_tpu.server.scan_coordinator import scan_multi

        pairs = [(self._table.partitions[pidx], reqs)
                 for pidx, reqs in groups.items()]
        results = scan_multi(pairs, epoch_now())
        return {pidx: resps for (pidx, _reqs), resps
                in zip(groups.items(), results)}

    def point_read_multi(self, groups):
        """Batched point reads for many partitions (in-process form):
        one coordinator flush serves every partition's get / ttl /
        multi_get(sort keys) / batch_get ops — same API shape as the
        cluster client's. `groups`: {pidx: [(op, args,
        partition_hash)]} -> {pidx: [result]}."""
        from pegasus_tpu.server.read_coordinator import point_read_multi

        pairs = [(self._table.partitions[pidx], ops)
                 for pidx, ops in groups.items()]
        results = point_read_multi(pairs)
        return {pidx: res for (pidx, _ops), res
                in zip(groups.items(), results)}

    # ---- scanners -----------------------------------------------------

    def get_scanner(self, hash_key: bytes, start_sortkey: bytes = b"",
                    stop_sortkey: bytes = b"",
                    options: Optional[ScanOptions] = None) -> PegasusScanner:
        """Ordered scan within one hashkey (single partition)."""
        from pegasus_tpu.base.key_schema import generate_next_bytes

        if not hash_key:
            # parity: PERR_INVALID_HASH_KEY — "hash key cannot be empty
            # when scan" (pegasus_client_impl.cpp:1147)
            raise ValueError("hash key cannot be empty when scan")
        opts = options or ScanOptions()
        start_key = generate_key(hash_key, start_sortkey)
        if stop_sortkey:
            stop_key = generate_key(hash_key, stop_sortkey)
        else:
            stop_key = generate_next_bytes(hash_key)
            # stop bound is exclusive of the whole hashkey range; force
            # stop_inclusive off so _after() isn't applied to it
            from dataclasses import replace
            opts = replace(opts, stop_inclusive=False)
        req = self._make_scan_request(start_key, stop_key, opts)
        return PegasusScanner([self._table.resolve(hash_key)], req)

    def get_unordered_scanners(self, max_split_count: int,
                               options: Optional[ScanOptions] = None
                               ) -> List[PegasusScanner]:
        """Full-table scan fan-out (parity: client.h:1164): partitions are
        divided among up to max_split_count scanners the caller can drive
        in parallel."""
        if max_split_count < 1:
            raise ValueError("max_split_count must be >= 1")
        opts = options or ScanOptions()
        partitions = self._table.all_partitions()
        split = min(max_split_count, len(partitions))
        groups: List[List[PartitionServer]] = [[] for _ in range(split)]
        for i, p in enumerate(partitions):
            groups[i % split].append(p)
        req = self._make_scan_request(b"", b"", opts, full_scan=True)
        return [PegasusScanner(g, req) for g in groups if g]

    @staticmethod
    def _make_scan_request(start_key: bytes, stop_key: bytes,
                           opts: ScanOptions,
                           full_scan: bool = False) -> GetScannerRequest:
        pushdown = None
        if opts.value_filter_type != FT_NO_FILTER:
            pushdown = PushdownSpec(
                value_filter_type=opts.value_filter_type,
                value_filter_pattern=opts.value_filter_pattern)
            pushdown.check()
        return GetScannerRequest(
            start_key=start_key, stop_key=stop_key,
            start_inclusive=opts.start_inclusive,
            stop_inclusive=opts.stop_inclusive,
            batch_size=opts.batch_size, no_value=opts.no_value,
            hash_key_filter_type=opts.hash_key_filter_type,
            hash_key_filter_pattern=opts.hash_key_filter_pattern,
            sort_key_filter_type=opts.sort_key_filter_type,
            sort_key_filter_pattern=opts.sort_key_filter_pattern,
            validate_partition_hash=True,
            return_expire_ts=opts.return_expire_ts,
            full_scan=full_scan,
            only_return_count=opts.only_return_count,
            pushdown=pushdown)
