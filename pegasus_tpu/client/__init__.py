"""Client API (reference: src/include/pegasus/client.h, src/client_lib/)."""

from pegasus_tpu.client.table import Table
from pegasus_tpu.client.client import PegasusClient, PegasusScanner, ScanOptions
