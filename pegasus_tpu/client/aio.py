"""Async client facade (parity: the async half of the reference C++
client API, include/pegasus/client.h async_get/async_set/async_multi_get
/... :42-1180, and the twisted-based python client).

A sync client instance is a SERIAL protocol endpoint (one
request/reply pump, one config cache), so the facade runs every call on
one dedicated worker thread guarded by a lock: the asyncio event loop
is never blocked, calls from many tasks interleave safely, and there is
ONE code path for the actual protocol. `gather_*` helpers express the
scatter/join shape of the reference's async API; for true wire-level
parallelism, shard work across several AsyncPegasusClient instances
(each wrapping its own sync client), exactly as the reference scales
with multiple sessions."""

from __future__ import annotations

import asyncio
from concurrent.futures import ThreadPoolExecutor
from typing import Optional, Sequence, Tuple

# the async facade shares the sync client's retryability surface
# VERBATIM — one definition, two exports, so the sync and async stacks
# can never disagree on which typed errors retry or which retries are
# forbidden from burning a config refresh (the tier-1 retryability
# matrix test asserts this identity)
from pegasus_tpu.client.cluster_client import (  # noqa: F401
    DEFAULT_TENANT,
    NO_REFRESH_CODES,
    RETRYABLE_CODES,
    sanitize_tenant,
)


class AsyncPegasusClient:
    """Wraps any sync client (PegasusClient or ClusterClient-backed).

    Robustness rides the wrapped client: end-to-end deadlines and the
    jittered retry backoff run on the worker thread, so awaiting tasks
    see the same typed ERR_TIMEOUT/ERR_BUSY surface as the sync API and
    the event loop never blocks on a backoff sleep."""

    # kwargs forward verbatim, so cluster-backed read ops accept
    # consistency=bounded_stale(...)/MONOTONIC exactly like the sync
    # API (await aio.get(hk, sk, consistency=MONOTONIC))
    _FORWARDED = (
        "set", "get", "delete", "exist", "ttl", "incr",
        "multi_set", "multi_get", "multi_get_sortkeys", "multi_del",
        "batch_get", "sortkey_count", "check_and_set",
        "check_and_mutate", "scan_multi", "scan_page", "scan_abort",
        "point_read_multi", "write_multi",
    )

    def __init__(self, client, max_workers: int = 1,
                 op_timeout_ms: Optional[float] = None,
                 tenant: Optional[str] = None) -> None:
        """`op_timeout_ms`: per-op end-to-end deadline override applied
        to the wrapped client (ClusterClient.op_timeout_ms); None keeps
        the client_op_timeout_ms flag default.

        `tenant`: QoS identity override applied to the wrapped cluster
        client (ClusterClient.tenant) — every op issued through this
        facade is billed to it; None keeps the wrapped client's tag."""
        import threading

        self._c = client
        if tenant is not None:
            if not hasattr(client, "tenant"):
                # mirror the op_timeout_ms guard: only the cluster
                # client carries tenant identity on the wire
                raise TypeError(
                    f"{type(client).__name__} does not support "
                    "tenant tags (a ClusterClient feature)")
            self._c.tenant = sanitize_tenant(tenant)
            self._c._tenant_explicit = True
        if op_timeout_ms is not None:
            if not hasattr(client, "op_timeout_ms"):
                # only the cluster client enforces deadlines; silently
                # setting a dead attribute would leave the caller
                # believing a bound is active when none is
                raise TypeError(
                    f"{type(client).__name__} does not support "
                    "op_timeout_ms (deadlines are a ClusterClient "
                    "feature)")
            self._c.op_timeout_ms = op_timeout_ms
        self._lock = threading.Lock()
        self._pool = ThreadPoolExecutor(
            max_workers=max_workers,
            thread_name_prefix="pegasus-aio")

    def __getattr__(self, name: str):
        if name not in self._FORWARDED:
            raise AttributeError(name)
        fn = getattr(self._c, name)

        async def call(*args, **kwargs):
            loop = asyncio.get_running_loop()

            def locked():
                with self._lock:
                    return fn(*args, **kwargs)

            return await loop.run_in_executor(self._pool, locked)

        return call

    async def gather_get(self, keys: Sequence[Tuple[bytes, bytes]]):
        """Concurrent point gets; returns [(err, value)] in key order."""
        return await asyncio.gather(
            *(self.get(hk, sk) for hk, sk in keys))

    async def gather_set(self, items: Sequence[Tuple[bytes, bytes, bytes]],
                         ttl_seconds: int = 0):
        """Concurrent puts; returns [err] in item order."""
        return await asyncio.gather(
            *(self.set(hk, sk, v, ttl_seconds) for hk, sk, v in items))

    @staticmethod
    def _scan_options(batch_size: int, value_filter: Optional[bytes]):
        from pegasus_tpu.client.client import ScanOptions
        from pegasus_tpu.ops.predicates import FT_MATCH_ANYWHERE

        if value_filter:
            # server-side pushdown: only matching rows cross the wire
            # (old servers stream everything and the scanner filters
            # locally — same rows either way)
            return ScanOptions(batch_size=batch_size,
                               value_filter_type=FT_MATCH_ANYWHERE,
                               value_filter_pattern=value_filter)
        return ScanOptions(batch_size=batch_size)

    async def scan_all(self, hash_key: bytes, batch_size: int = 100,
                       value_filter: Optional[bytes] = None,
                       consistency=None):
        """Drain a hashkey scan without blocking the event loop between
        pages; returns [(hashkey, sortkey, value)]. `value_filter`
        keeps only rows whose value contains the pattern, evaluated
        server-side when the server supports pushdown. `consistency`
        (cluster-backed clients): bounded_stale(...)/MONOTONIC routes
        the pages to lease-holding secondaries — see
        ClusterClient.get_scanner."""
        loop = asyncio.get_running_loop()
        opts = self._scan_options(batch_size, value_filter)

        def scan():
            with self._lock:
                if consistency is not None:
                    scanner = self._c.get_scanner(
                        hash_key, options=opts, consistency=consistency)
                else:
                    scanner = self._c.get_scanner(hash_key, options=opts)
                return list(scanner)

        return await loop.run_in_executor(self._pool, scan)

    async def scan_count(self, hash_key: bytes,
                         value_filter: Optional[bytes] = None) -> int:
        """Count a hashkey's (optionally value-filtered) rows via
        aggregate pushdown: the server replies with one tiny partial
        instead of streaming rows (pre-pushdown servers stream and the
        scanner counts locally)."""
        loop = asyncio.get_running_loop()
        opts = self._scan_options(100, value_filter)

        def count():
            with self._lock:
                return self._c.get_scanner(hash_key, options=opts).count()

        return await loop.run_in_executor(self._pool, count)

    def close(self) -> None:
        self._pool.shutdown(wait=False)
