"""Table: a partitioned rrdb app.

In-process stand-in for the cluster side of the reference's client stack:
the partition resolver maps pegasus_key_hash(key) % partition_count to a
partition (src/client/partition_resolver.cpp:48,
pegasus_client_impl.cpp:124) and dispatches to that partition's primary. Here the "primaries" are local PartitionServer
instances; the RPC/meta layers (resolver cache, config refresh) take over
dispatch in the distributed deployment.
"""

from __future__ import annotations

import os
import shutil
from typing import Dict, List, Optional

from pegasus_tpu.base.key_schema import partition_index
from pegasus_tpu.server.partition_server import PartitionServer


def compact_partitions_parallel(servers, parallel: Optional[int] = None,
                                device=None, **compact_kwargs) -> None:
    """Manually compact many PartitionServers on a small thread pool
    (parity: the manual compact service's
    max_concurrent_running_count).

    parallel defaults to 8 for BOTH placements: on an accelerator each
    partition's eval waits on the link (GIL released) so overlap hides
    round-trips; on the host XLA backend the eval and the disk
    flush/fsync both release the GIL, and overlapping partitions keeps
    cores and the disk queue busy (measured: serial host compaction ran
    3-5x slower than 8-way on two independent environments — the
    round-3 serial heuristic was the single largest bench regression).

    `device` pins workers' jax dispatch: jax.default_device is
    thread-local, so the caller's context does not reach the pool."""
    import contextlib
    from concurrent.futures import ThreadPoolExecutor

    if parallel is None:
        parallel = 8

    def one(srv):
        ctx = contextlib.nullcontext()
        if device is not None:
            import jax

            ctx = jax.default_device(device)
        with ctx:
            srv.manual_compact(**compact_kwargs)

    with ThreadPoolExecutor(max_workers=max(1, parallel)) as ex:
        for f in [ex.submit(one, s) for s in servers]:
            f.result()


class Table:
    def __init__(self, data_dir: str, app_id: int = 1, app_name: str = "temp",
                 partition_count: int = 8, data_version: int = 1) -> None:
        if partition_count < 1:
            raise ValueError("partition_count must be >= 1")
        self.data_dir = data_dir
        self.app_id = app_id
        self.app_name = app_name
        self.partition_count = partition_count
        self.data_version = data_version
        self.partitions: Dict[int, PartitionServer] = {}
        for pidx in range(partition_count):
            self.partitions[pidx] = PartitionServer(
                os.path.join(data_dir, f"{app_id}.{pidx}"),
                app_id=app_id, pidx=pidx, partition_count=partition_count,
                data_version=data_version)

    def resolve(self, hash_key: bytes,
                sort_key: bytes = b"") -> PartitionServer:
        """Route by pegasus_key_hash of the full key (see partition_index):
        single-key ops pass their sort_key; multi-key ops pass b"" —
        matching the reference client's tmp_key construction
        (pegasus_client_impl.cpp:212)."""
        return self.route(hash_key, sort_key)[0]

    def route(self, hash_key: bytes,
              sort_key: bytes = b"") -> "tuple[PartitionServer, int]":
        """(server, partition_hash): the hash is computed once and carried
        with the request — the server validates it against its post-split
        partition_version so a request routed under a stale partition
        count is rejected instead of silently acked (parity: the
        rpc-header partition_hash, rpc_message.h:81-126)."""
        from pegasus_tpu.base.key_schema import key_hash_parts

        h = key_hash_parts(hash_key, sort_key)
        return self.partitions[h % self.partition_count], h

    def all_partitions(self) -> List[PartitionServer]:
        return [self.partitions[i] for i in range(self.partition_count)]

    def flush_all(self) -> None:
        for p in self.all_partitions():
            p.flush()

    def manual_compact_all(self, default_ttl=None, rules_filter=None,
                           parallel: int = 8, device=None) -> None:
        """None defaults defer to each partition's app-envs. Partitions
        overlap via compact_partitions_parallel."""
        compact_partitions_parallel(
            self.all_partitions(), parallel=parallel, device=device,
            default_ttl=default_ttl, rules_filter=rules_filter)

    def update_app_envs(self, envs: dict) -> None:
        """Propagate per-table envs to every partition (parity: meta
        config-sync pushing app-envs to replicas)."""
        for p in self.all_partitions():
            p.update_app_envs(envs)

    def split(self) -> None:
        """In-place 2x partition split (parity: replica/split/
        replica_split_manager.h:58 — each child copies its parent's state,
        the group flips to the doubled partition count, and the stale half
        of every partition is dropped lazily: masked from scans by the
        partition-hash predicate, physically removed at the next manual
        compaction via the same predicate in the compaction filter,
        key_ttl_compaction_filter.h:114-121).

        Known limitation: scanners opened before the split keep their old
        partition groups and may miss records that moved to the children
        mid-drain; the reference's clients detect this via partition-
        version mismatch errors on the wire — re-open scanners after a
        split (the wire layer will carry the same signal here).
        """
        old_count = self.partition_count
        if old_count & (old_count - 1):
            # the stale-half mask predicate is an &-mask: only meaningful
            # for power-of-two counts (reference split counts are pow2 by
            # construction)
            raise ValueError(
                f"partition split requires a power-of-two count, "
                f"have {old_count}")
        new_count = old_count * 2
        created = []
        touched_dirs = []
        # hold EVERY parent's write lock from first checkpoint through the
        # partition-count flip: a write accepted by a parent after its
        # child's checkpoint (routed by the old count) whose hash maps to
        # the child under the new count would be absent from the child and
        # later GC'd from the parent as stale-half data — silent loss. The
        # reference avoids this with a child catch-up from the parent's
        # private log plus a write fence before the flip
        # (replica_split_manager.h:76-123); this offline table-level split
        # fences instead. Locks in pidx order (the only multi-lock site).
        from contextlib import ExitStack
        with ExitStack() as stack:
            for pidx in range(old_count):
                stack.enter_context(self.partitions[pidx]._write_lock)
            try:
                for pidx in range(old_count):
                    parent = self.partitions[pidx]
                    child_pidx = pidx + old_count
                    child_dir = os.path.join(self.data_dir,
                                             f"{self.app_id}.{child_pidx}")
                    # track + clear the dir BEFORE writing anything into
                    # it: a failed earlier attempt must not leave stale
                    # SSTs that a retry would merge with fresh ones
                    touched_dirs.append(child_dir)
                    shutil.rmtree(child_dir, ignore_errors=True)
                    # checkpoint straight into the child's sst dir (no
                    # tempdir double-copy); writes are fenced table-wide
                    parent.engine.checkpoint(os.path.join(child_dir, "sst"))
                    child = PartitionServer(
                        child_dir, app_id=self.app_id, pidx=child_pidx,
                        partition_count=new_count,
                        data_version=self.data_version)
                    created.append((child_pidx, child))
                    if parent.app_envs:
                        child.update_app_envs(dict(parent.app_envs))
            except BaseException:
                # roll back: a half-split table must not leak open children
                # or partially-written child dirs
                for _, child in created:
                    child.close()
                for child_dir in touched_dirs:
                    shutil.rmtree(child_dir, ignore_errors=True)
                raise
            for child_pidx, child in created:
                self.partitions[child_pidx] = child
            for p in self.partitions.values():
                p.update_partition_count(new_count)
            self.partition_count = new_count

    def close(self) -> None:
        for p in self.partitions.values():
            p.close()

    def drop(self) -> None:
        self.close()
        shutil.rmtree(self.data_dir, ignore_errors=True)
