"""Table: a partitioned rrdb app.

In-process stand-in for the cluster side of the reference's client stack:
the partition resolver maps crc64(hashkey) % partition_count to a
partition (src/client/partition_resolver.cpp:48) and dispatches to that
partition's primary. Here the "primaries" are local PartitionServer
instances; the RPC/meta layers (resolver cache, config refresh) take over
dispatch in the distributed deployment.
"""

from __future__ import annotations

import os
import shutil
from typing import Dict, List

from pegasus_tpu.base.key_schema import partition_index
from pegasus_tpu.server.partition_server import PartitionServer


class Table:
    def __init__(self, data_dir: str, app_id: int = 1, app_name: str = "temp",
                 partition_count: int = 8, data_version: int = 1) -> None:
        if partition_count < 1:
            raise ValueError("partition_count must be >= 1")
        self.data_dir = data_dir
        self.app_id = app_id
        self.app_name = app_name
        self.partition_count = partition_count
        self.partitions: Dict[int, PartitionServer] = {}
        for pidx in range(partition_count):
            self.partitions[pidx] = PartitionServer(
                os.path.join(data_dir, f"{app_id}.{pidx}"),
                app_id=app_id, pidx=pidx, partition_count=partition_count,
                data_version=data_version)

    def resolve(self, hash_key: bytes) -> PartitionServer:
        return self.partitions[partition_index(hash_key, self.partition_count)]

    def all_partitions(self) -> List[PartitionServer]:
        return [self.partitions[i] for i in range(self.partition_count)]

    def flush_all(self) -> None:
        for p in self.all_partitions():
            p.flush()

    def manual_compact_all(self, default_ttl=None, rules_filter=None) -> None:
        """None defaults defer to each partition's app-envs."""
        for p in self.all_partitions():
            p.manual_compact(default_ttl=default_ttl, rules_filter=rules_filter)

    def update_app_envs(self, envs: dict) -> None:
        """Propagate per-table envs to every partition (parity: meta
        config-sync pushing app-envs to replicas)."""
        for p in self.all_partitions():
            p.update_app_envs(envs)

    def close(self) -> None:
        for p in self.partitions.values():
            p.close()

    def drop(self) -> None:
        self.close()
        shutil.rmtree(self.data_dir, ignore_errors=True)
