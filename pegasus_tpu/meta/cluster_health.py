"""ClusterHealth: the meta-side view of every node's watchdog.

Each node's HealthEngine ships a compact digest (status + firing rules)
and its new typed events on the EXISTING config-sync report; this
machine folds them into per-node and per-table status
(ok/degraded/critical) plus one bounded cluster-wide event journal —
the `shell health` / `shell timeline` surfaces and the collector's
`_health`/`_alerts` stat rows all read from here.

Flap damping, meta side: a node's cluster-visible status WORSENS
immediately (degradation is urgent) but only IMPROVES after
`IMPROVE_REPORTS` consecutive calmer reports — a node oscillating at a
rule boundary shows one steady degraded state, not a strobe. A node
that stops reporting entirely goes `stale` after `STALE_S` (its last
digest may be arbitrarily old; the failure detector owns dead-node
truth, this just refuses to claim health it cannot see).
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional

from pegasus_tpu.utils.health import worse

IMPROVE_REPORTS = 2
STALE_S = 60.0
JOURNAL_CAP = 1024


class ClusterHealth:
    def __init__(self, meta) -> None:
        self.meta = meta
        # node -> {"status", "firing", "candidate", "candidate_n",
        #          "at", "ring_bytes", "events_total", "dropped"}
        self._nodes: Dict[str, dict] = {}
        self.journal: "deque[dict]" = deque()

    # ---- ingest (config-sync) ------------------------------------------

    def on_report(self, node: str, payload: dict) -> Optional[int]:
        """Fold one node's health block; returns the high-water event
        seq to ack on the reply (None = no health block). Nodes re-ship
        unacked events, so the journal dedupes by seq."""
        health = payload.get("health")
        if not isinstance(health, dict):
            return None
        now = self.meta.clock()
        st = self._nodes.setdefault(node, {
            "status": "ok", "firing": [], "candidate": "ok",
            "candidate_n": 0, "at": now, "ring_bytes": 0,
            "events_total": 0, "dropped": 0, "last_seq": 0})
        reported = health.get("status", "ok")
        # damped fold: worse wins now; better must repeat
        if worse(reported, st["status"]) == reported \
                and reported != st["status"]:
            st["status"] = reported
            st["candidate"], st["candidate_n"] = reported, 0
        elif reported != st["status"]:
            if reported == st["candidate"]:
                st["candidate_n"] += 1
            else:
                st["candidate"], st["candidate_n"] = reported, 1
            if st["candidate_n"] >= IMPROVE_REPORTS:
                st["status"] = reported
                st["candidate_n"] = 0
        else:
            st["candidate"], st["candidate_n"] = reported, 0
        st["firing"] = list(health.get("firing") or [])
        st["at"] = now
        st["ring_bytes"] = int(health.get("ring_bytes") or 0)
        st["events_total"] = int(health.get("events_total") or 0)
        st["dropped"] += int(health.get("dropped") or 0)
        last_seq = st.setdefault("last_seq", 0)
        hw = int(health.get("seq_hw") or 0)
        if hw < last_seq:
            # the node's seq moved backward: its process restarted with
            # a fresh engine — reset the dedupe cursor or every event
            # it fires post-restart would be silently skipped and acked
            last_seq = 0
        for ev in health.get("events") or []:
            seq = int(ev.get("seq") or 0)
            if seq and seq <= last_seq:
                continue  # re-shipped (reply lost): already journaled
            last_seq = max(last_seq, seq)
            self.journal.append(dict(ev, node=node))
        st["last_seq"] = last_seq
        while len(self.journal) > JOURNAL_CAP:
            self.journal.popleft()
        return last_seq

    # ---- derived views --------------------------------------------------

    def _table_status(self, now: float) -> Dict[str, dict]:
        """Per-table fold: a firing rule on a replica entity ("app.pidx")
        or a duplication entity marks that table through its app id.
        Stale nodes are skipped — their frozen firing list must not
        assert table health this meta can no longer see."""
        tables: Dict[str, dict] = {}
        for node, st in self._nodes.items():
            if now - st["at"] > STALE_S:
                continue
            for f in st["firing"]:
                etype, eid = f.get("entity", (None, None))
                app_id = None
                if etype == "replica":
                    app_id = eid.split(".")[0]
                elif etype == "duplication":
                    # node.app.pidx.dupN ids carry the app in slot 2
                    parts = eid.split(".")
                    if len(parts) >= 2:
                        app_id = parts[1]
                if app_id is None:
                    continue
                t = tables.setdefault(app_id, {"status": "ok",
                                               "firing": []})
                t["status"] = worse(t["status"], f.get("severity", "ok"))
                t["firing"].append(dict(f, node=node))
        return tables

    def status(self) -> dict:
        """The `shell health` surface: per-node + per-table status and
        the cluster-wide worst."""
        now = self.meta.clock()
        nodes = {}
        cluster = "ok"
        for node, st in sorted(self._nodes.items()):
            stale = now - st["at"] > STALE_S
            nodes[node] = {
                "status": "stale" if stale else st["status"],
                "firing": st["firing"],
                "ring_bytes": st["ring_bytes"],
                "events_total": st["events_total"],
                "report_age_s": round(now - st["at"], 1),
            }
            if not stale:
                cluster = worse(cluster, st["status"])
        tables = self._table_status(now)
        for t in tables.values():
            cluster = worse(cluster, t["status"])
        return {"cluster": cluster, "nodes": nodes, "tables": tables}

    def events(self, node: Optional[str] = None,
               table: Optional[str] = None,
               since: Optional[float] = None,
               limit: int = 128) -> List[dict]:
        """Cluster journal slice (the `shell timeline` ledger): filter
        by reporting node, by table (replica/duplication entities of
        that app id), and/or by start time."""
        out = []
        for ev in self.journal:
            if node is not None and ev.get("node") != node:
                continue
            if since is not None and ev.get("ts", 0.0) < since:
                continue
            if table is not None:
                etype, eid = ev.get("entity", (None, ""))
                parts = (eid or "").split(".")
                app = (parts[0] if etype == "replica"
                       else parts[1] if etype == "duplication"
                       and len(parts) >= 2 else None)
                if app != str(table):
                    continue
            out.append(ev)
        return out[-limit:]
