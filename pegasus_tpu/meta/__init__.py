"""Meta server: cluster control plane (reference: src/meta/)."""

from pegasus_tpu.meta.meta_storage import MetaStorage
from pegasus_tpu.meta.failure_detector import FailureDetector
from pegasus_tpu.meta.server_state import AppState, PartitionConfig, ServerState
from pegasus_tpu.meta.meta_service import MetaService
