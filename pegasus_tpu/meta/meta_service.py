"""MetaService: leader control plane — DDL, FD, partition guardian.

Parity: src/meta/meta_service.{h,cpp} (admin RPC surface :480-571),
server_state.cpp:1161 (create_app), partition_guardian.h:41 (cures), and
meta_server_failure_detector.h:64 (worker liveness). Single-meta here;
leader election over a distributed lock slots in front of this class the
way the reference elects via ZK (meta_service.cpp:393) — followers
forward to the leader.

Guardian cures mirror the reference's proposal types:
- dead primary  -> promote an alive secondary (ballot+1)
- dead secondary-> remove it (ballot+1)
- under-replicated -> tell the primary to add a learner on a spare node;
  on learn completion, upgrade the learner to secondary (ballot+1).
"""

from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional, Tuple

from pegasus_tpu.meta.failure_detector import FailureDetector
from pegasus_tpu.meta.meta_storage import MetaStorage
from pegasus_tpu.meta.server_state import (
    AS_AVAILABLE,
    AS_DROPPED,
    AppState,
    PartitionConfig,
    ServerState,
)
from pegasus_tpu.utils.errors import ErrorCode, PegasusError

Gpid = Tuple[int, int]


class MetaService:
    def __init__(self, name: str, data_dir: str, net,
                 clock: Callable[[], float],
                 peers: Optional[List[str]] = None) -> None:
        """`peers`: the full meta group (including this node) for
        leader-elected multi-meta deployments; None/singleton = the
        single-meta mode every existing caller gets."""
        from pegasus_tpu.meta.election import (
            MetaElection,
            ReplicatedMetaStorage,
        )

        self.name = name
        self.net = net
        self.clock = clock
        self.storage = ReplicatedMetaStorage(os.path.join(data_dir,
                                                          "meta.json"))
        self.state = ServerState(self.storage)
        self.election = MetaElection(self, list(peers or [name]),
                                     self.storage)
        self.fd = FailureDetector(on_worker_dead=self._on_node_dead)
        # latest stored-replica report per node (config_sync payloads):
        # the `recover` verb rebuilds lost app state from these — the
        # replicas are the recovery source of truth (parity: shell
        # `recover` from replica list, commands.h:209)
        self._stored_reports: Dict[str, list] = {}
        # latest tail-kept slow-trace summary per node (rides the same
        # config_sync report): `shell traces --slow` reads the whole
        # cluster's kept roots with ONE meta admin call
        self._trace_reports: Dict[str, dict] = {}
        # latest per-tenant QoS snapshot per node (same channel): the
        # shell's `tenants` verb and the collector's `_tenants` row
        # read the cluster-folded view with ONE admin call
        self._tenant_reports: Dict[str, dict] = {}
        # latest per-partition workload shape digest (rides the stored
        # entries of config_sync like the CU load signals): `shell
        # workload <table>` folds these per table with ONE admin call
        self._workload_reports: Dict[tuple, dict] = {}
        # in-flight learner adds: gpid -> (learner, started_at); prevents
        # every guardian tick from restarting a slow learn from scratch
        self._pending_learns: Dict[Gpid, Tuple[str, float]] = {}
        self._learn_timeout = 60.0
        self._learn_resend = 9.0  # re-drive lost add-learner cmds
        # balancer copy-secondary moves waiting on a learn: gpid -> node to
        # remove once the learner lands
        self._pending_moves: Dict[Gpid, str] = {}
        # partitions created from a backup that have not restored yet:
        # gpid -> {root, policy, backup_id, src_app_id}. The guardian must
        # not add learners to these (a learner would copy the pre-restore
        # empty state). Persisted so a meta restart keeps driving them.
        self.pending_restores: Dict[Gpid, dict] = {}
        self._load_pending_restores()
        from pegasus_tpu.meta.backup_service import MetaBackupService
        from pegasus_tpu.meta.bulk_load_service import MetaBulkLoadService
        from pegasus_tpu.meta.duplication_service import (
            MetaDuplicationService,
        )

        from pegasus_tpu.meta.elasticity import ElasticityController
        from pegasus_tpu.meta.split_service import MetaSplitService

        self.backup = MetaBackupService(self)
        self.bulk_load = MetaBulkLoadService(self)
        self.duplication = MetaDuplicationService(self)
        self.split = MetaSplitService(self)
        # the detect→decide→act elasticity closed loop (signals flow in
        # through config_sync whatever the level; it ACTS only in lively)
        self.elasticity = ElasticityController(self)
        # cluster-level compaction stagger: heavy-compaction demand
        # reports ride config_sync, leased grants ride the reply
        from pegasus_tpu.meta.compaction_scheduler import (
            CompactionCoordinator,
        )

        self.compaction = CompactionCoordinator(self)
        # cluster flight-recorder fold: every node's watchdog digest +
        # typed health events ride config_sync into this per-node/
        # per-table status machine (`shell health` / `shell timeline`)
        from pegasus_tpu.meta.cluster_health import ClusterHealth

        self.health = ClusterHealth(self)
        # cluster function level (parity: meta_function_level / shell
        # get_meta_level|set_meta_level): "freezed" = no guardian cures
        # or proposals; "steady" = cures but manual balance only
        # (default); "lively" = auto-rebalance on the guardian timer
        self.function_level = self.storage.get("/meta_level") or "steady"
        self._lively_last_balance = 0.0
        self._lively_interval = 30.0
        from pegasus_tpu.utils.command_manager import CommandManager

        self.commands = CommandManager()
        self.commands.register(
            "meta.status",
            lambda _a: {"name": self.name,
                        "leader": self.election.leader,
                        "is_leader": self.election.is_leader,
                        "term": self.election.term,
                        "state_seq": self.storage.seq,
                        "alive_nodes": self.fd.alive_workers()},
            "leadership + state version + live workers")
        net.register(name, self.on_message)

    # ---- multi-meta plumbing ------------------------------------------

    @property
    def is_leader(self) -> bool:
        return self.election.is_leader

    def reload_state(self) -> None:
        """Follower: re-derive in-memory views after replicated storage
        changed underneath (cheap — meta state is small)."""
        self.state = ServerState(self.storage)

    def on_leadership_acquired(self) -> None:
        """Fresh leader: rebuild every service's in-memory view from the
        replicated storage. The FD starts empty — no worker is declared
        dead until its grace expires from MISSING beacons, so a leader
        change never mass-cures healthy partitions."""
        self.reload_state()
        self._load_pending_restores()
        self.backup._load()
        self.bulk_load._load_state()
        self.duplication._load()
        self.split._load()
        self.function_level = self.storage.get("/meta_level") or "steady"

    # ---- messages -----------------------------------------------------

    _LEADER_ONLY = frozenset({
        "beacon", "learn_completed", "replication_error",
        "replica_corrupted", "config_sync",
        "admin", "backup_partition_done", "restore_partition_done",
        "ingest_done", "duplication_sync", "register_child",
        "query_config", "admin_reply",
    })

    def on_message(self, src: str, msg_type: str, payload) -> None:
        if self.election.on_message(src, msg_type, payload):
            return
        if msg_type == "meta_forward":
            # a follower forwarded a request (the wrapper keeps transport
            # routes clean); handle it as if from the original requester —
            # replies travel over OUR route to that requester
            self.on_message(payload["src"], payload["msg_type"],
                            payload["payload"])
            return
        if msg_type == "config_sync" and not self.election.is_leader:
            # stubs broadcast config_sync to the whole group; followers
            # gain nothing from it and forwarding would just triple the
            # leader's work — drop silently
            return
        if msg_type == "beacon":
            # every group member tracks beacons PASSIVELY (parity:
            # multimaster FD) so a freshly elected leader has a warm
            # liveness view — but only the LEADER grants leases (acks):
            # a follower ack would let a worker keep serving while the
            # actual authority considers it dead
            self.fd.on_beacon(payload["node"], self.clock())
            if self.election.is_leader:
                self.net.send(self.name, src, "beacon_ack", {"ok": True})
            return
        if (msg_type in self._LEADER_ONLY
                and self.election.forward_to_leader(src, msg_type,
                                                    payload)):
            return  # forwarded with the ORIGINAL src; reply goes direct
        if msg_type == "learn_completed":
            self._on_learn_completed(tuple(payload["gpid"]),
                                     payload["learner"])
            return
        if msg_type == "replication_error":
            self._on_replication_error(tuple(payload["gpid"]),
                                       payload["member"])
            return
        if msg_type == "replica_corrupted":
            self._on_replica_corrupted(tuple(payload["gpid"]),
                                       payload["node"])
            return
        if msg_type == "config_sync":
            self._on_config_sync(src, payload)
            return
        if msg_type == "admin":
            self._on_admin(src, payload)
            return
        if msg_type == "backup_partition_done":
            self.backup.on_backup_partition_done(payload)
            return
        if msg_type == "restore_partition_done":
            self.backup.on_restore_partition_done(payload)
            return
        if msg_type == "ingest_done":
            self.bulk_load.on_ingest_done(payload)
            return
        if msg_type == "duplication_sync":
            self.duplication.on_duplication_sync(payload)
            return
        if msg_type == "register_child":
            self.split.on_register_child(src, payload)
            return
        if msg_type == "admin_reply":
            # replies to admin verbs THIS meta issued (dup bootstrap
            # asking the follower cluster's meta to restore_app; the
            # failover drill's follower-side flip)
            self.duplication.on_admin_reply(payload)
            self.duplication.on_flip_reply(payload)
            return
        if msg_type == "remote_command":
            rid = payload.get("rid")
            try:
                result = self.commands.call(payload["cmd"],
                                            payload.get("args") or [])
                err = 0
            except (KeyError, ValueError, TypeError) as e:
                result = str(e)
                err = int(ErrorCode.ERR_HANDLER_NOT_FOUND)
            self.net.send(self.name, src, "remote_command_reply", {
                "rid": rid, "err": err, "result": result})
            return
        if msg_type == "query_config":
            # client partition-config resolution (parity: RPC_CM_QUERY_
            # PARTITION_CONFIG_BY_INDEX, the miss path of the client
            # resolver — partition_resolver.h:122)
            rid = payload.get("rid")
            try:
                app_id, count, configs = self.query_config(
                    payload["app_name"])
                app = self.state.find_app(payload["app_name"])
                reply = {
                    "rid": rid, "err": int(ErrorCode.ERR_OK),
                    "app_id": app_id, "partition_count": count,
                    "configs": [{"ballot": pc.ballot, "primary": pc.primary,
                                 "secondaries": list(pc.secondaries)}
                                for pc in configs],
                    # table envs ride the config reply so clients can
                    # adopt table-scoped defaults (qos.default_tenant)
                    # without a second admin round-trip
                    "envs": dict(app.envs) if app is not None else {},
                }
            except PegasusError as e:
                reply = {"rid": rid, "err": int(e.code), "app_id": 0,
                         "partition_count": 0, "configs": []}
            self.net.send(self.name, src, "query_config_reply", reply)
            return
        raise ValueError(f"meta: unknown message {msg_type}")

    def tick(self) -> None:
        """Periodic FD check + guardian pass (parity: the meta's FD check
        timer and partition-guardian scans). Followers only run the
        election timer."""
        self.election.tick()
        if not self.election.is_leader:
            return
        if self.function_level != "freezed":
            # frozen: beacons still refresh leases but nothing is
            # DECLARED dead (fd.check skipped) and no cures run —
            # unfreezing replays missed death declarations on the next
            # tick. Orchestration (backup/bulk-load/dup/split) below
            # keeps ticking either way: fl_freezed stops cure/balance
            # CONFIG actions, not in-flight operational state machines.
            self.fd.check(self.clock())
            self._guardian_pass()
        self.backup.tick()
        self.bulk_load.tick()
        self.duplication.tick()
        self.split.tick()
        if self.function_level != "freezed":
            # steady: signals + metrics only; lively: the controller may
            # also split/move (its own pacing + pressure backoff inside)
            self.elasticity.tick(act=(self.function_level == "lively"))
        if self.function_level == "lively":
            now = self.clock()
            if now - self._lively_last_balance >= self._lively_interval:
                self._lively_last_balance = now
                self.rebalance()

    def http_routes(self) -> dict:
        """The cluster/table info REST surface (parity:
        meta/meta_http_service.h): /meta/apps, /meta/app?name=,
        /meta/nodes, /meta/status."""

        def apps(_q):
            return [{"app_id": a.app_id, "app_name": a.app_name,
                     "partition_count": a.partition_count,
                     "replica_count": a.max_replica_count,
                     "envs": dict(a.envs)} for a in self.list_apps()]

        def app(q):
            app_id, count, configs = self.query_config(q["name"])
            return {"app_id": app_id, "partition_count": count,
                    "partitions": [{"pidx": i, "ballot": pc.ballot,
                                    "primary": pc.primary,
                                    "secondaries": list(pc.secondaries)}
                                   for i, pc in enumerate(configs)]}

        def nodes(_q):
            return {"alive": self.fd.alive_workers()}

        def status(_q):
            return {"name": self.name, "leader": self.election.leader,
                    "is_leader": self.election.is_leader,
                    "term": self.election.term,
                    "state_seq": self.storage.seq}

        return {"/meta/apps": apps, "/meta/app": app,
                "/meta/nodes": nodes, "/meta/status": status}

    # ---- restore bookkeeping ------------------------------------------

    def _load_pending_restores(self) -> None:
        raw = self.state._storage.get("/restore/pending") or []
        self.pending_restores = {tuple(e["gpid"]): e["info"] for e in raw}

    def persist_pending_restores(self) -> None:
        self.state._storage.set_batch({"/restore/pending": [
            {"gpid": list(gpid), "info": info}
            for gpid, info in self.pending_restores.items()]})

    def _on_admin(self, src: str, payload: dict) -> None:
        """Networked DDL/admin surface (parity: the meta admin RPC table,
        meta_service.cpp:480-571 — create/drop/recall app, envs, balancer
        — invoked by shell/admin clients over the wire)."""
        rid = payload.get("rid")
        cmd = payload.get("cmd")
        args = payload.get("args") or {}
        try:
            if cmd == "create_app":
                result = self.create_app(
                    args["app_name"], args["partition_count"],
                    args.get("replica_count", 3), args.get("envs"))
            elif cmd == "drop_app":
                result = self.drop_app(args["app_name"])
            elif cmd == "recall_app":
                result = self.recall_app(args["app_name"])
            elif cmd == "list_apps":
                result = [{"app_id": a.app_id, "app_name": a.app_name,
                           "partition_count": a.partition_count,
                           "envs": dict(a.envs),
                           "replica_count": a.max_replica_count}
                          for a in self.list_apps()]
            elif cmd == "update_app_envs":
                result = self.update_app_envs(args["app_name"],
                                              args["envs"])
            elif cmd == "rebalance":
                result = len(self.rebalance())
            elif cmd == "drain_node":
                result = self.drain_node(args["node"])
            elif cmd == "list_nodes":
                result = self.fd.alive_workers()
            elif cmd == "start_backup":
                result = self.backup.start_backup(
                    args["app_name"], args["root"],
                    args.get("policy", "manual"))
            elif cmd == "backup_status":
                result = self.backup.backup_status(args["backup_id"])
            elif cmd == "add_backup_policy":
                result = self.backup.add_policy(
                    args["name"], args["app_names"], args["root"],
                    args.get("interval_seconds", 86400),
                    args.get("backup_history_count", 3))
            elif cmd == "restore_app":
                result = self.backup.create_app_from_backup(
                    args["new_name"], args["root"],
                    args.get("policy", "manual"), args["backup_id"],
                    args.get("replica_count", 3))
            elif cmd == "start_bulk_load":
                result = self.bulk_load.start_bulk_load(
                    args["app_name"], args["root"], args.get("src_app"))
            elif cmd == "bulk_load_status":
                result = self.bulk_load.bulk_load_status(args["app_name"])
            elif cmd == "add_dup":
                result = self.duplication.add_duplication(
                    args["app_name"], args["follower_meta"],
                    args["follower_app"])
            elif cmd == "query_dup":
                result = self.duplication.query_duplication(
                    args["app_name"])
            elif cmd == "remove_dup":
                result = self.duplication.remove_duplication(
                    args["dupid"])
            elif cmd == "start_partition_split":
                result = self.split.start_partition_split(
                    args["app_name"])
            elif cmd == "split_status":
                result = self.split.split_status(args["app_name"])
            elif cmd == "hot_partitions":
                result = self.elasticity.status(
                    args.get("app_name", ""))
            elif cmd == "compact_sched":
                result = self.compaction.status()
            elif cmd == "cluster_health":
                # the `shell health` surface: damped per-node/per-table
                # status + firing rules off the config-sync digests
                result = self.health.status()
            elif cmd == "health_events":
                result = self.health.events(
                    node=args.get("node"), table=args.get("table"),
                    since=args.get("since"),
                    limit=int(args.get("limit", 128)))
            elif cmd == "partition_primary":
                # routing-hash -> hosting primary (one meta call: the
                # shell's wire-mode `explain` routes straight to the
                # serving node instead of probing the fleet)
                app = self.state.find_app(args["app_name"])
                if app is None:
                    raise PegasusError(ErrorCode.ERR_APP_NOT_EXIST,
                                       args["app_name"])
                pidx = (int(args.get("partition_hash") or 0)
                        % app.partition_count)
                pc_ = self.state.get_partition(app.app_id, pidx)
                result = {"app_id": app.app_id, "pidx": pidx,
                          "primary": pc_.primary}
            elif cmd == "workload":
                # the `shell workload <table>` surface: per-partition
                # shape digests (off the config-sync stored entries)
                # folded into one table rollup
                result = self.workload_status(args.get("app_name", ""))
            elif cmd == "slow_traces":
                # per-node tail-kept trace roots, newest last (the
                # `shell traces --slow` surface; full spans fan out on
                # demand via the trace-dump remote command)
                result = {n: dict(t) for n, t in
                          sorted(self._trace_reports.items())}
            elif cmd == "del_app_envs":
                result = self.del_app_envs(args["app_name"], args["keys"])
            elif cmd == "clear_app_envs":
                result = self.clear_app_envs(args["app_name"],
                                             args.get("prefix", ""))
            elif cmd == "rename_app":
                result = self.rename_app(args["old_name"],
                                         args["new_name"])
            elif cmd == "get_meta_level":
                result = self.function_level
            elif cmd == "set_meta_level":
                result = self.set_meta_level(args["level"])
            elif cmd == "get_replica_count":
                app = self.state.find_app(args["app_name"])
                if app is None:
                    raise PegasusError(ErrorCode.ERR_APP_NOT_EXIST,
                                       args["app_name"])
                result = app.max_replica_count
            elif cmd == "set_replica_count":
                result = self.set_app_replica_count(args["app_name"],
                                                    args["count"])
            elif cmd == "cluster_info":
                result = self.cluster_info()
            elif cmd == "ddd_diagnose":
                result = self.ddd_diagnose()
            elif cmd == "recover":
                result = self.recover_from_reports()
            elif cmd == "list_dups":
                result = self.duplication.list_all()
            elif cmd == "dup_stats":
                result = self.duplication.dup_stats(
                    args.get("app_name", ""))
            elif cmd == "tenant_stats":
                result = self.tenant_stats()
            elif cmd == "dup_failover":
                result = self.duplication.start_failover(
                    args["app_name"])
            elif cmd == "dup_failover_status":
                result = self.duplication.failover_status(
                    args["app_name"])
            elif cmd == "query_restore_status":
                result = self.query_restore_status(
                    args.get("app_name", ""))
            elif cmd == "propose":
                result = self.propose(args["app_name"], args["pidx"],
                                      args["action"], args["node"],
                                      force=bool(args.get("force")))
            elif cmd == "ls_backup_policy":
                result = self.backup.list_policies()
            elif cmd == "query_backup_policy":
                result = self.backup.query_policy(args["name"])
            elif cmd == "modify_backup_policy":
                result = self.backup.modify_policy(
                    args["name"], add_apps=args.get("add_apps"),
                    remove_apps=args.get("remove_apps"),
                    interval_seconds=args.get("interval_seconds"),
                    backup_history_count=args.get("backup_history_count"))
            elif cmd == "enable_backup_policy":
                result = self.backup.enable_policy(args["name"], True)
            elif cmd == "disable_backup_policy":
                result = self.backup.enable_policy(args["name"], False)
            elif cmd == "pause_dup":
                result = self.duplication.pause_duplication(args["dupid"])
            elif cmd == "start_dup":
                result = self.duplication.resume_duplication(args["dupid"])
            elif cmd == "set_dup_fail_mode":
                result = self.duplication.set_fail_mode(args["dupid"],
                                                        args["fail_mode"])
            elif cmd == "pause_bulk_load":
                result = self.bulk_load.pause_bulk_load(args["app_name"])
            elif cmd == "restart_bulk_load":
                result = self.bulk_load.restart_bulk_load(
                    args["app_name"])
            elif cmd == "cancel_bulk_load":
                result = self.bulk_load.cancel_bulk_load(args["app_name"])
            elif cmd == "clear_bulk_load":
                result = self.bulk_load.clear_bulk_load(args["app_name"])
            else:
                self.net.send(self.name, src, "admin_reply", {
                    "rid": rid,
                    "err": int(ErrorCode.ERR_HANDLER_NOT_FOUND),
                    "result": None})
                return
        except PegasusError as e:
            self.net.send(self.name, src, "admin_reply", {
                "rid": rid, "err": int(e.code), "result": str(e)})
            return
        except (KeyError, TypeError, ValueError) as e:
            # malformed request: reply immediately instead of letting the
            # client burn its full timeout waiting for nothing
            self.net.send(self.name, src, "admin_reply", {
                "rid": rid, "err": int(ErrorCode.ERR_INVALID_PARAMETERS),
                "result": f"bad admin args: {e}"})
            return
        except OSError as e:
            # e.g. a wrong bucket path handed to start_bulk_load/restore
            self.net.send(self.name, src, "admin_reply", {
                "rid": rid,
                "err": int(ErrorCode.ERR_FILE_OPERATION_FAILED),
                "result": str(e)})
            return
        self.net.send(self.name, src, "admin_reply", {
            "rid": rid, "err": int(ErrorCode.ERR_OK), "result": result})

    def _on_config_sync(self, src: str, payload: dict) -> None:
        """Pull-reconciliation (parity: on_query_configuration_by_node,
        meta_service.cpp:793 + meta_admin.thrift:103-115): reply with the
        node's authoritative partition configs and the stored replicas it
        should delete. GC is deliberately conservative: only replicas of
        apps that no longer exist anywhere (fully gone, not in the
        dropped-recall window) are listed — a replica missing from its
        partition's member list may be an in-flight learner."""
        node = payload["node"]
        self._stored_reports[node] = list(payload.get("stored", []))
        if "trace_report" in payload:
            self._trace_reports[node] = payload["trace_report"]
        if "tenants" in payload:
            self._tenant_reports[node] = {"at": self.clock(),
                                          "tenants": payload["tenants"]}
        # per-partition workload digests (primaries stamp them onto
        # their stored entries, exactly like the CU load signals);
        # digests of apps meta no longer knows AT ALL are pruned each
        # report — without this, per-job temp-table churn grows the map
        # forever (dropped-but-recallable apps keep their profile)
        for entry in payload.get("stored", []):
            wl = entry.get("workload")
            if wl is not None:
                self._workload_reports[tuple(entry["gpid"])] = dict(
                    wl, node=node, at=self.clock())
        if self._workload_reports:
            self._workload_reports = {
                g: w for g, w in self._workload_reports.items()
                if g[0] in self.state.apps}
        # elasticity detect phase: the same report carries per-partition
        # capacity units + hotkey results and the node's pressure counts
        self.elasticity.on_report(node, payload)
        # duplication health: per-dup lag/shipping entries feeding the
        # dup_stats surface and the failover drill's drain evidence
        self.duplication.on_report(node, payload)
        # watchdog digest + typed events -> the ClusterHealth machine;
        # the reply acks the journaled event seq so the node can stop
        # re-shipping those events
        health_ack = self.health.on_report(node, payload)
        # compaction stagger: demand in, leased grant out (None = the
        # node reported no compaction block — say nothing)
        compact_grant = self.compaction.on_report(node, payload)
        # recovery adoption: a replica holding a HIGHER ballot than our
        # state knows (e.g. updates lost across a leader change) is the
        # truth — adopt its view before answering
        for entry in payload.get("stored", []):
            gpid = tuple(entry["gpid"])
            if gpid[0] not in self.state.apps or "primary" not in entry:
                continue
            pc = self.state.get_partition(*gpid)
            if entry["ballot"] > pc.ballot:
                self.state.update_partition(gpid[0], gpid[1], PartitionConfig(
                    ballot=entry["ballot"], primary=entry["primary"],
                    secondaries=list(entry["secondaries"])))
        configs = []
        for app in self.list_apps():
            for pidx in range(app.partition_count):
                pc = self.state.get_partition(app.app_id, pidx)
                if node in pc.members():
                    configs.append({
                        "gpid": (app.app_id, pidx), "ballot": pc.ballot,
                        "primary": pc.primary,
                        "secondaries": list(pc.secondaries),
                        "partition_count": app.partition_count,
                        "envs": dict(app.envs),
                    })
        gc = []
        # freezed level suspends GC entirely: an operator recovering a
        # meta that lost its state sets freezed FIRST, so replicas of
        # apps this meta does not know yet are never deleted before
        # `recover` can adopt them
        if self.function_level != "freezed":
            for entry in payload.get("stored", []):
                app_id = tuple(entry["gpid"])[0]
                # dropped apps stay in state (recall window) — only
                # replicas of apps unknown to meta entirely are garbage
                if app_id not in self.state.apps:
                    gc.append(tuple(entry["gpid"]))
        reply = {"configs": configs, "gc": gc}
        if compact_grant is not None:
            reply["compact_grant"] = compact_grant
        if health_ack is not None:
            reply["health_ack"] = health_ack
        self.net.send(self.name, src, "config_sync_reply", reply)

    def tenant_stats(self) -> dict:
        """Cluster-folded per-tenant QoS view from the config-sync
        tenant blocks. Counters fold by MAX, not sum: in-process sim
        stubs share ONE process-global registry, so every node reports
        the identical snapshot and a sum would multiply by node count
        (same dedupe rule as the collector's workload fold); deployed,
        max reports the worst node — the honest aggregate for an SLO
        check. The burn ratio keeps the worst node's value; brownout
        is true if ANY node holds the gate (the aggressor is shed
        wherever it lands)."""
        tenants: Dict[str, dict] = {}
        for node, rep in sorted(self._tenant_reports.items()):
            for name, st in (rep.get("tenants") or {}).items():
                agg = tenants.setdefault(name, {
                    "weight": st.get("weight"),
                    "cu_budget": st.get("cu_budget"),
                    "cu_total": 0, "cu_ratio": 0.0,
                    "shed": 0, "overbudget": 0,
                    "browned": False, "nodes": 0})
                agg["cu_total"] = max(agg["cu_total"],
                                      int(st.get("cu_total") or 0))
                agg["cu_ratio"] = max(agg["cu_ratio"],
                                      float(st.get("cu_ratio") or 0.0))
                agg["shed"] = max(agg["shed"],
                                  int(st.get("shed") or 0))
                agg["overbudget"] = max(agg["overbudget"],
                                        int(st.get("overbudget") or 0))
                agg["browned"] = agg["browned"] or bool(st.get("browned"))
                agg["nodes"] += 1
        return {"tenants": tenants,
                "nodes_reporting": len(self._tenant_reports)}

    def workload_status(self, app_name: str = "") -> dict:
        """Per-table workload shape rollup from the config-sync
        digests: partition rows + one folded table row (counts sum,
        percentile-ish stats take the worst partition)."""
        from pegasus_tpu.server.workload import fold_summaries

        apps = {}
        for app in self.list_apps():
            if app_name and app.app_name != app_name:
                continue
            apps[app.app_id] = app.app_name
        out: dict = {}
        for gpid, wl in sorted(self._workload_reports.items()):
            name = apps.get(gpid[0])
            if name is None:
                continue
            tbl = out.setdefault(name, {"partitions": []})
            tbl["partitions"].append(dict(wl, gpid=list(gpid)))
        for name, tbl in out.items():
            tbl["table"] = fold_summaries(tbl["partitions"])
        return out

    # ---- DDL surface (parity: meta_service.cpp:480-571) ---------------

    def create_app(self, app_name: str, partition_count: int,
                   replica_count: int = 3,
                   envs: Optional[Dict[str, str]] = None,
                   restore_from: Optional[dict] = None) -> int:
        if self.state.find_app(app_name) is not None:
            raise PegasusError(ErrorCode.ERR_APP_EXIST, app_name)
        nodes = self.fd.alive_workers()
        if not nodes:
            raise PegasusError(ErrorCode.ERR_NOT_ENOUGH_MEMBER,
                               "no alive replica servers")
        # the DESIRED replica count is preserved even when fewer nodes are
        # alive now — the guardian restores the level as nodes return
        # (placement clamps, the app state doesn't)
        app = AppState(self.state.next_app_id(), app_name, partition_count,
                       AS_AVAILABLE, dict(envs or {}), replica_count)
        # restore-from-backup starts primary-only: secondaries join later
        # via LT_APP learning of the RESTORED state (guardian is held off
        # until the primary's download completes)
        placed = 1 if restore_from else min(replica_count, len(nodes))
        configs = []
        for pidx in range(partition_count):
            members = [nodes[(pidx + i) % len(nodes)]
                       for i in range(placed)]
            configs.append(PartitionConfig(
                ballot=1, primary=members[0], secondaries=members[1:]))
        self.state.put_app(app, configs)
        if restore_from:
            for pidx in range(partition_count):
                self.pending_restores[(app.app_id, pidx)] = dict(
                    restore_from)
            self.persist_pending_restores()
        for pidx, pc in enumerate(configs):
            self._propose(app.app_id, pidx, pc)
        if app.envs:
            self._propagate_envs(app)
        if restore_from:
            self.backup.drive_restores()
        return app.app_id

    def drop_app(self, app_name: str) -> None:
        app = self.state.find_app(app_name)
        if app is None:
            raise PegasusError(ErrorCode.ERR_APP_NOT_EXIST, app_name)
        app.status = AS_DROPPED
        self.state.put_app(app)
        for pidx in range(app.partition_count):
            pc = self.state.get_partition(app.app_id, pidx)
            old_members = pc.members()
            dead_pc = PartitionConfig(ballot=pc.ballot + 1, primary="",
                                      secondaries=[])
            self.state.update_partition(app.app_id, pidx, dead_pc)
            for node in old_members:
                self._send_proposal(node, app, pidx, dead_pc)

    def recall_app(self, app_name: str) -> int:
        """Parity: recall_app — resurrect a dropped table inside the recall
        window (data dirs still on the nodes)."""
        if self.state.find_app(app_name) is not None:
            # the name is back in use by a live table — recalling would
            # create two AVAILABLE apps with one name (reference rejects)
            raise PegasusError(ErrorCode.ERR_APP_EXIST, app_name)
        app = self.state.find_dropped_app(app_name)
        if app is None:
            raise PegasusError(ErrorCode.ERR_APP_NOT_EXIST, app_name)
        if not self.fd.alive_workers():
            raise PegasusError(ErrorCode.ERR_NOT_ENOUGH_MEMBER,
                               "no alive replica servers to recall onto")
        app.status = AS_AVAILABLE
        self.state.put_app(app)
        for pidx in range(app.partition_count):
            pc = self.state.get_partition(app.app_id, pidx)
            # reuse the last known membership before the drop is gone;
            # fall back to fresh placement
            members = [n for n in pc.members() if self.fd.is_alive(n)]
            if not members:
                nodes = self.fd.alive_workers()
                members = [nodes[(pidx + i) % len(nodes)]
                           for i in range(min(app.max_replica_count,
                                              len(nodes)))]
            new_pc = PartitionConfig(ballot=pc.ballot + 1,
                                     primary=members[0],
                                     secondaries=members[1:])
            self.state.update_partition(app.app_id, pidx, new_pc)
            self._propose(app.app_id, pidx, new_pc)
        return app.app_id

    def list_apps(self) -> List[AppState]:
        return [a for a in self.state.apps.values()
                if a.status == AS_AVAILABLE]

    def query_config(self, app_name: str
                     ) -> Tuple[int, int, List[PartitionConfig]]:
        """Parity: query_cfg (idl/rrdb.thrift:366) — (app_id,
        partition_count, configs)."""
        app = self.state.find_app(app_name)
        if app is None:
            raise PegasusError(ErrorCode.ERR_APP_NOT_EXIST, app_name)
        return app.app_id, app.partition_count, [
            self.state.get_partition(app.app_id, pidx)
            for pidx in range(app.partition_count)]

    def update_app_envs(self, app_name: str, envs: Dict[str, str]) -> None:
        app = self.state.find_app(app_name)
        if app is None:
            raise PegasusError(ErrorCode.ERR_APP_NOT_EXIST, app_name)
        app.envs.update(envs)
        self.state.put_app(app)
        self._propagate_envs(app)

    def del_app_envs(self, app_name: str, keys: List[str]) -> int:
        """Parity: shell del_app_envs — drop named per-table envs; the
        full (reduced) set re-propagates so nodes converge on removal."""
        app = self.state.find_app(app_name)
        if app is None:
            raise PegasusError(ErrorCode.ERR_APP_NOT_EXIST, app_name)
        removed = 0
        for k in keys:
            removed += app.envs.pop(k, None) is not None
        self.state.put_app(app)
        self._propagate_envs(app)
        return removed

    def clear_app_envs(self, app_name: str,
                       prefix: str = "") -> int:
        """Parity: shell clear_app_envs [-p prefix]."""
        app = self.state.find_app(app_name)
        if app is None:
            raise PegasusError(ErrorCode.ERR_APP_NOT_EXIST, app_name)
        victims = [k for k in app.envs if k.startswith(prefix)]
        for k in victims:
            del app.envs[k]
        self.state.put_app(app)
        self._propagate_envs(app)
        return len(victims)

    def rename_app(self, old_name: str, new_name: str) -> None:
        """Parity: shell rename (RPC_CM_RENAME_APP). Routing is by
        app_id, so a rename is pure metadata — clients resolving the new
        name pick up the same partitions on their next config query."""
        if self.state.find_app(new_name) is not None:
            raise PegasusError(ErrorCode.ERR_INVALID_PARAMETERS,
                               f"{new_name} already exists")
        app = self.state.find_app(old_name)
        if app is None:
            raise PegasusError(ErrorCode.ERR_APP_NOT_EXIST, old_name)
        app.app_name = new_name
        self.state.put_app(app)
        # backup policies cover tables BY NAME — follow the rename or
        # the table silently drops out of its backup schedule
        self.backup.on_app_renamed(old_name, new_name)

    def set_meta_level(self, level: str) -> str:
        """Parity: shell set_meta_level (RPC_CM_CONTROL_META).
        freezed|steady|lively — see function_level in __init__."""
        if level not in ("freezed", "steady", "lively"):
            raise PegasusError(ErrorCode.ERR_INVALID_PARAMETERS, level)
        self.function_level = level
        self.storage.set("/meta_level", level)
        return level

    def set_app_replica_count(self, app_name: str, count: int) -> int:
        """Parity: shell set_replica_count (online max_replica_count
        update, RPC_CM_SET_MAX_REPLICA_COUNT). The guardian converges
        membership: add-learner cures grow under-replicated partitions;
        the over-replication shed path drains extras one per tick."""
        if count < 1:
            raise PegasusError(ErrorCode.ERR_INVALID_PARAMETERS,
                               str(count))
        app = self.state.find_app(app_name)
        if app is None:
            raise PegasusError(ErrorCode.ERR_APP_NOT_EXIST, app_name)
        app.max_replica_count = count
        self.state.put_app(app)
        return count

    def cluster_info(self) -> dict:
        """Parity: shell cluster_info."""
        apps = self.list_apps()
        return {
            "meta": self.name,
            "meta_leader": self.election.leader,
            "term": self.election.term,
            "meta_level": self.function_level,
            "alive_nodes": self.fd.alive_workers(),
            "app_count": len(apps),
            "partition_count": sum(a.partition_count for a in apps),
            "state_seq": self.storage.seq,
        }

    def query_restore_status(self, app_name: str = "") -> List[dict]:
        """Restore progress per pending partition (parity: shell
        query_restore_status): which partitions of a
        created-from-backup app are still downloading their
        checkpoint."""
        want_id = None
        if app_name:
            app = self.state.find_app(app_name)
            if app is None:
                raise PegasusError(ErrorCode.ERR_APP_NOT_EXIST, app_name)
            want_id = app.app_id
        out = []
        for gpid, info in sorted(self.pending_restores.items()):
            if want_id is not None and gpid[0] != want_id:
                continue
            out.append({"gpid": list(gpid), "status": "restoring",
                        **{k: info[k] for k in ("policy", "backup_id")
                           if k in info}})
        return out

    def recover_from_reports(self) -> dict:
        """Rebuild app state for replicas this meta does not know
        (parity: shell `recover` from replica list, commands.h:209 —
        used after total meta-state loss). For each unknown app_id in
        the nodes' config-sync reports, recreate the app (named
        recovered_<id>; rename_app afterwards) adopting each partition's
        HIGHEST-ballot reported config. Run under `freezed` level so
        config-sync GC cannot delete the orphans first."""
        by_app: Dict[int, Dict[int, dict]] = {}
        for _node, stored in self._stored_reports.items():
            for entry in stored:
                gpid = tuple(entry["gpid"])
                if gpid[0] in self.state.apps or "ballot" not in entry:
                    continue
                cur = by_app.setdefault(gpid[0], {}).get(gpid[1])
                if cur is None or entry["ballot"] > cur["ballot"]:
                    by_app[gpid[0]][gpid[1]] = entry
        created = []
        for app_id in sorted(by_app):
            parts = by_app[app_id]
            partition_count = max(
                int(e.get("partition_count") or 0)
                for e in parts.values()) or (max(parts) + 1)
            app = AppState(app_id, f"recovered_{app_id}",
                           partition_count, AS_AVAILABLE, {}, 3)
            configs = []
            for pidx in range(partition_count):
                e = parts.get(pidx)
                if e is None:
                    # no survivor reported this partition: leave it
                    # empty for ddd_diagnose / propose to resolve
                    configs.append(PartitionConfig(ballot=0, primary="",
                                                   secondaries=[]))
                else:
                    configs.append(PartitionConfig(
                        ballot=e["ballot"], primary=e.get("primary", ""),
                        secondaries=list(e.get("secondaries") or [])))
            self.state.put_app(app, configs)
            created.append({"app_id": app_id, "app_name": app.app_name,
                            "partition_count": partition_count,
                            "recovered_partitions": len(parts)})
        return {"created": created,
                "nodes_reporting": sorted(self._stored_reports)}

    def ddd_diagnose(self) -> List[dict]:
        """Parity: shell ddd_diagnose (DDD = 'double-dead diagnosis',
        partition_guardian's on_ddd): partitions with no live primary —
        the guardian cannot cure them without operator action (a member
        returning, or a `propose` forcing a primary)."""
        out = []
        for app in self.list_apps():
            for pidx in range(app.partition_count):
                pc = self.state.get_partition(app.app_id, pidx)
                dead_primary = bool(pc.primary) and not self.fd.is_alive(
                    pc.primary)
                if pc.primary and not dead_primary:
                    continue
                out.append({
                    "gpid": [app.app_id, pidx],
                    "app_name": app.app_name,
                    "ballot": pc.ballot,
                    "last_primary": pc.primary,
                    "secondaries": list(pc.secondaries),
                    "alive_members": [m for m in pc.members()
                                      if self.fd.is_alive(m)],
                })
        return out

    def propose(self, app_name: str, pidx: int, action: str,
                node: str, force: bool = False) -> None:
        """Parity: shell propose — a manual config proposal
        (ASSIGN_PRIMARY / ADD_SECONDARY / DOWNGRADE_TO_INACTIVE) for
        operator-driven recovery of partitions the guardian won't touch.

        assign_primary requires `node` to be alive and (unless `force`)
        already a member holding the partition's data — promoting a
        non-member opens an EMPTY replica there and serves empty reads.
        `force=True` is the operator's explicit data-loss acknowledgment
        for unrecoverable partitions."""
        app = self.state.find_app(app_name)
        if app is None:
            raise PegasusError(ErrorCode.ERR_APP_NOT_EXIST, app_name)
        if not 0 <= pidx < app.partition_count:
            raise PegasusError(ErrorCode.ERR_INVALID_PARAMETERS,
                               f"pidx {pidx}")
        gpid = (app.app_id, pidx)
        pc = self.state.get_partition(app.app_id, pidx)
        if action in ("assign_primary", "add_secondary"):
            if not self.fd.is_alive(node):
                raise PegasusError(ErrorCode.ERR_INVALID_PARAMETERS,
                                   f"{node} is not alive")
        if action == "assign_primary":
            if pc.primary == node:
                return
            # a revived ex-member is out of pc.members() (its death was
            # reconciled away) but still HOLDS the data on disk — its
            # config-sync stored-replica report proves it. That is the
            # DDD-recovery case propose exists for (parity: shell
            # `propose`/`recover`, commands.h:209-211); only a node with
            # neither membership nor stored data needs `force`.
            holds_data = any(
                tuple(e["gpid"]) == gpid
                for e in self._stored_reports.get(node, []))
            if node not in pc.members() and not holds_data and not force:
                raise PegasusError(
                    ErrorCode.ERR_INVALID_PARAMETERS,
                    f"{node} holds no replica of {app_name}.{pidx} — "
                    "pass force=true to accept an empty primary")
            # keep the old primary only if it is alive — appending a
            # dead node would park it in the config forever (its death
            # event already fired and will not fire again)
            keep_old = (pc.primary and pc.primary != node
                        and self.fd.is_alive(pc.primary))
            new_pc = PartitionConfig(
                ballot=pc.ballot + 1, primary=node,
                secondaries=[s for s in pc.secondaries if s != node] +
                            ([pc.primary] if keep_old else []))
        elif action == "add_secondary":
            if node in pc.members():
                return
            if not pc.primary:
                raise PegasusError(ErrorCode.ERR_INVALID_STATE,
                                   "no primary to learn from")
            self._pending_learns[gpid] = (node, self.clock())
            self.net.send(self.name, pc.primary, "add_learner_cmd", {
                "gpid": gpid, "learner": node})
            return
        elif action == "downgrade":
            if node not in pc.secondaries:
                raise PegasusError(ErrorCode.ERR_INVALID_PARAMETERS,
                                   f"{node} is not a secondary")
            new_pc = PartitionConfig(
                ballot=pc.ballot + 1, primary=pc.primary,
                secondaries=[s for s in pc.secondaries if s != node])
        else:
            raise PegasusError(ErrorCode.ERR_INVALID_PARAMETERS, action)
        self.state.update_partition(app.app_id, pidx, new_pc)
        self._propose(app.app_id, pidx, new_pc)
        if action == "downgrade":
            self._send_proposal(node, app, pidx, new_pc)

    # ---- guardian (parity: partition_guardian.h:41) -------------------

    def _on_node_dead(self, node: str) -> None:
        for app in self.list_apps():
            for pidx in range(app.partition_count):
                pc = self.state.get_partition(app.app_id, pidx)
                if node not in pc.members():
                    continue
                if pc.primary == node:
                    alive_secs = [s for s in pc.secondaries
                                  if self.fd.is_alive(s)]
                    if not alive_secs:
                        continue  # DDD: wait for a node to return
                    new_pc = PartitionConfig(
                        ballot=pc.ballot + 1, primary=alive_secs[0],
                        secondaries=alive_secs[1:])
                else:
                    new_pc = PartitionConfig(
                        ballot=pc.ballot + 1, primary=pc.primary,
                        secondaries=[s for s in pc.secondaries if s != node])
                self.state.update_partition(app.app_id, pidx, new_pc)
                self._propose(app.app_id, pidx, new_pc)

    def _on_replication_error(self, gpid: Gpid, member: str) -> None:
        """A member NAK'd replication (e.g. gap after a lost prepare):
        remove it; the guardian pass re-adds it as a learner."""
        app = self.state.apps.get(gpid[0])
        if app is None or app.status != AS_AVAILABLE:
            return
        pc = self.state.get_partition(*gpid)
        if member == pc.primary or member not in pc.members():
            return
        new_pc = PartitionConfig(
            ballot=pc.ballot + 1, primary=pc.primary,
            secondaries=[s for s in pc.secondaries if s != member])
        self.state.update_partition(gpid[0], gpid[1], new_pc)
        self._propose(gpid[0], gpid[1], new_pc)
        # the removed node must deactivate too
        self._send_proposal(member, app, gpid[1], new_pc)

    def _on_replica_corrupted(self, gpid: Gpid, node: str) -> None:
        """A replica self-quarantined over storage corruption (block
        crc / index failure / disk IO error). The cure is removal +
        re-learn: a corrupt SECONDARY leaves the membership (ballot+1)
        and the guardian pass tops the partition back up with a fresh
        learner built from a healthy peer; a corrupt PRIMARY demotes —
        an alive secondary is promoted in the same config change (the
        client's retry + config refresh lands on it) and the sick node
        drops out. The quarantined node already trashed its store, so
        when the guardian picks it as the learn target it rebuilds from
        clean bytes, never from the corrupt ones."""
        app = self.state.apps.get(gpid[0])
        if app is None or app.status != AS_AVAILABLE:
            return
        # PR 5 quarantine firing mid-split: a corrupt REGISTERED child
        # must be unregistered (its single replica just trashed its
        # store) so the split re-spawns it from the parent — the normal
        # demote/remove cure below cannot repair a one-replica child
        if self.split.on_replica_corrupted(gpid, src_node=node):
            return
        pc = self.state.get_partition(*gpid)
        # a pending learn targeting the quarantined node is dead; clear
        # it BEFORE the membership check — a corrupt LEARNER is not in
        # members() (it was never upgraded), and leaving the entry
        # would stall the repair learn for the full learn timeout
        pending = self._pending_learns.get(gpid)
        if pending is not None and pending[0] == node:
            self._pending_learns.pop(gpid, None)
            self._pending_moves.pop(gpid, None)
        if node not in pc.members():
            return  # corrupt learner / duplicate report: nothing to cure
        if node == pc.primary:
            alive = [s for s in pc.secondaries if self.fd.is_alive(s)]
            if not alive:
                # no healthy member to promote: leave the config for
                # ddd_diagnose / an operator `propose` — promoting
                # nothing beats promoting nothing-with-data-loss
                return
            new_pc = PartitionConfig(ballot=pc.ballot + 1,
                                     primary=alive[0],
                                     secondaries=alive[1:])
        else:
            new_pc = PartitionConfig(
                ballot=pc.ballot + 1, primary=pc.primary,
                secondaries=[s for s in pc.secondaries if s != node])
        self.state.update_partition(gpid[0], gpid[1], new_pc)
        self._propose(gpid[0], gpid[1], new_pc)

    def _guardian_pass(self) -> None:
        """Re-replicate under-replicated partitions onto spare nodes."""
        now = self.clock()
        for app in self.list_apps():
            for pidx in range(app.partition_count):
                gpid = (app.app_id, pidx)
                if gpid in self.pending_restores:
                    continue  # no learners until the restore lands
                pc = self.state.get_partition(app.app_id, pidx)
                if not pc.primary:
                    continue
                pending = self._pending_learns.get(gpid)
                if len(pc.members()) >= app.max_replica_count:
                    # a pending learn on a FULL partition is a balancer
                    # copy-secondary move: keep its guard alive until the
                    # learner lands, dies, or times out (dropping it early
                    # would let a second move start and over-replicate)
                    if pending is not None:
                        learner, started = pending[0], pending[1]
                        if (learner in pc.members()
                                or now - started >= self._learn_timeout
                                or not self.fd.is_alive(learner)):
                            self._pending_learns.pop(gpid, None)
                            if learner not in pc.members():
                                # the move failed: forget the planned
                                # removal or a later unrelated learn would
                                # strip a healthy secondary
                                self._pending_moves.pop(gpid, None)
                    elif (len(pc.members()) > app.max_replica_count
                            and pc.secondaries):
                        # over-replicated (set_replica_count lowered the
                        # target): shed one secondary per pass — gradual,
                        # like the guardian's one-cure-per-tick style.
                        # Prefer shedding a dead one.
                        victim = next((s for s in pc.secondaries
                                       if not self.fd.is_alive(s)),
                                      pc.secondaries[-1])
                        new_pc = PartitionConfig(
                            ballot=pc.ballot + 1, primary=pc.primary,
                            secondaries=[s for s in pc.secondaries
                                         if s != victim])
                        self.state.update_partition(app.app_id, pidx,
                                                    new_pc)
                        self._propose(app.app_id, pidx, new_pc)
                        self._send_proposal(victim, app, pidx, new_pc)
                    continue
                if pending is not None:
                    learner, started = pending[0], pending[1]
                    last_sent = pending[2] if len(pending) > 2 else started
                    if (now - started < self._learn_timeout
                            and self.fd.is_alive(learner)):
                        # learn in flight: re-send the command at a slow
                        # cadence — the one-shot cmd (or its learn RPCs)
                        # may have been LOST in a partition/storm, and
                        # without a re-drive the cure stalls a full
                        # learn_timeout. The primary's add_learner and
                        # the learner's learn_request are idempotent.
                        if now - last_sent >= self._learn_resend:
                            self._pending_learns[gpid] = (learner,
                                                          started, now)
                            self.net.send(self.name, pc.primary,
                                          "add_learner_cmd",
                                          {"gpid": gpid,
                                           "learner": learner})
                        continue
                    self._pending_moves.pop(gpid, None)  # stale move, if any
                spare = [n for n in self.fd.alive_workers()
                         if n not in pc.members()]
                if not spare:
                    continue
                learner = spare[(app.app_id + pidx) % len(spare)]
                self._pending_learns[gpid] = (learner, now)
                self.net.send(self.name, pc.primary, "add_learner_cmd", {
                    "gpid": gpid, "learner": learner})

    def _on_learn_completed(self, gpid: Gpid, learner: str) -> None:
        app = self.state.apps.get(gpid[0])
        if app is None or app.status != AS_AVAILABLE:
            return
        self._pending_learns.pop(gpid, None)
        pc = self.state.get_partition(*gpid)
        if learner in pc.members():
            return
        secondaries = pc.secondaries + [learner]
        # a balancer copy-secondary move completes here: the source node
        # leaves in the same config update its TARGET learner joins in
        # (a different learner completing — e.g. a guardian heal — must
        # not trigger the removal)
        leaving = None
        move = self._pending_moves.get(gpid)
        if move is not None and move[0] == learner:
            leaving = move[1]
            del self._pending_moves[gpid]
        if leaving is not None and leaving in secondaries:
            secondaries = [s for s in secondaries if s != leaving]
        new_pc = PartitionConfig(ballot=pc.ballot + 1, primary=pc.primary,
                                 secondaries=secondaries)
        self.state.update_partition(gpid[0], gpid[1], new_pc)
        self._propose(gpid[0], gpid[1], new_pc)
        if leaving is not None and leaving not in new_pc.members():
            self._send_proposal(leaving, app, gpid[1], new_pc)
        # the newcomer needs the table's envs too (it wasn't a member when
        # they were last propagated)
        if app.envs:
            self.net.send(self.name, learner, "update_app_envs", {
                "app_id": app.app_id, "envs": dict(app.envs)})

    # ---- balancer (parity: meta_service rebalance RPC ->
    # greedy_load_balancer proposals) -----------------------------------

    def rebalance(self) -> List:
        """Compute and apply balance proposals (parity:
        RPC_CM_START_BALANCER -> server_load_balancer::rebalance).
        Primary moves apply immediately (zero-copy config change);
        secondary copies start a targeted learner flow and complete when
        the learn lands. Returns the proposals applied/started."""
        from pegasus_tpu.meta.balancer import propose_app_balanced_moves

        nodes = self.fd.alive_workers()
        configs = {}
        for app in self.list_apps():
            if app.app_id in self.split._splits:
                # an in-flight split owns this app's configuration: a
                # balancer move racing the child registration / count
                # flip could relocate a fenced parent or start a learn
                # the flip invalidates — skip until the split lands
                # (start_partition_split refuses the mirror race)
                continue
            for pidx in range(app.partition_count):
                configs[(app.app_id, pidx)] = self.state.get_partition(
                    app.app_id, pidx)
        proposals = propose_app_balanced_moves(configs, nodes)
        self.elasticity._proposal_count.increment(len(proposals))
        for prop in proposals:
            app = self.state.apps[prop.gpid[0]]
            pc = self.state.get_partition(*prop.gpid)
            if prop.kind == "move_primary":
                if prop.to_node not in pc.secondaries:
                    continue  # config changed since proposal generation
                self._move_primary(prop.gpid, prop.to_node)
            else:  # copy_secondary via the learner flow
                if prop.gpid in self._pending_learns:
                    continue
                self._pending_moves[prop.gpid] = (prop.to_node,
                                                  prop.from_node)
                self._pending_learns[prop.gpid] = (prop.to_node,
                                                   self.clock())
                self.net.send(self.name, pc.primary, "add_learner_cmd", {
                    "gpid": prop.gpid, "learner": prop.to_node})
        return proposals

    def drain_node(self, node: str) -> int:
        """Move every primary OFF `node` (graceful offline — parity:
        admin_tools/pegasus_offline_node.sh's migrate-primaries step).
        Each affected partition promotes one remaining secondary via a
        zero-copy config change; the drained node stays a secondary so
        the operator can stop it without a read-availability dip and
        let the guardian re-replicate afterwards. Returns the number of
        primaries moved; partitions with no other member are skipped
        (dropping their primary would lose the partition)."""
        moved = 0
        for app in self.list_apps():
            for pidx in range(app.partition_count):
                pc = self.state.get_partition(app.app_id, pidx)
                if pc is None or pc.primary != node:
                    continue
                # only hand leadership to a LIVE secondary — in the
                # beacon-timeout window a dead one still sits in the
                # config and promoting it would black out the partition
                live = [s for s in pc.secondaries
                        if self.fd.is_alive(s)]
                if not live:
                    continue
                self._move_primary((app.app_id, pidx), live[0])
                moved += 1
        return moved

    def _move_primary(self, gpid, target: str) -> None:
        """Zero-copy leadership move: the target secondary becomes
        primary at ballot+1 and the old primary stays as a secondary
        (shared by the balancer's move_primary and drain_node)."""
        pc = self.state.get_partition(*gpid)
        new_pc = PartitionConfig(
            ballot=pc.ballot + 1, primary=target,
            secondaries=[s for s in pc.secondaries
                         if s != target] + [pc.primary])
        self.state.update_partition(gpid[0], gpid[1], new_pc)
        self._propose(gpid[0], gpid[1], new_pc)

    # ---- proposal delivery --------------------------------------------

    def _propose(self, app_id: int, pidx: int, pc: PartitionConfig) -> None:
        app = self.state.apps[app_id]
        for node in pc.members():
            self._send_proposal(node, app, pidx, pc)

    def _send_proposal(self, node: str, app: AppState, pidx: int,
                       pc: PartitionConfig) -> None:
        self.net.send(self.name, node, "config_proposal", {
            "gpid": (app.app_id, pidx), "ballot": pc.ballot,
            "primary": pc.primary, "secondaries": list(pc.secondaries),
            "partition_count": app.partition_count,
            # a partition created from a backup must not serve until its
            # restore lands — the replica gates clients on this flag
            "restoring": (app.app_id, pidx) in self.pending_restores,
            # a split parent whose child registered stays write-fenced on
            # whoever holds primaryship until the count flip
            "splitting": self.split.is_parent_fenced(app.app_id, pidx)})

    def _propagate_envs(self, app: AppState) -> None:
        nodes = set()
        for pidx in range(app.partition_count):
            nodes.update(self.state.get_partition(app.app_id,
                                                  pidx).members())
        for node in nodes:
            self.net.send(self.name, node, "update_app_envs", {
                "app_id": app.app_id, "envs": dict(app.envs)})
