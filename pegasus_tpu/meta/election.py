"""Meta group: lease-based leader election + replicated meta storage.

Parity: the reference elects its meta leader through a distributed lock
and keeps cluster state in a replicated store (meta_service.cpp:384-401
elect via ZK lock; meta_state_service_zookeeper.h:50), with followers
forwarding every request to the leader (check_leader,
meta_service.h:304). Without an external ZooKeeper, the meta GROUP
provides both itself:

- Election: term-numbered vote rounds. A follower whose leader lease
  expires becomes a candidate, increments its term, and asks every peer
  for a vote; a peer grants iff the term is new AND the candidate's
  storage sequence is at least its own (the up-to-date gate). A majority
  of the full group elects. The leader heartbeats {term, seq}; any
  message with a newer term demotes.
- Storage replication: every leader-side storage mutation gets a
  sequence number and fans out to followers, which apply it to their
  local stores. A follower that detects a gap (heartbeat seq ahead of
  its own) pulls a full snapshot — meta state is small, so snapshot
  catch-up beats log reconciliation in complexity. The vote gate then
  guarantees the next leader has the most complete state among any
  electing majority.

Window semantics: an update acked to a client but not yet replicated
when the leader dies can be lost (the reference accepts the analogous
window only because ZK persists first). The cluster self-heals: replica
config-sync reports carry ballots, and the new leader adopts any
reported config whose ballot is ahead of its own state — the replicas
are the recovery source of truth (parity: `recover` from replica list,
shell commands.h:209).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from pegasus_tpu.meta.meta_storage import MetaStorage

LEASE_SECONDS = 8.0
HEARTBEAT_EVERY = 2.0


class ReplicatedMetaStorage(MetaStorage):
    """MetaStorage that notifies a replication hook on every mutation.
    The hook fires ONLY for locally-originated writes (the leader's);
    follower-applied updates go through `apply_replicated`."""

    def __init__(self, path: str) -> None:
        super().__init__(path)
        self.seq = int(self._tree.get("/__meta_seq", 0))
        # the TERM whose leader wrote the latest mutation: freshness is
        # (state_term, seq) lexicographic, so a deposed leader that kept
        # writing (inflating seq under its OLD term) can never outrank
        # state written under a newer term
        self.state_term = int(self._tree.get("/__meta_term", 0))
        self.term_source: Callable[[], int] = lambda: 0
        self.on_mutate: Optional[Callable[[Dict[str, Any]], None]] = None

    @property
    def version(self):
        return (self.state_term, self.seq)

    def _bump(self, updates: Dict[str, Any]) -> Dict[str, Any]:
        self.seq += 1
        self.state_term = self.term_source()
        updates = dict(updates)
        updates["/__meta_seq"] = self.seq
        updates["/__meta_term"] = self.state_term
        return updates

    def set(self, node: str, value: Any) -> None:
        self.set_batch({node: value})

    def set_batch(self, updates: Dict[str, Any]) -> None:
        updates = self._bump(updates)
        super().set_batch(updates)
        if self.on_mutate is not None:
            self.on_mutate(updates)

    def delete(self, node: str) -> None:
        # deletions replicate as explicit tombstone lists inside a batch
        keys = [k for k in self._tree
                if k == node or k.startswith(node + "/")]
        for k in keys:
            self._tree.pop(k, None)
        self.seq += 1
        self.state_term = self.term_source()
        self._tree["/__meta_seq"] = self.seq
        self._tree["/__meta_term"] = self.state_term
        self._persist()
        if self.on_mutate is not None:
            self.on_mutate({"/__meta_seq": self.seq,
                            "/__meta_term": self.state_term,
                            "/__tombstones": keys})

    def apply_replicated(self, seq: int, updates: Dict[str, Any]) -> None:
        """Follower-side apply (no re-replication). Caller has already
        gap-checked seq."""
        tombs = updates.pop("/__tombstones", None)
        if tombs:
            for k in tombs:
                self._tree.pop(k, None)
            updates = {k: v for k, v in updates.items() if v is not None}
        self._tree.update(updates)
        self.seq = max(self.seq, seq)
        self.state_term = int(updates.get("/__meta_term",
                                          self.state_term))
        self._tree["/__meta_seq"] = self.seq
        self._persist()

    def snapshot(self) -> Dict[str, Any]:
        return dict(self._tree)

    def load_snapshot(self, tree: Dict[str, Any]) -> None:
        self._tree = dict(tree)
        self.seq = int(self._tree.get("/__meta_seq", 0))
        self.state_term = int(self._tree.get("/__meta_term", 0))
        self._persist()


class MetaElection:
    """Election + replication sidecar for one MetaService instance."""

    def __init__(self, meta, peers: List[str],
                 storage: ReplicatedMetaStorage) -> None:
        self.meta = meta
        self.peers = [p for p in peers if p != meta.name]
        self.group = sorted(set(peers) | {meta.name})
        self.storage = storage
        self.term = 0
        self.voted_term = 0
        self.is_leader = len(self.peers) == 0  # single-meta: always lead
        self._peer_contact: Dict[str, float] = {}
        self._prevotes: Optional[set] = None
        self.leader: Optional[str] = meta.name if self.is_leader else None
        # boot counts as a heartbeat: with -inf every member would
        # campaign on its FIRST tick simultaneously and split the vote;
        # the staggered delays only order timers measured from a common
        # reference point
        self._last_heartbeat = meta.clock()
        self._last_sent_hb = float("-inf")
        self._votes: set = set()
        # staggered election timeouts break split-vote livelock the way
        # Raft's randomized timeouts do, but DETERMINISTICALLY (the sim
        # must replay from its seed). The per-index stagger must exceed
        # the slowest tick interval (SimCluster ticks each 3s) or two
        # timers cross within one tick and split the vote; 2 heartbeats
        # (4s) clears it, so the lowest-indexed live member campaigns
        # alone and wins before the next member's timer fires
        self._election_delay = (LEASE_SECONDS
                                + self.group.index(meta.name)
                                * 2 * HEARTBEAT_EVERY)
        storage.term_source = lambda: self.term
        storage.on_mutate = self._replicate

    # ---- leader-side ---------------------------------------------------

    def _replicate(self, updates: Dict[str, Any]) -> None:
        if not self.is_leader:
            return
        for peer in self.peers:
            self.meta.net.send(self.meta.name, peer, "meta_replicate", {
                "term": self.term, "seq": self.storage.seq,
                "updates": updates})

    def _send_heartbeats(self, now: float) -> None:
        if now - self._last_sent_hb < HEARTBEAT_EVERY:
            return
        self._last_sent_hb = now
        for peer in self.peers:
            self.meta.net.send(self.meta.name, peer, "meta_heartbeat", {
                "term": self.term,
                "version": list(self.storage.version)})

    # ---- follower/candidate side ---------------------------------------

    def _refuses_depose(self, src: str, now: float) -> bool:
        """Live evidence the cluster already has a working leader, so
        this member should neither grant (pre-)votes nor campaign:
        - as LEADER: fresh ack contact with a majority (check-quorum —
          a seated leader must not help a flaky-linked member assemble
          a deposing majority);
        - as follower: a fresh lease from a leader other than `src`
          (the leader itself re-campaigning is never refused)."""
        if self.is_leader:
            fresh = 1 + sum(1 for t in self._peer_contact.values()
                            if now - t <= LEASE_SECONDS
                            - HEARTBEAT_EVERY)
            return fresh * 2 > len(self.group)
        return (self.leader is not None
                and self.leader != self.meta.name
                and src != self.leader
                and now - self._last_heartbeat <= LEASE_SECONDS)

    def _start_prevote(self) -> None:
        """Raft-style pre-vote: ask whether a majority WOULD grant a
        vote at term+1 before touching self.term. An isolated member
        (e.g. one-way link loss from the leader) fails the pre-vote and
        never inflates its term — so it cannot force the healthy
        majority to adopt a higher term, silence their heartbeat acks,
        and dethrone a leader they can still reach; and after the link
        heals, its un-inflated term lets the leader's heartbeats
        reintegrate it immediately."""
        self._prevotes = {self.meta.name}
        # we campaign because the lease EXPIRED — drop the leader
        # binding now, or tick()'s re-arm of _last_heartbeat would make
        # the dead leader look fresh to our own _refuses_depose and we
        # would discard every prevote ack; a real heartbeat re-binds it
        # and cancels this round
        self.leader = None
        for peer in self.peers:
            self.meta.net.send(self.meta.name, peer, "meta_prevote_req", {
                "term": self.term + 1,
                "version": list(self.storage.version)})

    def _start_election(self) -> None:
        self.term += 1
        self.voted_term = self.term  # vote for self
        self._votes = {self.meta.name}
        self.is_leader = False
        self.leader = None
        for peer in self.peers:
            self.meta.net.send(self.meta.name, peer, "meta_vote_req", {
                "term": self.term,
                "version": list(self.storage.version)})
        self._maybe_win()

    def _maybe_win(self) -> None:
        if len(self._votes) * 2 > len(self.group):
            self.is_leader = True
            self.leader = self.meta.name
            self._peer_contact = {p: self.meta.clock()
                                  for p in self._votes
                                  if p != self.meta.name}
            self._last_sent_hb = float("-inf")
            self._send_heartbeats(self.meta.clock())
            # a fresh leader re-learns worker liveness before curing:
            # without this, the guardian would treat every worker as dead
            self.meta.on_leadership_acquired()

    # ---- message handlers (wired from MetaService.on_message) ----------

    def on_message(self, src: str, msg_type: str, payload: dict) -> bool:
        """Returns True if the message was an election-internal one."""
        if msg_type == "meta_heartbeat":
            if payload["term"] >= self.term:
                if payload["term"] > self.term or self.is_leader:
                    self._step_down(payload["term"])
                self.leader = src
                self._last_heartbeat = self.meta.clock()
                self._prevotes = None  # live leader: cancel any prevote
                # the ack is the leader's lease evidence: without it a
                # partitioned leader would keep is_leader forever and
                # serve stale leader-only reads (split-brain)
                self.meta.net.send(self.meta.name, src,
                                   "meta_heartbeat_ack",
                                   {"term": payload["term"]})
                if tuple(payload["version"]) > self.storage.version:
                    self.meta.net.send(self.meta.name, src,
                                       "meta_fetch_state", {})
            return True
        if msg_type == "meta_heartbeat_ack":
            if self.is_leader and payload["term"] == self.term:
                self._peer_contact[src] = self.meta.clock()
            return True
        if msg_type == "meta_replicate":
            if payload["term"] >= self.term:
                if payload["seq"] > self.storage.seq + 1:
                    # a replicated update was lost: applying past the gap
                    # would silently fork state while seq ties defeat
                    # every later freshness check — pull a full snapshot
                    self.meta.net.send(self.meta.name, src,
                                       "meta_fetch_state", {})
                elif payload["seq"] == self.storage.seq + 1:
                    self.storage.apply_replicated(payload["seq"],
                                                  dict(payload["updates"]))
                    self.meta.reload_state()
                # seq <= ours: stale duplicate, ignore
            return True
        if msg_type == "meta_prevote_req":
            if (payload["term"] > self.voted_term
                    and not self._refuses_depose(src, self.meta.clock())
                    and tuple(payload["version"])
                    >= self.storage.version):
                # NO state change: a pre-vote promises nothing
                self.meta.net.send(self.meta.name, src,
                                   "meta_prevote_ack",
                                   {"term": payload["term"]})
            return True
        if msg_type == "meta_prevote_ack":
            if (not self.is_leader
                    and payload["term"] == self.term + 1
                    and self._prevotes is not None
                    # a heartbeat may have landed between our prevote
                    # and this (possibly jitter-delayed) ack — a fresh
                    # leader cancels the round
                    and not self._refuses_depose("", self.meta.clock())):
                self._prevotes.add(src)
                if len(self._prevotes) * 2 > len(self.group):
                    self._prevotes = None  # one real campaign per round
                    self._start_election()
            return True
        if msg_type == "meta_vote_req":
            if payload["term"] > self.term:
                # ALWAYS adopt a higher term, granted or not — otherwise
                # a stale-state member campaigning faster permanently
                # outruns everyone else's term and no leader ever wins
                self._step_down(payload["term"])
            # lease-sticky voting / check-quorum: while we hold live
            # evidence of a working leader we refuse to elect anyone
            # else — otherwise a node that merely lost its INBOUND link
            # from the leader can win a majority while the leader
            # (still acked by the rest) keeps its lease: split brain
            grant = (payload["term"] > self.voted_term
                     and not self._refuses_depose(src,
                                                  self.meta.clock())
                     and tuple(payload["version"])
                     >= self.storage.version)
            if grant:
                self.voted_term = payload["term"]
                self.meta.net.send(self.meta.name, src, "meta_vote_ack", {
                    "term": payload["term"]})
            return True
        if msg_type == "meta_vote_ack":
            if (not self.is_leader and payload["term"] == self.term
                    and self.voted_term == self.term):
                self._votes.add(src)
                self._maybe_win()
            return True
        if msg_type == "meta_fetch_state":
            if self.is_leader:
                self.meta.net.send(self.meta.name, src,
                                   "meta_state_snapshot", {
                                       "term": self.term,
                                       "seq": self.storage.seq,
                                       "tree": self.storage.snapshot()})
            return True
        if msg_type == "meta_state_snapshot":
            if payload["term"] >= self.term and not self.is_leader:
                self.storage.load_snapshot(dict(payload["tree"]))
                self.meta.reload_state()
            return True
        return False

    def _step_down(self, term: int) -> None:
        self.term = term
        self.is_leader = False

    # ---- timer ---------------------------------------------------------

    def tick(self) -> None:
        if not self.peers:
            return  # single-meta
        now = self.meta.clock()
        if self.is_leader:
            self._send_heartbeats(now)
            # margin of one heartbeat below the followers' minimum
            # election delay: the leader must demote strictly BEFORE
            # any follower can start a winning campaign, even with the
            # ack's one-way delay anchoring our clock later than theirs
            fresh = 1 + sum(1 for t in self._peer_contact.values()
                            if now - t <= LEASE_SECONDS
                            - HEARTBEAT_EVERY)
            if fresh * 2 <= len(self.group):
                # contact lost with a majority: the lease can no longer
                # be presumed held — demote BEFORE a newly elected peer
                # and this node answer leader-only requests differently
                self.is_leader = False
                self.leader = None
                self._last_heartbeat = now  # full (staggered) delay
        elif now - self._last_heartbeat > self._election_delay:
            # re-arm before campaigning so a failed round retries after
            # another full (still staggered) delay, not every tick
            self._last_heartbeat = now
            self._start_prevote()

    def forward_to_leader(self, src: str, msg_type: str,
                          payload: dict) -> bool:
        """Follower-side request forwarding (parity: check_leader →
        forward, meta_service.h:304). The original request is WRAPPED —
        spoofing the original src would make a TCP leader bind the
        requester's name to the follower's connection, blackholing the
        leader's replies to the real requester."""
        if self.is_leader:
            return False
        if self.leader is not None and self.leader != self.meta.name:
            self.meta.net.send(self.meta.name, self.leader,
                               "meta_forward", {
                                   "src": src, "msg_type": msg_type,
                                   "payload": payload})
        return True
