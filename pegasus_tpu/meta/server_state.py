"""Cluster state: tables + partition configurations, persisted.

Parity: src/meta/server_state.{h,cpp} — all app_state (table metadata,
envs, status incl. the dropped-recall window) and every partition's
partition_configuration (ballot, primary, secondaries,
idl/dsn.layer2.thrift:34-46), persisted to the meta storage tree and
mutated only through ballot-bumping updates.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from pegasus_tpu.meta.meta_storage import MetaStorage

AS_AVAILABLE = "available"
AS_DROPPED = "dropped"


@dataclass
class PartitionConfig:
    ballot: int = 0
    primary: str = ""
    secondaries: List[str] = field(default_factory=list)

    def to_json(self) -> dict:
        return {"ballot": self.ballot, "primary": self.primary,
                "secondaries": list(self.secondaries)}

    @staticmethod
    def from_json(d: dict) -> "PartitionConfig":
        return PartitionConfig(d["ballot"], d["primary"],
                               list(d["secondaries"]))

    def members(self) -> List[str]:
        return ([self.primary] if self.primary else []) + list(self.secondaries)


@dataclass
class AppState:
    app_id: int
    app_name: str
    partition_count: int
    status: str = AS_AVAILABLE
    envs: Dict[str, str] = field(default_factory=dict)
    max_replica_count: int = 3

    def to_json(self) -> dict:
        return {"app_id": self.app_id, "app_name": self.app_name,
                "partition_count": self.partition_count,
                "status": self.status, "envs": dict(self.envs),
                "max_replica_count": self.max_replica_count}

    @staticmethod
    def from_json(d: dict) -> "AppState":
        return AppState(d["app_id"], d["app_name"], d["partition_count"],
                        d["status"], dict(d["envs"]),
                        d.get("max_replica_count", 3))


class ServerState:
    def __init__(self, storage: MetaStorage) -> None:
        self._storage = storage
        self.apps: Dict[int, AppState] = {}
        self.configs: Dict[int, List[PartitionConfig]] = {}
        self._load()

    def _load(self) -> None:
        for app_id_s in self._storage.children("/apps"):
            app_id = int(app_id_s)
            data = self._storage.get(f"/apps/{app_id}")
            if data is None:
                continue
            app = AppState.from_json(data)
            self.apps[app_id] = app
            pcs = []
            for pidx in range(app.partition_count):
                pc = self._storage.get(f"/apps/{app_id}/{pidx}")
                pcs.append(PartitionConfig.from_json(pc) if pc
                           else PartitionConfig())
            self.configs[app_id] = pcs

    def next_app_id(self) -> int:
        return max(self.apps, default=0) + 1

    def find_app(self, app_name: str) -> Optional[AppState]:
        for app in self.apps.values():
            if app.app_name == app_name and app.status == AS_AVAILABLE:
                return app
        return None

    def find_dropped_app(self, app_name: str) -> Optional[AppState]:
        for app in self.apps.values():
            if app.app_name == app_name and app.status == AS_DROPPED:
                return app
        return None

    def put_app(self, app: AppState,
                configs: Optional[List[PartitionConfig]] = None) -> None:
        self.apps[app.app_id] = app
        updates = {f"/apps/{app.app_id}": app.to_json()}
        if configs is not None:
            self.configs[app.app_id] = configs
            for pidx, pc in enumerate(configs):
                updates[f"/apps/{app.app_id}/{pidx}"] = pc.to_json()
        self._storage.set_batch(updates)

    def update_partition(self, app_id: int, pidx: int,
                         pc: PartitionConfig) -> None:
        """Persist-then-publish: the new config hits reliable storage
        before anyone can observe it (reference ordering in
        server_state config updates)."""
        self._storage.set(f"/apps/{app_id}/{pidx}", pc.to_json())
        self.configs[app_id][pidx] = pc

    def set_partition_raw(self, app_id: int, pidx: int,
                          pc: PartitionConfig) -> None:
        """update_partition for an index beyond the app's current count —
        partition split registers child configs BEFORE the count flips
        (parity: meta_split_service child registration)."""
        self._storage.set(f"/apps/{app_id}/{pidx}", pc.to_json())
        self._extend_configs(app_id, pidx)
        self.configs[app_id][pidx] = pc

    def _extend_configs(self, app_id: int, pidx: int) -> None:
        """Grow the in-memory list to cover `pidx`, loading any persisted
        beyond-count entries from storage — a meta restart mid-split must
        not blank child configs registered before the restart (boot only
        loads indices < partition_count)."""
        configs = self.configs[app_id]
        while len(configs) <= pidx:
            data = self._storage.get(f"/apps/{app_id}/{len(configs)}")
            configs.append(PartitionConfig.from_json(data) if data
                           else PartitionConfig())

    def get_partition(self, app_id: int, pidx: int) -> PartitionConfig:
        if pidx >= len(self.configs[app_id]):
            self._extend_configs(app_id, pidx)
        return self.configs[app_id][pidx]
