"""Reliable tree-structured KV for cluster metadata.

Parity: src/meta/meta_state_service.h:56 (interface) with the
`meta_state_service_simple` local implementation (the ZK-free test/onebox
backend, src/meta/meta_state_service_simple.h) — node paths like
/apps/<id>/<pidx> with JSON values, persisted atomically to one file.
A ZooKeeper-backed implementation slots in behind the same interface for
multi-meta deployments.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Dict, List, Optional


class MetaStorage:
    def __init__(self, path: str) -> None:
        self.path = path
        self._tree: Dict[str, Any] = {}
        if os.path.exists(path):
            with open(path) as f:
                self._tree = json.load(f)

    def _persist(self) -> None:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(self.path) or ".")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(self._tree, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except BaseException:
            if os.path.exists(tmp):
                os.remove(tmp)
            raise

    def set(self, node: str, value: Any) -> None:
        self._tree[node] = value
        self._persist()

    def set_batch(self, updates: Dict[str, Any]) -> None:
        """Many nodes, one persisted write+fsync (DDL writes an app plus
        all its partitions; per-node persists would be O(partitions)
        full-file fsyncs)."""
        self._tree.update(updates)
        self._persist()

    def get(self, node: str) -> Optional[Any]:
        return self._tree.get(node)

    def delete(self, node: str) -> None:
        removed = False
        for key in [k for k in self._tree
                    if k == node or k.startswith(node + "/")]:
            del self._tree[key]
            removed = True
        if removed:
            self._persist()

    def children(self, node: str) -> List[str]:
        prefix = node.rstrip("/") + "/"
        out = set()
        for key in self._tree:
            if key.startswith(prefix):
                out.add(key[len(prefix):].split("/")[0])
        return sorted(out)
