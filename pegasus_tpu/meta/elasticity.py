"""Elasticity controller: the detect→decide→act closed loop.

Parity: the pieces the reference ships separately — the collector's
hotspot_partition_calculator (detect), meta_split_service and
greedy_load_balancer (act), and the operator who connects them — closed
into one meta-side loop on the guardian timer:

- **detect** — per-partition load signals flow node→meta on the
  EXISTING config-sync report channel: each stored-replica entry a node
  reports for a partition it leads carries the partition's cumulative
  capacity units (server/capacity_units.py) and the HotkeyCollector's
  published result; the node additionally reports its foreground
  pressure counters (deadline_expired_count + read_shed_count — the
  PR 2 shed/deadline machinery) and fence rejects.
- **decide** — z-score outlier over per-partition CU rates
  (server/hotkey.hotspot_partition_indices — the same statistic the
  reference's hotspot calculator applies to partition QPS). A flagged
  partition first gets hotkey detection STARTED on its primary (the
  `detect_hotkey` message); what comes back splits the diagnosis:
  a DOMINANT hashkey means the heat is one key — a split cannot shed
  it (a hashkey never spans partitions), so the cure is a load-driven
  primary move off the hot node; diffuse heat (detection window passes
  with no dominant key) or sustained whole-table overload is
  capacity-shaped — the cure is a SPLIT doubling the partition count.
- **act** — split via MetaSplitService.start_partition_split (which
  refuses on unhealthy/quarantined partitions and on pending balancer
  moves), rebalance via MetaService.rebalance (which skips apps with an
  in-flight split). Actions are PACED: at most one per act interval,
  and whenever any node's pressure counters grew since the last look
  the controller backs off exponentially instead of acting —
  background elasticity must never pile data movement onto a cluster
  already shedding foreground work.

Metrics (meta entity): partition_split_inflight (gauge),
balance_proposal_count, elasticity_split_count, elasticity_move_count,
elasticity_backoff_count. The `hot_partitions` admin/shell verb dumps
the signals and the controller's state, so an operator sees exactly
what the loop sees.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from pegasus_tpu.server.hotkey import hotspot_partition_indices
from pegasus_tpu.utils.errors import PegasusError
from pegasus_tpu.utils.flags import FLAGS, define_flag
from pegasus_tpu.utils.metrics import METRICS

Gpid = Tuple[int, int]

define_flag("pegasus.meta", "elasticity_act_interval_s", 15.0,
            "minimum seconds between elasticity actions (split or "
            "load-driven move); pressure backoff multiplies this",
            mutable=True)
define_flag("pegasus.meta", "elasticity_split_cu_rate", 2000.0,
            "sustained per-partition capacity-unit rate (CU/s averaged "
            "over the whole table) above which the table is considered "
            "oversized and split",
            mutable=True)
define_flag("pegasus.meta", "elasticity_detect_grace_s", 10.0,
            "seconds a started hotkey detection may run before diffuse "
            "heat is concluded (and a split considered)",
            mutable=True)


class ElasticityController:
    """One per MetaService; leader-only tick (the guardian timer)."""

    HOT_ZSCORE = 3.0
    MAX_BACKOFF = 16

    def __init__(self, meta) -> None:
        self.meta = meta
        ent = METRICS.entity("meta", meta.name)
        self._split_inflight = ent.gauge("partition_split_inflight")
        self._proposal_count = ent.counter("balance_proposal_count")
        self._split_count = ent.counter("elasticity_split_count")
        self._move_count = ent.counter("elasticity_move_count")
        self._backoff_count = ent.counter("elasticity_backoff_count")
        # gpid -> latest primary-reported load sample:
        # {node, read_cu, write_cu, hot_key, hot_state, at}
        self._reports: Dict[Gpid, dict] = {}
        # gpid -> (node, cu_total, at) of the previous sample (rate
        # basis; the node matters — two nodes' cumulative counters
        # are unrelated, so a failover must re-base, not diff)
        self._last_cu: Dict[Gpid, Tuple[str, int, float]] = {}
        # gpid -> smoothed CU/s rate
        self.rates: Dict[Gpid, float] = {}
        # gpid -> latest UNsmoothed CU/s rate (episode-end signal)
        self._inst: Dict[Gpid, float] = {}
        # node -> latest cumulative pressure count (shed + expired)
        self._pressure: Dict[str, int] = {}
        self._pressure_seen = 0
        self._backoff = 1
        self._next_act = 0.0
        # gpid -> sim-time a hotkey detection was commanded
        self._detect_started: Dict[Gpid, float] = {}
        self.last_action: Optional[dict] = None

    # ---- detect: node→meta report intake ------------------------------

    def on_report(self, node: str, payload: dict) -> None:
        """Config-sync intake (the existing report channel): pick up the
        per-partition load samples and the node pressure counters."""
        pressure = payload.get("pressure")
        if pressure:
            self._pressure[node] = int(pressure.get("deadline_expired", 0)
                                       + pressure.get("read_shed", 0))
        for entry in payload.get("stored", []):
            load = entry.get("load")
            if not load:
                continue
            gpid = tuple(entry["gpid"])
            self._reports[gpid] = {
                "node": node,
                "read_cu": int(load.get("read_cu", 0)),
                "write_cu": int(load.get("write_cu", 0)),
                "hot_key": load.get("hot_key"),
                "hot_state": load.get("hot_state"),
                "at": float(load.get("at", 0.0)),
            }

    def _update_rates(self) -> None:
        for gpid, rep in self._reports.items():
            total = rep["read_cu"] + rep["write_cu"]
            prev = self._last_cu.get(gpid)
            self._last_cu[gpid] = (rep["node"], total, rep["at"])
            if prev is None:
                continue
            prev_node, prev_total, prev_at = prev
            if prev_node != rep["node"]:
                continue  # leadership moved: diffing the new node's
                # counter against the old node's would manufacture a
                # huge phantom rate (or clamp a real one to zero) —
                # re-base and wait for the next same-node sample
            dt = rep["at"] - prev_at
            if dt <= 0:
                continue  # same sample re-reported; keep the old rate
            inst = max(0.0, (total - prev_total) / dt)
            self._inst[gpid] = inst
            old = self.rates.get(gpid)
            # light smoothing: one noisy interval must not trigger a
            # split, one quiet one must not un-flag a real hotspot
            self.rates[gpid] = (inst if old is None
                                else 0.5 * old + 0.5 * inst)

    def node_load(self) -> Dict[str, float]:
        """node -> summed CU/s over the partitions it leads."""
        out: Dict[str, float] = {}
        for gpid, rate in self.rates.items():
            rep = self._reports.get(gpid)
            if rep is not None:
                out[rep["node"]] = out.get(rep["node"], 0.0) + rate
        return out

    # ---- decide + act --------------------------------------------------

    def tick(self, act: bool = True) -> None:
        """`act=False` (steady level): keep the signal pipeline and
        metrics warm for `hot_partitions`, but never split or move —
        acting is the lively level's contract, like auto-balance."""
        meta = self.meta
        self._split_inflight.set(len(meta.split._splits))
        apps = meta.list_apps()
        # drop signal state for gpids that no longer exist (dropped
        # table, admin split flip): a frozen hot rate would otherwise
        # haunt node_load() forever and skew every move decision
        live = {(a.app_id, p) for a in apps
                for p in range(a.partition_count)}
        for d in (self._reports, self._last_cu, self.rates,
                  self._inst, self._detect_started):
            for gpid in [g for g in d if g not in live]:
                del d[gpid]
        self._update_rates()
        if not act:
            return
        now = meta.clock()
        interval = float(FLAGS.get("pegasus.meta",
                                   "elasticity_act_interval_s"))
        # foreground-pressure gate: if shed/deadline counters grew since
        # the last look, the cluster is fighting for its life — back off
        # instead of adding split/learn traffic
        pressure_now = sum(self._pressure.values())
        if pressure_now > self._pressure_seen:
            self._pressure_seen = pressure_now
            self._backoff = min(self._backoff * 2, self.MAX_BACKOFF)
            self._backoff_count.increment()
            self._next_act = max(self._next_act,
                                 now + interval * self._backoff)
            return
        self._pressure_seen = pressure_now
        if self._backoff > 1:
            self._backoff -= 1
        if now < self._next_act:
            return
        for app in apps:
            if app.app_id in meta.split._splits:
                continue  # the in-flight split IS the elasticity action
            action = self._decide(app, now)
            if action is None:
                continue
            if self._act(app, action, now):
                self._next_act = now + interval * self._backoff
                return  # one action per interval, cluster-wide
            # guarded off: a refusal is not an action — keep scanning
            # so one perpetually-refused app can't starve the rest

    def _decide(self, app, now: float) -> Optional[dict]:
        rates = [self.rates.get((app.app_id, p), 0.0)
                 for p in range(app.partition_count)]
        if not any(rates):
            return None
        split_rate = float(FLAGS.get("pegasus.meta",
                                     "elasticity_split_cu_rate"))
        hot = hotspot_partition_indices(rates, self.HOT_ZSCORE)
        # a detection window belongs to ONE flag episode, and the
        # episode ends on the INSTANTANEOUS rate: a z-score over the
        # smoothed rates can never un-flag a lone outlier (z saturates
        # at sqrt(n-1) however small the gap), so judging "cooled" on
        # the smoothed signal would let a stale stamp survive the quiet
        # weeks and instantly conclude "diffuse" — splitting unprovoked
        # — the moment the partition re-flags
        inst = [self._inst.get((app.app_id, p), 0.0)
                for p in range(app.partition_count)]
        inst_hot = set(hotspot_partition_indices(inst, self.HOT_ZSCORE))
        live = {(app.app_id, p) for p in hot if p in inst_hot}
        for gpid in [g for g in self._detect_started
                     if g[0] == app.app_id and g not in live]:
            del self._detect_started[gpid]
        if hot:
            pidx = max(hot, key=lambda p: rates[p])
            gpid = (app.app_id, pidx)
            if pidx not in inst_hot:
                # smoothed memory of a cooling partition: no new
                # episode, no action — let the rate decay
                return None
            rep = self._reports.get(gpid) or {}
            if rep.get("hot_key"):
                # one dominant hashkey: a split cannot shed it (the key
                # stays whole in one partition) — move the load instead
                return {"kind": "move", "gpid": gpid,
                        "hot_key": rep["hot_key"]}
            started = self._detect_started.get(gpid)
            grace = float(FLAGS.get("pegasus.meta",
                                    "elasticity_detect_grace_s"))
            if started is None:
                # detect: command the two-phase hotkey detection on the
                # partition's primary and wait for its verdict; no
                # alive primary to command -> no window, retry next tick
                if self._start_detection(gpid):
                    self._detect_started[gpid] = now
                return None
            if now - started < grace:
                # detector sampling; re-send each tick (a no-op on a
                # running collector) so a lost command or a failed-over
                # primary still gets a detector under the window
                self._start_detection(gpid)
                return None
            if not self._detection_ran(rep):
                # grace elapsed but no collector ever sampled (command
                # lost, or the primary died and its successor reports
                # fresh stopped collectors): concluding "diffuse" here
                # would split on zero evidence — restart the window
                if self._start_detection(gpid):
                    self._detect_started[gpid] = now
                return None
            # diffuse heat: many keys share the load — capacity-shaped,
            # a split halves every key range
            return {"kind": "split", "reason": "diffuse_hotspot",
                    "gpid": gpid}
        avg = sum(rates) / len(rates)
        if avg >= split_rate:
            return {"kind": "split", "reason": "oversized", "avg": avg}
        return None

    def _act(self, app, action: dict, now: float) -> bool:
        meta = self.meta
        record = dict(action, app=app.app_name, at=now)
        try:
            if action["kind"] == "split":
                new_count = meta.split.start_partition_split(app.app_name)
                record["new_count"] = new_count
                self._split_count.increment()
                self._split_inflight.set(len(meta.split._splits))
                # the count flip re-keys every (app_id, pidx) signal;
                # stale pre-split rates must not double-trigger
                self._forget_app(app.app_id)
            else:
                moved = self._move_hot_primary(action["gpid"])
                record["moved_to"] = moved
                if moved:
                    self._move_count.increment()
                # the verdict is consumed: re-arm detection (restart
                # clears the collector's FINISHED result) so the NEXT
                # episode must re-prove a dominant key — a stale verdict
                # must never pin this partition to "move" forever while
                # later heat is actually diffuse and needs a split
                if self._start_detection(action["gpid"]):
                    self._detect_started[action["gpid"]] = now
        except PegasusError as e:
            # guarded off (unhealthy partition, pending balancer move,
            # concurrent split): record it; tick scans the next app
            record["refused"] = str(e)
            self.last_action = record
            return False
        self.last_action = record
        return True

    @staticmethod
    def _detection_ran(rep: dict) -> bool:
        """True when the latest primary report shows a hotkey collector
        actually sampling — evidence the detect command landed. Reports
        without the hot_state block (older nodes) are trusted."""
        hs = rep.get("hot_state")
        if hs is None:
            return True
        return any(v != "stopped" for v in hs.values())

    def _move_hot_primary(self, gpid: Gpid) -> Optional[str]:
        """Load-driven primary move: hand the hot partition's
        leadership to its coolest alive secondary (zero-copy — the
        balancer's move_primary shape, chosen by CU load instead of
        counts)."""
        meta = self.meta
        pc = meta.state.get_partition(*gpid)
        loads = self.node_load()
        here = loads.get(pc.primary, 0.0)
        candidates = [s for s in pc.secondaries if meta.fd.is_alive(s)]
        if not candidates:
            return None
        target = min(candidates, key=lambda n: loads.get(n, 0.0))
        # the move only helps if the target stays cooler than the
        # source was WITH the partition's own load on board — otherwise
        # the partition remains the outlier on its new node and the
        # next interval moves it straight back (ballot-bumping
        # ping-pong that never reduces heat)
        rate = self.rates.get(gpid, 0.0)
        if loads.get(target, 0.0) + rate >= here:
            return None
        meta._move_primary(gpid, target)
        self._proposal_count.increment()
        return target

    def _start_detection(self, gpid: Gpid) -> bool:
        pc = self.meta.state.get_partition(*gpid)
        if not pc.primary:
            return False
        self.meta.net.send(self.meta.name, pc.primary,
                           "detect_hotkey", {"gpid": gpid})
        return True

    def _forget_app(self, app_id: int) -> None:
        for d in (self._reports, self._last_cu, self.rates,
                  self._inst, self._detect_started):
            for gpid in [g for g in d if g[0] == app_id]:
                del d[gpid]

    # ---- observability (the hot_partitions verb) -----------------------

    def status(self, app_name: str = "") -> dict:
        meta = self.meta
        apps = meta.list_apps()
        if app_name:
            apps = [a for a in apps if a.app_name == app_name]
        partitions = []
        for app in apps:
            for pidx in range(app.partition_count):
                gpid = (app.app_id, pidx)
                rep = self._reports.get(gpid) or {}
                hk = rep.get("hot_key")
                partitions.append({
                    "app": app.app_name, "gpid": list(gpid),
                    "primary": meta.state.get_partition(*gpid).primary,
                    "cu_rate": round(self.rates.get(gpid, 0.0), 1),
                    "read_cu": rep.get("read_cu", 0),
                    "write_cu": rep.get("write_cu", 0),
                    "hot_key": (hk.decode(errors="replace")
                                if isinstance(hk, (bytes, bytearray))
                                else hk),
                    "splitting": app.app_id in meta.split._splits,
                })
        partitions.sort(key=lambda p: -p["cu_rate"])
        return {
            "partitions": partitions,
            "node_load": {n: round(v, 1)
                          for n, v in sorted(self.node_load().items())},
            "splits_inflight": sorted(meta.split._splits),
            "pressure": dict(self._pressure),
            "backoff": self._backoff,
            "last_action": self.last_action,
        }
