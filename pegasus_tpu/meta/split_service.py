"""Meta-side partition split orchestration.

Parity: src/meta/meta_split_service.h:34 — drives the in-place 2x
partition-count doubling: commands every parent partition's primary to
spawn its child (replica_split_manager.h:58 does the replica-side state
copy + catch-up), registers each child partition as it reports in, and
flips the app's partition count once EVERY child is registered. The
flip propagates through config proposals; parents drop their write
fence on receiving the new count, and clients pick it up via the
partition-hash gate + config refresh (ERR_PARENT_PARTITION_MISUSED).

Split state is persisted: a meta restart mid-split keeps driving it.
"""

from __future__ import annotations

from typing import Dict

from pegasus_tpu.meta.server_state import PartitionConfig
from pegasus_tpu.utils.errors import ErrorCode, PegasusError


class MetaSplitService:
    def __init__(self, meta) -> None:
        self.meta = meta
        # app_id -> {old_count, new_count, registered: [child_pidx]}
        self._splits: Dict[int, dict] = {}
        self._load()

    def _load(self) -> None:
        raw = self.meta.state._storage.get("/split/inflight") or {}
        self._splits = {int(k): v for k, v in raw.items()}

    def _save(self) -> None:
        self.meta.state._storage.set_batch({"/split/inflight": {
            str(k): v for k, v in self._splits.items()}})

    # ---- control surface (parity: RPC_CM_START_PARTITION_SPLIT) --------

    def start_partition_split(self, app_name: str) -> int:
        app = self.meta.state.find_app(app_name)
        if app is None:
            raise PegasusError(ErrorCode.ERR_APP_NOT_EXIST, app_name)
        if app.app_id in self._splits:
            raise PegasusError(ErrorCode.ERR_SPLITTING, app_name)
        if app.partition_count & (app.partition_count - 1):
            raise PegasusError(
                ErrorCode.ERR_INVALID_PARAMETERS,
                "split requires a power-of-two partition count")
        # serialize against the balancer: a copy-secondary move in
        # flight on this app rides the learner flow, and the count flip
        # would land it on a pre-split config (the mirror guard of
        # MetaService.rebalance skipping splitting apps)
        pending = sorted(g for g in set(self.meta._pending_moves)
                         | set(self.meta._pending_learns)
                         if g[0] == app.app_id)
        if pending:
            raise PegasusError(
                ErrorCode.ERR_INVALID_STATE,
                f"balancer/learner moves pending on {app_name}: "
                f"{pending} — retry once they land")
        # only split a HEALTHY table: every parent needs an alive
        # primary to checkpoint from (a quarantined/dead partition is
        # mid-repair — splitting would copy from nothing or race the
        # re-learn), and a restoring partition has no data yet
        for pidx in range(app.partition_count):
            gpid = (app.app_id, pidx)
            if gpid in self.meta.pending_restores:
                raise PegasusError(ErrorCode.ERR_INVALID_STATE,
                                   f"partition {pidx} is restoring")
            pc = self.meta.state.get_partition(app.app_id, pidx)
            if not pc.primary or not self.meta.fd.is_alive(pc.primary):
                raise PegasusError(
                    ErrorCode.ERR_INVALID_STATE,
                    f"partition {pidx} has no alive primary "
                    "(unhealthy/quarantined) — split refused")
        self._splits[app.app_id] = {
            "old_count": app.partition_count,
            "new_count": app.partition_count * 2,
            "registered": [],
        }
        self._save()
        self._drive(app.app_id)
        return app.partition_count * 2

    def split_status(self, app_name: str) -> dict:
        app = self.meta.state.find_app(app_name)
        if app is None:
            raise PegasusError(ErrorCode.ERR_APP_NOT_EXIST, app_name)
        info = self._splits.get(app.app_id)
        if info is None:
            return {"splitting": False,
                    "partition_count": app.partition_count}
        return {"splitting": True, "old_count": info["old_count"],
                "registered": sorted(info["registered"])}

    # ---- driving -------------------------------------------------------

    def _drive(self, app_id: int) -> None:
        info = self._splits.get(app_id)
        if info is None:
            return
        for pidx in range(info["old_count"]):
            child_pidx = pidx + info["old_count"]
            if child_pidx in info["registered"]:
                continue
            pc = self.meta.state.get_partition(app_id, pidx)
            if not pc.primary:
                continue
            self.meta.net.send(self.meta.name, pc.primary, "start_split", {
                "gpid": (app_id, pidx),
                "child_gpid": (app_id, child_pidx),
                "new_count": info["new_count"]})

    def is_parent_fenced(self, app_id: int, pidx: int) -> bool:
        """A parent whose child has registered must stay write-fenced on
        WHOEVER is its primary until the flip: a failover would otherwise
        hand primaryship to an unfenced node whose writes to the child
        half silently vanish at the flip. The flag rides in every config
        proposal, so a new primary is fenced in the same message that
        promotes it."""
        info = self._splits.get(app_id)
        return (info is not None
                and pidx + info["old_count"] in info["registered"])

    def on_register_child(self, src: str, payload: dict) -> None:
        """Parity: register_child_on_meta — the child partition enters the
        cluster state; the count flips once every child is in."""
        child = tuple(payload["child_gpid"])
        app_id = child[0]
        info = self._splits.get(app_id)
        if info is None:
            return
        app = self.meta.state.apps.get(app_id)
        if app is None:
            return
        if child[1] not in info["registered"]:
            info["registered"].append(child[1])
            # the child starts primary-only on the node that built it;
            # the guardian restores the replication level after the flip
            self.meta.state.set_partition_raw(
                app_id, child[1],
                PartitionConfig(ballot=1, primary=payload["primary"],
                                secondaries=[]))
            self._save()
            # re-propose the parent config ballot+1 carrying the fence
            # flag — the CURRENT primary (which may have changed since
            # the drain) learns it must stay fenced until the flip
            parent_pidx = child[1] - info["old_count"]
            pc = self.meta.state.get_partition(app_id, parent_pidx)
            new_pc = PartitionConfig(ballot=pc.ballot + 1,
                                     primary=pc.primary,
                                     secondaries=list(pc.secondaries))
            self.meta.state.update_partition(app_id, parent_pidx, new_pc)
            self.meta._propose(app_id, parent_pidx, new_pc)
        if len(info["registered"]) == info["old_count"]:
            self._finish(app_id, info)

    def _unregister_child(self, app_id: int, info: dict,
                          child_pidx: int) -> None:
        """Forget a registered child (its only replica died or
        quarantined pre-flip): clear its config, unfence + re-propose
        the parent so a fresh spawn re-registers it. The parent still
        holds the full pre-split key range until the post-flip
        compaction GC, so nothing is lost."""
        info["registered"].remove(child_pidx)
        self.meta.state.set_partition_raw(app_id, child_pidx,
                                          PartitionConfig())
        parent_pidx = child_pidx - info["old_count"]
        pc = self.meta.state.get_partition(app_id, parent_pidx)
        new_pc = PartitionConfig(ballot=pc.ballot + 1,
                                 primary=pc.primary,
                                 secondaries=list(pc.secondaries))
        self.meta.state.update_partition(app_id, parent_pidx, new_pc)
        self.meta._propose(app_id, parent_pidx, new_pc)

    def on_replica_corrupted(self, gpid, src_node: str) -> bool:
        """PR 5 quarantine firing mid-split: when the corrupt replica is
        a REGISTERED (pre-flip, single-replica) child, the usual
        remove-and-relearn cure cannot apply — there is no healthy peer
        of the child to learn from. Unregister it and re-drive the
        parent, which re-spawns the child from its own (healthy) state.
        Returns True when the report was consumed here."""
        app_id, pidx = gpid
        info = self._splits.get(app_id)
        if info is None or pidx not in info["registered"]:
            return False
        pc = self.meta.state.get_partition(app_id, pidx)
        if pc.primary != src_node:
            return False  # stale/duplicate report for a re-spawned child
        self._unregister_child(app_id, info, pidx)
        self._save()
        self._drive(app_id)
        return True

    def _finish(self, app_id: int, info: dict) -> None:
        # a registered child whose (single-replica) primary died before
        # the flip would be an empty partition after it — unregister and
        # let the tick re-split it from the parent, which still holds the
        # full pre-split key range until the post-flip compaction GC
        dead = [cp for cp in info["registered"]
                if not self.meta.fd.is_alive(
                    self.meta.state.get_partition(app_id, cp).primary)]
        if dead:
            for cp in dead:
                self._unregister_child(app_id, info, cp)
            self._save()
            self._drive(app_id)
            return
        app = self.meta.state.apps[app_id]
        app.partition_count = info["new_count"]
        self.meta.state.put_app(app)
        del self._splits[app_id]
        self._save()
        # propagate the flip: every partition (parents AND children) gets
        # a proposal carrying the new count; parents unfence on receipt
        for pidx in range(info["new_count"]):
            pc = self.meta.state.get_partition(app_id, pidx)
            self.meta._propose(app_id, pidx, pc)

    def tick(self) -> None:
        for app_id in list(self._splits):
            self._drive(app_id)
