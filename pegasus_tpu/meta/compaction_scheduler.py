"""Cluster-level compaction coordinator: the meta half of the
background-I/O scheduler.

A cluster where every node starts its env-triggered manual compaction
in the same config-sync round (the trigger env reaches everyone
together) compacts EVERYWHERE at once — every replica of every
partition loses its disk bandwidth simultaneously, which is exactly
when quorum reads have nowhere healthy to go. The coordinator
staggers the heavy runs: nodes report compaction demand on the
EXISTING config-sync channel (the PR 6 signal-channel pattern —
`{running, waiting, bytes_per_s}` rides the same payload as the
elasticity load signals), and the reply carries a leased boolean
grant. At most `compaction_concurrent_nodes` nodes hold a grant at a
time; holders are preferred while they still report running work (a
revoked mid-run compaction saves nothing — the IO is already spent),
waiters are admitted in report order as slots free, and a holder that
stops reporting (dead node) ages out after the lease.

Failure posture is deliberately soft: the node side fails OPEN (no
coordinator answer, or an expired lease, means "run") — the stagger
is a bandwidth optimization, and a meta outage must never wedge
compaction cluster-wide.
"""

from __future__ import annotations

from typing import Dict, Optional

from pegasus_tpu.utils.flags import FLAGS, define_flag
from pegasus_tpu.utils.metrics import METRICS

define_flag("pegasus.meta", "compaction_concurrent_nodes", 1,
            "how many nodes may run heavy (env-triggered) manual "
            "compactions concurrently; 0 = no stagger (every node "
            "granted)", mutable=True)
define_flag("pegasus.meta", "compaction_grant_lease_s", 30.0,
            "seconds a grant survives without the holder reporting "
            "demand (running or waiting) on config-sync", mutable=True)


class CompactionCoordinator:
    """One per MetaService; leader-only (followers drop config_sync)."""

    def __init__(self, meta) -> None:
        self.meta = meta
        # node -> latest report {running, waiting, bytes_per_s, at}
        self._reports: Dict[str, dict] = {}
        # node -> grant issue time (the live grant set)
        self._grants: Dict[str, float] = {}
        # waiters in first-seen order (dict preserves insertion)
        self._queue: Dict[str, float] = {}
        ent = METRICS.entity("meta", meta.name)
        self._g_granted = ent.gauge("compact_grant_nodes")
        self._c_grants = ent.counter("compact_grant_count")

    # ---- intake (rides _on_config_sync) --------------------------------

    def on_report(self, node: str, payload: dict) -> Optional[bool]:
        """Record the node's compaction block and answer its grant for
        this round, or None when the node reported no compaction block
        (an older node — say nothing rather than gate it)."""
        comp = payload.get("compaction")
        if comp is None:
            return None
        now = self.meta.clock()
        running = int(comp.get("running", 0))
        waiting = bool(comp.get("waiting"))
        self._reports[node] = {"running": running, "waiting": waiting,
                               "bytes_per_s":
                                   int(comp.get("bytes_per_s", 0)),
                               "at": now}
        if waiting or running:
            self._queue.setdefault(node, now)
        else:
            self._queue.pop(node, None)
        lease = float(FLAGS.get("pegasus.meta",
                                "compaction_grant_lease_s"))
        granted_at = self._grants.get(node)
        if granted_at is not None and not running \
                and now - granted_at > lease / 3:
            # a holder that is NOT running releases its slot — whether
            # it finished (no demand left) or it still reports waiting
            # (it had its turn; more demand means the BACK of the
            # queue, or rotation never advances — in-process sim nodes
            # even share the governor's waiting flag, so camping here
            # livelocks every other node's heavy compactions). The
            # lease/3 grace covers the delivery race: the grant rides
            # the NEXT reply to this node, so its first report after
            # being granted predates it ever seeing the slot — a
            # graceless release would pass the grant around the ring
            # forever with no reply ever saying yes.
            self._grants.pop(node, None)
            if node in self._queue:
                del self._queue[node]
                self._queue[node] = now  # re-queue at the tail
        self._admit(now)
        k = int(FLAGS.get("pegasus.meta", "compaction_concurrent_nodes"))
        if k <= 0:
            return True  # stagger off: everyone may run
        return node in self._grants

    def _admit(self, now: float) -> None:
        lease = float(FLAGS.get("pegasus.meta",
                                "compaction_grant_lease_s"))
        k = int(FLAGS.get("pegasus.meta", "compaction_concurrent_nodes"))
        # expire grants whose holder went silent (dead node / dropped
        # channel): a slot must never leak
        for node in list(self._grants):
            rep = self._reports.get(node)
            if rep is None or now - rep["at"] > lease:
                del self._grants[node]
        # age out reports of nodes that stopped reporting entirely
        # (removed/replaced hosts): a long-lived meta must not grow a
        # dict entry per node ever seen, and `compact_sched` must not
        # dump dead nodes forever
        for node in list(self._reports):
            if now - self._reports[node]["at"] > 10 * lease:
                del self._reports[node]
                self._queue.pop(node, None)
        if k <= 0:
            self._g_granted.set(len(self._grants))
            return
        # admit waiters in first-seen order while slots are free
        for node in list(self._queue):
            if len(self._grants) >= k:
                break
            if node in self._grants:
                continue
            rep = self._reports.get(node)
            if rep is None or now - rep["at"] > lease:
                self._queue.pop(node, None)
                continue
            self._grants[node] = now
            self._c_grants.increment()
        self._g_granted.set(len(self._grants))

    # ---- observability --------------------------------------------------

    def status(self) -> dict:
        return {
            "granted": sorted(self._grants),
            "waiting": [n for n in self._queue
                        if n not in self._grants],
            "reports": {n: dict(r)
                        for n, r in sorted(self._reports.items())},
            "concurrent_limit": int(FLAGS.get(
                "pegasus.meta", "compaction_concurrent_nodes")),
        }
