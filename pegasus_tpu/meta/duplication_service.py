"""Meta-side duplication bookkeeping.

Parity: src/meta/duplication/meta_duplication_service.h +
duplication_info.h — dup add/query/remove, per-partition confirmed-
decree bookkeeping persisted in meta state (synced up from primaries the
way duplication_sync_timer reports, meta_service.cpp RPC_CM_DUPLICATION_
SYNC), and re-homing: every tick re-sends dup_add to each partition's
CURRENT primary, so a failover moves the shipping session to the new
primary which resumes from the persisted confirmed decree.
"""

from __future__ import annotations

from typing import Dict, List

from pegasus_tpu.utils.errors import ErrorCode, PegasusError


class MetaDuplicationService:
    def __init__(self, meta) -> None:
        self.meta = meta
        # dupid -> {app_id, app_name, follower_meta, follower_app, status,
        #           progress: {str(pidx): confirmed_decree}}
        self._dups: Dict[int, dict] = {}
        self._next_dupid = 1
        # (dupid, pidx) -> latest per-session health entry from the
        # config-sync `dup` block (lag, shipped bytes, errors, last
        # error), stamped with this meta's receive clock — the
        # cluster-wide dup health surface AND the failover drill's
        # drain evidence (a drain is judged only on reports newer than
        # the fence, so a pre-fence snapshot can never fake "drained")
        self._health: Dict[tuple, dict] = {}
        # app_name -> failover drill state machine (persisted: a meta
        # failover mid-drill resumes fencing/draining where it stood)
        self._failover: Dict[str, dict] = {}
        self._load()

    def _load(self) -> None:
        raw = self.meta.state._storage.get("/duplication/dups") or {}
        self._dups = {int(k): v for k, v in raw.items()}
        self._next_dupid = max(self._dups, default=0) + 1
        self._failover = dict(self.meta.state._storage.get(
            "/duplication/failover") or {})

    def _save(self) -> None:
        self.meta.state._storage.set_batch({
            "/duplication/dups": {
                str(k): v for k, v in self._dups.items()},
            "/duplication/failover": dict(self._failover)})

    # ---- control surface (parity: dup add/query/remove RPCs) ----------

    def add_duplication(self, app_name: str, follower_meta: str,
                        follower_app: str,
                        bootstrap_root: str = "") -> int:
        """`bootstrap_root`: when set, pre-existing data is synced first
        (parity: the reference's DS_PREPARE stage — the follower table is
        created FROM a checkpoint of the master, then incremental log
        shipping starts from the checkpoint decrees; meta_duplication_
        service's follower-table creation). Empty = incremental-only (the
        follower table must already exist)."""
        app = self.meta.state.find_app(app_name)
        if app is None:
            raise PegasusError(ErrorCode.ERR_APP_NOT_EXIST, app_name)
        for info in self._dups.values():
            if (info["app_id"] == app.app_id
                    and info["follower_meta"] == follower_meta
                    and info["follower_app"] == follower_app):
                raise PegasusError(ErrorCode.ERR_DUP_EXIST, app_name)
        dupid = self._next_dupid
        self._next_dupid += 1
        self._dups[dupid] = {
            "app_id": app.app_id, "app_name": app_name,
            "follower_meta": follower_meta, "follower_app": follower_app,
            "status": "bootstrap" if bootstrap_root else "start",
            "bootstrap_root": bootstrap_root,
            "backup_id": 0, "restore_sent": False,
            "progress": {str(p): 0 for p in range(app.partition_count)},
        }
        if bootstrap_root:
            self._dups[dupid]["backup_id"] = (
                self.meta.backup.start_backup(
                    app_name, bootstrap_root, policy=f"dup{dupid}"))
        self._save()
        if not bootstrap_root:
            self._drive(dupid)
        return dupid

    def _tick_bootstrap(self, dupid: int, info: dict) -> None:
        """DS_PREPARE: wait for the master checkpoint, ask the follower
        cluster's meta to create the table from it (RETRIED every tick
        until its admin reply confirms — a dropped message or transient
        error must not stall the dup forever), then seed progress with
        the checkpoint decrees and go incremental."""
        st = self.meta.backup.backup_status(info["backup_id"])
        if not st["complete"]:
            return
        # re-send each tick until on_admin_reply flips the status; the
        # follower's ERR_APP_EXIST makes the retry idempotent
        self.meta.net.send(self.meta.name, info["follower_meta"],
                           "admin", {
                               "rid": f"dupboot-{dupid}",
                               "cmd": "restore_app",
                               "args": {
                                   "new_name": info["follower_app"],
                                   "root": info["bootstrap_root"],
                                   "policy": f"dup{dupid}",
                                   "backup_id": info["backup_id"]}})

    def on_admin_reply(self, payload: dict) -> None:
        """Completion signal for the bootstrap's restore_app verb."""
        import json as _json

        from pegasus_tpu.storage.block_service import block_service_for

        rid = payload.get("rid")
        if not isinstance(rid, str) or not rid.startswith("dupboot-"):
            return
        dupid = int(rid.split("-", 1)[1])
        info = self._dups.get(dupid)
        if info is None or info["status"] != "bootstrap":
            return
        if payload["err"] not in (0, int(ErrorCode.ERR_APP_EXIST)):
            if payload["err"] in (int(ErrorCode.ERR_INVALID_PARAMETERS),
                                  int(ErrorCode.ERR_FILE_OPERATION_FAILED)):
                # permanent: surface it instead of retrying forever
                info["status"] = "failed"
                info["error"] = str(payload.get("result"))
                self._save()
            return  # transient failures: the tick re-sends
        policy = f"dup{dupid}"
        bs = block_service_for(info["bootstrap_root"])
        for pidx_s in list(info["progress"]):
            meta_blob = _json.loads(bs.read_file(
                f"{policy}/{info['backup_id']}/{info['app_id']}/"
                f"{pidx_s}/meta.json"))
            info["progress"][pidx_s] = meta_blob["decree"]
        info["status"] = "start"
        self._save()
        self._drive(dupid)

    def list_all(self) -> List[dict]:
        """Every duplication on the cluster (parity: shell `dups` —
        the cluster-wide listing, vs query_dup's per-table view)."""
        return [dict(info, dupid=dupid)
                for dupid, info in sorted(self._dups.items())]

    def query_duplication(self, app_name: str) -> List[dict]:
        app = self.meta.state.find_app(app_name)
        if app is None:
            raise PegasusError(ErrorCode.ERR_APP_NOT_EXIST, app_name)
        return [dict(info, dupid=dupid)
                for dupid, info in self._dups.items()
                if info["app_id"] == app.app_id]

    def remove_duplication(self, dupid: int) -> None:
        info = self._dups.pop(dupid, None)
        self._save()
        if info is None:
            return
        self._stop_sessions(dupid, info)

    def _stop_sessions(self, dupid: int, info: dict) -> None:
        for pidx in range(len(info["progress"])):
            pc = self.meta.state.get_partition(info["app_id"], pidx)
            for node in pc.members():
                self.meta.net.send(self.meta.name, node, "dup_remove", {
                    "gpid": (info["app_id"], pidx), "dupid": dupid})

    def pause_duplication(self, dupid: int) -> None:
        """Parity: the shell's pause_dup (dup status DS_PAUSE). Replica
        sessions are torn down; confirmed progress stays at meta, so
        resuming re-ships from the confirmed decree (idempotent on the
        follower via timetags)."""
        info = self._dups.get(dupid)
        if info is None:
            raise PegasusError(ErrorCode.ERR_OBJECT_NOT_FOUND, str(dupid))
        if info["status"] != "start":
            raise PegasusError(
                ErrorCode.ERR_INVALID_STATE,
                f"dup {dupid} is {info['status']}, not started")
        info["status"] = "pause"
        self._save()
        self._stop_sessions(dupid, info)

    def resume_duplication(self, dupid: int) -> None:
        """Parity: start_dup on a paused duplication (DS_PAUSE->DS_START)."""
        info = self._dups.get(dupid)
        if info is None:
            raise PegasusError(ErrorCode.ERR_OBJECT_NOT_FOUND, str(dupid))
        if info["status"] != "pause":
            raise PegasusError(
                ErrorCode.ERR_INVALID_STATE,
                f"dup {dupid} is {info['status']}, not paused")
        info["status"] = "start"
        self._save()
        self._drive(dupid)

    def set_fail_mode(self, dupid: int, fail_mode: str) -> None:
        """Parity: set_dup_fail_mode FAIL_SLOW|FAIL_SKIP
        (duplication_info fail_mode): slow = retry the same mutation
        forever; skip = give up on a mutation after bounded retries and
        advance (data loss accepted by the operator)."""
        if fail_mode not in ("slow", "skip"):
            raise PegasusError(ErrorCode.ERR_INVALID_PARAMETERS, fail_mode)
        info = self._dups.get(dupid)
        if info is None:
            raise PegasusError(ErrorCode.ERR_OBJECT_NOT_FOUND, str(dupid))
        info["fail_mode"] = fail_mode
        self._save()
        if info["status"] == "start":
            self._drive(dupid)  # re-announce so live sessions pick it up

    # ---- progress sync (parity: RPC_CM_DUPLICATION_SYNC) ---------------

    def on_duplication_sync(self, payload: dict) -> None:
        info = self._dups.get(payload["dupid"])
        if info is None:
            return
        gpid = tuple(payload["gpid"])
        key = str(gpid[1])
        if payload["confirmed"] > info["progress"].get(key, 0):
            info["progress"][key] = payload["confirmed"]
            self._save()

    # ---- cluster-wide dup health (rides the config-sync report) --------

    def on_report(self, node: str, payload: dict) -> None:
        """Per-session health entries from a node's config-sync `dup`
        block. Only sessions of dups this meta owns are kept (a stale
        node may still report a removed dup for a tick or two)."""
        for entry in payload.get("dup") or ():
            dupid = entry.get("dupid")
            if dupid not in self._dups:
                continue
            gpid = entry.get("gpid") or (0, 0)
            self._health[(dupid, int(gpid[1]))] = dict(
                entry, node=node, at=self.meta.clock())

    def dup_stats(self, app_name: str = "") -> List[dict]:
        """Cluster-wide duplication health: one row per dup with its
        per-partition lag/shipping entries merged in (the `shell
        dup_stats` surface; collector scrapes the node twin verb)."""
        out = []
        for dupid, info in sorted(self._dups.items()):
            if app_name and info["app_name"] != app_name:
                continue
            parts = {str(p): h for (d, p), h in self._health.items()
                     if d == dupid}
            lag_decrees = [h.get("lag_decrees", 0)
                           for h in parts.values()]
            lag_ms = [h.get("lag_ms", 0.0) for h in parts.values()]
            out.append({
                "dupid": dupid,
                "app_name": info["app_name"],
                "follower_meta": info["follower_meta"],
                "follower_app": info["follower_app"],
                "status": info["status"],
                "fail_mode": info.get("fail_mode", "slow"),
                "progress": dict(info["progress"]),
                "max_lag_decrees": max(lag_decrees, default=0),
                "max_lag_ms": max(lag_ms, default=0.0),
                "shipped_bytes": sum(h.get("shipped_bytes", 0)
                                     for h in parts.values()),
                "error_count": sum(h.get("error_count", 0)
                                   for h in parts.values()),
                "skip_count": sum(h.get("skip_count", 0)
                                  for h in parts.values()),
                "partitions": parts,
                "failover": self._failover.get(info["app_name"]),
            })
        return out

    # ---- controlled failover drill (`shell dup_failover <table>`) ------

    def start_failover(self, app_name: str) -> dict:
        """Fence the source table (client writes get typed
        ERR_DUP_FENCED, retryable), drain every partition's duplication
        to `confirmed == last_committed`, then flip the follower table
        writable (clear any `dup.fence` env over there). Asynchronous —
        meta's tick drives the phases; poll `dup_failover_status`."""
        app = self.meta.state.find_app(app_name)
        if app is None:
            raise PegasusError(ErrorCode.ERR_APP_NOT_EXIST, app_name)
        dupids = [d for d, info in self._dups.items()
                  if info["app_name"] == app_name
                  and info["status"] == "start"]
        if not dupids:
            raise PegasusError(
                ErrorCode.ERR_INVALID_STATE,
                f"no started duplication on {app_name}")
        st = self._failover.get(app_name)
        if st is not None and st["phase"] != "done":
            return self.failover_status(app_name)  # already in flight
        self._failover[app_name] = {
            "phase": "draining",
            "fence_at": self.meta.clock(),
            "dupids": dupids,
            "flip_acked": [],
        }
        # the fence propagates like every app env: config-sync replies
        # carry the authoritative set, replicas gate on it
        self.meta.update_app_envs(app_name, {"dup.fence": "write"})
        self._save()
        return self.failover_status(app_name)

    def failover_status(self, app_name: str) -> dict:
        st = self._failover.get(app_name)
        if st is None:
            raise PegasusError(ErrorCode.ERR_OBJECT_NOT_FOUND,
                               f"no failover drill on {app_name}")
        detail = []
        for dupid in st["dupids"]:
            info = self._dups.get(dupid)
            if info is None:
                continue
            for pidx_s in info["progress"]:
                h = self._health.get((dupid, int(pidx_s)), {})
                # drain evidence must be POSITIVE: the report says the
                # replica had the fence applied when it was built (a
                # report merely received after fence_at may predate the
                # env landing — a not-yet-fenced replica could still
                # have acked a write after building it), and with the
                # fence on, confirmed == last_committed proves every
                # acked write shipped
                post_fence = (h.get("at", 0.0) > st["fence_at"]
                              and bool(h.get("fenced")))
                detail.append({
                    "dupid": dupid, "pidx": int(pidx_s),
                    "confirmed": h.get("confirmed", 0),
                    "last_committed": h.get("last_committed", 0),
                    "post_fence": post_fence,
                    "drained": (post_fence
                                and h.get("confirmed", -1)
                                == h.get("last_committed", -2)),
                })
        out = {"app_name": app_name, "phase": st["phase"],
               "partitions": detail,
               "drained": bool(detail)
               and all(d["drained"] for d in detail)}
        if st.get("flip_errors"):
            out["flip_errors"] = dict(st["flip_errors"])
        return out

    def _tick_failover(self) -> None:
        for app_name, st in list(self._failover.items()):
            if st["phase"] == "draining":
                status = self.failover_status(app_name)
                if not status["drained"]:
                    continue
                st["phase"] = "flipping"
                self._save()
            if st["phase"] == "flipping":
                # flip the follower table writable: clear any drill
                # fence on the follower side. Re-sent every tick until
                # the follower meta's admin reply confirms (a dropped
                # message must not wedge the drill).
                for dupid in st["dupids"]:
                    info = self._dups.get(dupid)
                    if info is None or dupid in st["flip_acked"]:
                        continue
                    self.meta.net.send(
                        self.meta.name, info["follower_meta"], "admin", {
                            "rid": f"dupflip-{dupid}",
                            "cmd": "del_app_envs",
                            "args": {
                                "app_name": info["follower_app"],
                                "keys": ["dup.fence"]}})
                if all(d in st["flip_acked"] or d not in self._dups
                       for d in st["dupids"]):
                    st["phase"] = "done"
                    st["done_at"] = self.meta.clock()
                    self._save()

    def on_flip_reply(self, payload: dict) -> None:
        """Completion signal for the drill's follower-side flip."""
        rid = payload.get("rid")
        if not isinstance(rid, str) or not rid.startswith("dupflip-"):
            return
        dupid = int(rid.split("-", 1)[1])
        info = self._dups.get(dupid)
        if info is None:
            return
        st = self._failover.get(info["app_name"])
        if st is None or st["phase"] != "flipping":
            return
        # del_app_envs on a table without the env is a clean no-op
        # (n=0). ERR_APP_NOT_EXIST means a mis-set follower_app: stop
        # retrying (the table will never appear) but RECORD the error
        # so dup_failover_status shows the broken flip instead of a
        # silently clean drill
        if payload["err"] == int(ErrorCode.ERR_APP_NOT_EXIST):
            st.setdefault("flip_errors", {})[str(dupid)] = (
                f"follower app {info['follower_app']!r} does not exist "
                f"on {info['follower_meta']}")
        elif payload["err"] != 0:
            return  # transient: the tick re-sends
        if dupid not in st["flip_acked"]:
            st["flip_acked"].append(dupid)
            self._save()

    # ---- driving -------------------------------------------------------

    def _drive(self, dupid: int) -> None:
        info = self._dups[dupid]
        for pidx_s, confirmed in info["progress"].items():
            pidx = int(pidx_s)
            pc = self.meta.state.get_partition(info["app_id"], pidx)
            if not pc.primary:
                continue
            self.meta.net.send(self.meta.name, pc.primary, "dup_add", {
                "gpid": (info["app_id"], pidx), "dupid": dupid,
                "follower_meta": info["follower_meta"],
                "follower_app": info["follower_app"],
                "confirmed": confirmed,
                "fail_mode": info.get("fail_mode", "slow")})

    def tick(self) -> None:
        for dupid, info in list(self._dups.items()):
            if info["status"] == "bootstrap":
                self._tick_bootstrap(dupid, info)
            elif info["status"] == "start":
                self._drive(dupid)
        self._tick_failover()
