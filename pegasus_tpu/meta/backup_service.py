"""Meta-side backup orchestration.

Parity: src/meta/meta_backup_service.h:360 (policy scheduler + one-shot
backups) and backup_engine.h:68 (per-partition progress tracking). The
replica side (checkpoint → block-service upload) already exists in
server/backup.py; this service owns WHICH partitions back up, retries
through failovers, persists in-flight state so a meta restart resumes,
and stamps the completion metadata.

Protocol:
    meta  → primary : "backup_partition" {gpid, backup_id, policy, root}
    primary → meta  : "backup_partition_done" {gpid, backup_id, decree}
Retries ride the meta tick: any still-pending partition is re-sent to
its CURRENT primary (idempotent server-side — re-uploading a checkpoint
overwrites the same remote path).

Restore: `create_app_from_backup` makes a primary-only table whose
primaries download their checkpoint before the guardian is allowed to
add learners (otherwise a learner could copy the pre-restore empty
state and later serve it).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Set, Tuple

from pegasus_tpu.server.backup import BackupEngine, BackupPolicy
from pegasus_tpu.storage.block_service import block_service_for
from pegasus_tpu.utils.errors import ErrorCode, PegasusError

Gpid = Tuple[int, int]


class MetaBackupService:
    def __init__(self, meta) -> None:
        self.meta = meta
        # persisted: policies + in-flight backups survive a meta restart
        self._policies: Dict[str, dict] = {}
        self._inflight: Dict[int, dict] = {}
        # finished ids (bounded): lets backup_status tell "done" from
        # "never heard of it" — an unknown id must NOT read as complete
        self._completed: Dict[int, dict] = {}
        self._last_policy_run: Dict[str, float] = {}
        self._load()

    # ---- persistence ---------------------------------------------------

    def _load(self) -> None:
        st = self.meta.state._storage
        self._policies = st.get("/backup/policies") or {}
        raw = st.get("/backup/inflight") or {}
        self._inflight = {int(k): v for k, v in raw.items()}
        done = st.get("/backup/completed") or {}
        self._completed = {int(k): v for k, v in done.items()}

    def _save(self) -> None:
        self.meta.state._storage.set_batch({
            "/backup/policies": self._policies,
            "/backup/inflight": {str(k): v
                                 for k, v in self._inflight.items()},
            "/backup/completed": {str(k): v
                                  for k, v in self._completed.items()},
        })

    # ---- policies (parity: add/ls/modify policy RPCs) ------------------

    def add_policy(self, name: str, app_names: List[str], root: str,
                   interval_seconds: int = 86400,
                   backup_history_count: int = 3) -> None:
        if name in self._policies:
            raise PegasusError(ErrorCode.ERR_LOCK_ALREADY_EXIST, name)
        if interval_seconds < 1 or backup_history_count < 1:
            raise PegasusError(
                ErrorCode.ERR_INVALID_PARAMETERS,
                f"interval {interval_seconds} / history "
                f"{backup_history_count}")
        self._policies[name] = {
            "name": name, "app_names": list(app_names), "root": root,
            "interval_seconds": interval_seconds,
            "backup_history_count": backup_history_count,
            "enabled": True,
        }
        self._save()

    def list_policies(self) -> List[dict]:
        return list(self._policies.values())

    def query_policy(self, name: str) -> dict:
        pol = self._policies.get(name)
        if pol is None:
            raise PegasusError(ErrorCode.ERR_OBJECT_NOT_FOUND, name)
        recent = [{"backup_id": bid, **info}
                  for bid, info in self._completed.items()
                  if info["policy"] == name][-8:]
        return dict(pol, recent_backups=recent)

    def modify_policy(self, name: str,
                      add_apps: Optional[List[str]] = None,
                      remove_apps: Optional[List[str]] = None,
                      interval_seconds: Optional[int] = None,
                      backup_history_count: Optional[int] = None) -> dict:
        """Parity: modify_backup_policy — add/remove covered tables,
        retune the schedule."""
        pol = self._policies.get(name)
        if pol is None:
            raise PegasusError(ErrorCode.ERR_OBJECT_NOT_FOUND, name)
        for a in add_apps or []:
            if a not in pol["app_names"]:
                pol["app_names"].append(a)
        for a in remove_apps or []:
            if a in pol["app_names"]:
                pol["app_names"].remove(a)
        if interval_seconds is not None:
            if interval_seconds < 1:
                raise PegasusError(ErrorCode.ERR_INVALID_PARAMETERS,
                                   f"interval {interval_seconds}")
            pol["interval_seconds"] = interval_seconds
        if backup_history_count is not None:
            if backup_history_count < 1:
                raise PegasusError(ErrorCode.ERR_INVALID_PARAMETERS,
                                   f"history count {backup_history_count}")
            pol["backup_history_count"] = backup_history_count
        self._save()
        return pol

    def on_app_renamed(self, old_name: str, new_name: str) -> None:
        """Keep name-keyed policy coverage intact across a rename."""
        changed = False
        for pol in self._policies.values():
            if old_name in pol["app_names"]:
                pol["app_names"] = [new_name if a == old_name else a
                                    for a in pol["app_names"]]
                changed = True
        if changed:
            self._save()

    def enable_policy(self, name: str, enabled: bool) -> None:
        """Parity: enable/disable_backup_policy — a disabled policy keeps
        its history and config but schedules nothing."""
        pol = self._policies.get(name)
        if pol is None:
            raise PegasusError(ErrorCode.ERR_OBJECT_NOT_FOUND, name)
        pol["enabled"] = enabled
        self._save()

    # ---- one-shot backup ----------------------------------------------

    def start_backup(self, app_name: str, root: str,
                     policy: str = "manual",
                     backup_id: Optional[int] = None) -> int:
        app = self.meta.state.find_app(app_name)
        if app is None:
            raise PegasusError(ErrorCode.ERR_APP_NOT_EXIST, app_name)
        backup_id = backup_id or int(time.time() * 1000)
        while backup_id in self._inflight:
            backup_id += 1  # same-millisecond starts must not collide
        self._inflight[backup_id] = {
            "app_id": app.app_id, "app_name": app_name,
            "partition_count": app.partition_count,
            "policy": policy, "root": root,
            "pending": list(range(app.partition_count)),
            "decrees": {},
        }
        self._save()
        self._drive_backup(backup_id)
        return backup_id

    def backup_status(self, backup_id: int) -> dict:
        info = self._inflight.get(backup_id)
        if info is not None:
            return {"backup_id": backup_id, "complete": False,
                    "pending": list(info["pending"])}
        if backup_id in self._completed:
            return {"backup_id": backup_id, "complete": True,
                    "pending": []}
        return {"backup_id": backup_id, "complete": False,
                "pending": [], "unknown": True}

    def _drive_backup(self, backup_id: int) -> None:
        info = self._inflight[backup_id]
        for pidx in list(info["pending"]):
            pc = self.meta.state.get_partition(info["app_id"], pidx)
            if not pc.primary:
                continue
            self.meta.net.send(self.meta.name, pc.primary,
                               "backup_partition", {
                                   "gpid": (info["app_id"], pidx),
                                   "backup_id": backup_id,
                                   "policy": info["policy"],
                                   "root": info["root"]})

    def on_backup_partition_done(self, payload: dict) -> None:
        backup_id = payload["backup_id"]
        info = self._inflight.get(backup_id)
        if info is None:
            return
        gpid = tuple(payload["gpid"])
        if gpid[1] in info["pending"]:
            info["pending"].remove(gpid[1])
            info["decrees"][str(gpid[1])] = payload["decree"]
        if not info["pending"]:
            engine = BackupEngine(block_service_for(info["root"]),
                                  info["policy"])
            engine.finish_backup(backup_id, info["app_id"],
                                 info["app_name"],
                                 info["partition_count"])
            hist = self._policies.get(info["policy"], {}).get(
                "backup_history_count")
            if hist:
                try:
                    engine.gc_old_backups(hist)
                except IOError:
                    # history GC is best-effort housekeeping: a blob-
                    # store fault here must not wedge the backup's
                    # COMPLETION bookkeeping (the next policy-driven
                    # backup retries the GC)
                    pass
            del self._inflight[backup_id]
            self._completed[backup_id] = {
                "root": info["root"], "policy": info["policy"],
                "app_name": info["app_name"]}
            # bounded history, oldest-FINISHED first (dict insertion
            # order — ids may be caller-supplied and not time-ordered)
            while len(self._completed) > 256:
                self._completed.pop(next(iter(self._completed)))
        self._save()

    # ---- restore (parity: server_state_restore.cpp) --------------------

    def create_app_from_backup(self, new_name: str, root: str,
                               policy: str, backup_id: int,
                               replica_count: int = 3) -> int:
        engine = BackupEngine(block_service_for(root), policy)
        meta_blob = engine.read_backup_metadata(backup_id)
        app_id = self.meta.create_app(
            new_name, meta_blob["partition_count"], replica_count,
            restore_from={"root": root, "policy": policy,
                          "backup_id": backup_id,
                          "src_app_id": meta_blob["app_id"]})
        return app_id

    def drive_restores(self) -> None:
        """Tick: (re)send restore commands for pending partitions."""
        for gpid, info in list(self.meta.pending_restores.items()):
            pc = self.meta.state.get_partition(*gpid)
            if not pc.primary:
                continue
            self.meta.net.send(self.meta.name, pc.primary,
                               "restore_partition", {
                                   "gpid": gpid,
                                   "backup_id": info["backup_id"],
                                   "policy": info["policy"],
                                   "root": info["root"],
                                   "src_app_id": info["src_app_id"]})

    def on_restore_partition_done(self, payload: dict) -> None:
        self.meta.pending_restores.pop(tuple(payload["gpid"]), None)
        self.meta.persist_pending_restores()

    # ---- timer ---------------------------------------------------------

    def tick(self) -> None:
        now = self.meta.clock()
        for name, pol in self._policies.items():
            if not pol.get("enabled", True):
                continue
            last = self._last_policy_run.get(name)
            if last is not None and now - last < pol["interval_seconds"]:
                continue
            self._last_policy_run[name] = now
            for app_name in pol["app_names"]:
                if self.meta.state.find_app(app_name) is not None:
                    self.start_backup(app_name, pol["root"], name)
        for backup_id in list(self._inflight):
            self._drive_backup(backup_id)
        self.drive_restores()
