"""Perfect failure detector: beacon protocol with lease / grace asymmetry.

Parity: src/failure_detector/failure_detector.h:79-121 and
src/meta/meta_server_failure_detector.h:64. The invariant that makes the
FD "perfect" (never splits authority): the worker's lease period is
SHORTER than the master's grace period, so a worker that cannot refresh
its lease stops serving BEFORE the master declares it dead and reassigns
its partitions. Clocks only need bounded drift, not synchrony.

Master side (here): record each worker's last beacon; `check(now)`
returns workers whose grace expired. Worker side: ReplicaStub sends
beacons; a worker whose lease expired must consider itself disconnected
(`worker_lease_valid`).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

# defaults mirror the reference's config shape (check_interval 2s,
# beacon every 3s, lease 9s, grace 10s in config.min.ini terms)
DEFAULT_BEACON_INTERVAL = 3.0
DEFAULT_LEASE = 9.0
DEFAULT_GRACE = 10.0


class FailureDetector:
    """Master-side FD state."""

    def __init__(self, grace_seconds: float = DEFAULT_GRACE,
                 on_worker_dead: Optional[Callable[[str], None]] = None,
                 on_worker_alive: Optional[Callable[[str], None]] = None
                 ) -> None:
        self.grace = grace_seconds
        self._last_beacon: Dict[str, float] = {}
        self._alive: Dict[str, bool] = {}
        self.on_worker_dead = on_worker_dead
        self.on_worker_alive = on_worker_alive

    def on_beacon(self, worker: str, now: float) -> None:
        self._last_beacon[worker] = now
        if not self._alive.get(worker, False):
            self._alive[worker] = True
            if self.on_worker_alive is not None:
                self.on_worker_alive(worker)

    def check(self, now: float) -> List[str]:
        """Declare workers dead whose grace expired; returns newly dead."""
        newly_dead = []
        for worker, last in self._last_beacon.items():
            if self._alive.get(worker, False) and now - last > self.grace:
                self._alive[worker] = False
                newly_dead.append(worker)
                if self.on_worker_dead is not None:
                    self.on_worker_dead(worker)
        return newly_dead

    def is_alive(self, worker: str) -> bool:
        return self._alive.get(worker, False)

    def alive_workers(self) -> List[str]:
        return sorted(w for w, a in self._alive.items() if a)


def worker_lease_valid(last_ack: float, now: float,
                       lease_seconds: float = DEFAULT_LEASE) -> bool:
    """Worker-side self-check: serving is only allowed under a valid lease
    (lease < grace makes the detector 'perfect')."""
    return now - last_ack <= lease_seconds
