"""Load balancer: even out primaries and replicas across nodes.

Parity: src/meta/greedy_load_balancer.h:46 + load_balance_policy /
app_balance_policy / cluster_balance_policy.h:47. The reference computes
primary placements with a ford-fulkerson max-flow and greedy copy moves;
this implementation keeps the same two proposal kinds with a greedy
matcher:

- MOVE_PRIMARY: demote the primary on an overloaded node in favour of an
  existing secondary on an underloaded node (a ballot-bump config
  change — no data movement).
- COPY_SECONDARY: relocate a secondary from an overloaded node to an
  underloaded one (data movement through the learner flow).

Proposals are pure data; MetaService.rebalance applies them.
"""

from __future__ import annotations

from collections import defaultdict
from dataclasses import dataclass
from typing import Dict, List, Tuple

Gpid = Tuple[int, int]


@dataclass
class BalanceProposal:
    kind: str                  # "move_primary" | "copy_secondary"
    gpid: Gpid
    from_node: str
    to_node: str


def _counts(configs: Dict[Gpid, "PartitionConfig"], nodes: List[str]):
    primaries = {n: 0 for n in nodes}
    replicas = {n: 0 for n in nodes}
    for pc in configs.values():
        if pc.primary in primaries:
            primaries[pc.primary] += 1
        for s in [pc.primary] + list(pc.secondaries):
            if s in replicas:
                replicas[s] += 1
    return primaries, replicas


def propose_primary_moves(configs: Dict[Gpid, "PartitionConfig"],
                          nodes: List[str]) -> List[BalanceProposal]:
    """Greedy primary balancing: while the spread exceeds 1, shift one
    primary from the most-loaded node to a least-loaded node that already
    holds a secondary of that partition (zero-copy move)."""
    if not nodes:
        return []
    primaries, _ = _counts(configs, nodes)
    proposals: List[BalanceProposal] = []
    moved = set()
    while True:
        hi = max(primaries, key=lambda n: primaries[n])
        lo = min(primaries, key=lambda n: primaries[n])
        if primaries[hi] - primaries[lo] <= 1:
            break
        candidate = None
        for gpid, pc in sorted(configs.items()):
            if gpid in moved:
                continue
            if pc.primary == hi and lo in pc.secondaries:
                candidate = gpid
                break
        if candidate is None:
            break
        proposals.append(BalanceProposal("move_primary", candidate, hi, lo))
        moved.add(candidate)
        primaries[hi] -= 1
        primaries[lo] += 1
    return proposals


def propose_secondary_moves(configs: Dict[Gpid, "PartitionConfig"],
                            nodes: List[str]) -> List[BalanceProposal]:
    """Greedy replica-count balancing: move a secondary off the most
    replica-loaded node onto the least-loaded node not already hosting
    the partition."""
    if not nodes:
        return []
    _, replicas = _counts(configs, nodes)
    proposals: List[BalanceProposal] = []
    moved = set()
    while True:
        lo = min(replicas, key=lambda n: replicas[n])
        # donor: the most replica-loaded node that actually has a movable
        # secondary for a partition not already on `lo` (the global max
        # may hold only primaries, which don't copy-move)
        candidate = None
        for donor in sorted(replicas, key=lambda n: -replicas[n]):
            if replicas[donor] - replicas[lo] <= 1:
                break
            for gpid, pc in sorted(configs.items()):
                if gpid in moved:
                    continue
                if donor in pc.secondaries and lo not in pc.members():
                    candidate = (gpid, donor)
                    break
            if candidate is not None:
                break
        if candidate is None:
            break
        gpid, donor = candidate
        proposals.append(BalanceProposal("copy_secondary", gpid, donor, lo))
        moved.add(gpid)
        replicas[donor] -= 1
        replicas[lo] += 1
    return proposals
