"""Load balancer: even out primaries and replicas across nodes.

Parity: src/meta/greedy_load_balancer.h:46 + load_balance_policy /
app_balance_policy / cluster_balance_policy.h:47. The reference computes
primary placements with a ford-fulkerson max-flow and greedy copy moves;
this implementation keeps the same two proposal kinds with a greedy
matcher:

- MOVE_PRIMARY: demote the primary on an overloaded node in favour of an
  existing secondary on an underloaded node (a ballot-bump config
  change — no data movement).
- COPY_SECONDARY: relocate a secondary from an overloaded node to an
  underloaded one (data movement through the learner flow).

Proposals are pure data; MetaService.rebalance applies them.
"""

from __future__ import annotations

from collections import defaultdict
from dataclasses import dataclass
from typing import Dict, List, Tuple

Gpid = Tuple[int, int]


@dataclass
class BalanceProposal:
    kind: str                  # "move_primary" | "copy_secondary"
    gpid: Gpid
    from_node: str
    to_node: str


def _counts(configs: Dict[Gpid, "PartitionConfig"], nodes: List[str]):
    primaries = {n: 0 for n in nodes}
    replicas = {n: 0 for n in nodes}
    for pc in configs.values():
        if pc.primary in primaries:
            primaries[pc.primary] += 1
        for s in [pc.primary] + list(pc.secondaries):
            if s in replicas:
                replicas[s] += 1
    return primaries, replicas


def propose_primary_moves(configs: Dict[Gpid, "PartitionConfig"],
                          nodes: List[str]) -> List[BalanceProposal]:
    """Greedy primary balancing: while the spread exceeds 1, shift one
    primary from the most-loaded node to a least-loaded node that already
    holds a secondary of that partition (zero-copy move)."""
    if not nodes:
        return []
    primaries, _ = _counts(configs, nodes)
    proposals: List[BalanceProposal] = []
    moved = set()
    while True:
        hi = max(primaries, key=lambda n: primaries[n])
        lo = min(primaries, key=lambda n: primaries[n])
        if primaries[hi] - primaries[lo] <= 1:
            break
        candidate = None
        for gpid, pc in sorted(configs.items()):
            if gpid in moved:
                continue
            if pc.primary == hi and lo in pc.secondaries:
                candidate = gpid
                break
        if candidate is None:
            break
        proposals.append(BalanceProposal("move_primary", candidate, hi, lo))
        moved.add(candidate)
        primaries[hi] -= 1
        primaries[lo] += 1
    return proposals


def propose_secondary_moves(configs: Dict[Gpid, "PartitionConfig"],
                            nodes: List[str]) -> List[BalanceProposal]:
    """Greedy replica-count balancing: move a secondary off the most
    replica-loaded node onto the least-loaded node not already hosting
    the partition."""
    if not nodes:
        return []
    _, replicas = _counts(configs, nodes)
    proposals: List[BalanceProposal] = []
    moved = set()
    while True:
        lo = min(replicas, key=lambda n: replicas[n])
        # donor: the most replica-loaded node that actually has a movable
        # secondary for a partition not already on `lo` (the global max
        # may hold only primaries, which don't copy-move)
        candidate = None
        for donor in sorted(replicas, key=lambda n: -replicas[n]):
            if replicas[donor] - replicas[lo] <= 1:
                break
            for gpid, pc in sorted(configs.items()):
                if gpid in moved:
                    continue
                if donor in pc.secondaries and lo not in pc.members():
                    candidate = (gpid, donor)
                    break
            if candidate is not None:
                break
        if candidate is None:
            break
        gpid, donor = candidate
        proposals.append(BalanceProposal("copy_secondary", gpid, donor, lo))
        moved.add(gpid)
        replicas[donor] -= 1
        replicas[lo] += 1
    return proposals


# ---- max-flow primary placement (parity: greedy_load_balancer.h:46 —
# ford-fulkerson primary balancing; meta/test/ford_fulkerson_test.cpp) ----


def _max_flow(n: int, cap: List[List[int]], s: int, t: int) -> List[List[int]]:
    """Edmonds-Karp over an adjacency-matrix network; returns the flow
    matrix."""
    flow = [[0] * n for _ in range(n)]
    while True:
        # BFS for an augmenting path in the residual graph
        parent = [-1] * n
        parent[s] = s
        queue = [s]
        while queue and parent[t] == -1:
            u = queue.pop(0)
            for v in range(n):
                if parent[v] == -1 and cap[u][v] - flow[u][v] > 0:
                    parent[v] = u
                    queue.append(v)
        if parent[t] == -1:
            return flow
        # bottleneck along the path
        path = []
        v = t
        while v != s:
            path.append((parent[v], v))
            v = parent[v]
        bottleneck = min(cap[u][v] - flow[u][v] for u, v in path)
        for u, v in path:
            flow[u][v] += bottleneck
            flow[v][u] -= bottleneck


def propose_primary_moves_maxflow(configs: Dict[Gpid, "PartitionConfig"],
                                  nodes: List[str]
                                  ) -> List[BalanceProposal]:
    """Primary placement as a flow problem: overloaded nodes source
    excess primaries, underloaded nodes sink them, and an edge u->v
    exists per partition whose primary sits on u with a secondary on v
    (a zero-copy move lane). Max flow finds MULTI-HOP schedules the
    greedy matcher cannot — e.g. A's movable primaries reach only B, but
    B's reach C: flow routes A->B->C and both moves ship together.
    """
    if not nodes:
        return []
    primaries, _ = _counts(configs, nodes)
    total = sum(primaries.values())
    n = len(nodes)
    t_lo = total // n
    t_hi = t_lo + (1 if total % n else 0)
    idx = {node: i + 1 for i, node in enumerate(nodes)}  # 0=src, n+1=sink
    size = n + 2
    src, sink = 0, n + 1
    cap = [[0] * size for _ in range(size)]
    # per-lane capacities: partitions whose primary=u have a secondary on v
    lanes: Dict[Tuple[str, str], List[Gpid]] = defaultdict(list)
    for gpid, pc in sorted(configs.items()):
        if pc.primary not in idx:
            continue
        for s in pc.secondaries:
            if s in idx:
                lanes[(pc.primary, s)].append(gpid)
                cap[idx[pc.primary]][idx[s]] += 1
    if max(primaries.values()) - min(primaries.values()) <= 1:
        return []  # balanced; avoid churn between equally-good layouts
    for node in nodes:
        # shed down to the floor, absorb up to the ceiling: with the
        # narrower (above-ceiling / below-floor) bands a layout like
        # [3,3,1] (t_lo=2, t_hi=3) has no sources and a 4-partition app
        # on 5 nodes (t_lo=0) has no sinks — both would stay skewed
        cap[src][idx[node]] = max(0, primaries[node] - t_lo)
        cap[idx[node]][sink] = max(0, t_hi - primaries[node])
    flow = _max_flow(size, cap, src, sink)
    proposals: List[BalanceProposal] = []
    # a partition with secondaries on SEVERAL nodes feeds several lanes
    # but can move only once per round — lanes draw from a shared pool;
    # a lane that runs dry just delivers less flow this round (the next
    # rebalance round finishes the job)
    used: set = set()
    for u in nodes:
        for v in nodes:
            f = flow[idx[u]][idx[v]]
            delivered = 0
            for gpid in lanes[(u, v)]:
                if delivered >= max(0, f):
                    break
                if gpid in used:
                    continue
                used.add(gpid)
                proposals.append(
                    BalanceProposal("move_primary", gpid, u, v))
                delivered += 1
    return proposals


def propose_app_balanced_moves(configs: Dict[Gpid, "PartitionConfig"],
                               nodes: List[str]) -> List[BalanceProposal]:
    """The policy stack (parity: app_balance_policy then
    cluster_balance_policy.h:47): balance each table's primaries with the
    max-flow placement FIRST (per-app skew is what hotspots one table),
    then even out cluster-wide replica counts with greedy copy moves."""
    proposals: List[BalanceProposal] = []
    by_app: Dict[int, Dict[Gpid, "PartitionConfig"]] = defaultdict(dict)
    for gpid, pc in configs.items():
        by_app[gpid[0]][gpid] = pc
    for app_id in sorted(by_app):
        proposals.extend(propose_primary_moves_maxflow(by_app[app_id],
                                                       nodes))
    moved = {p.gpid for p in proposals}
    remaining = {g: pc for g, pc in configs.items() if g not in moved}
    proposals.extend(propose_secondary_moves(remaining, nodes))
    return proposals
