"""Meta-side bulk-load orchestration.

Parity: src/meta/meta_bulk_load_service.h:143 — the per-partition
download→ingest state machine with rolling ingestion concurrency
(meta_bulk_load_ingestion_context.*). The data move itself is a
replicated OP_INGEST mutation through 2PC (replica_2pc.cpp:211-230), so
every member ingests at the same decree; this service owns WHICH
partitions ingest, how many at once, retries across failovers, and
persisted progress so a meta restart resumes the load.

Protocol:
    meta  → primary : "trigger_ingest" {gpid, root, src_app}
    primary → meta  : "ingest_done" {gpid, err}
"""

from __future__ import annotations

import json
from typing import Dict, Optional, Tuple

from pegasus_tpu.storage.block_service import block_service_for
from pegasus_tpu.utils.errors import ErrorCode, PegasusError

Gpid = Tuple[int, int]


class MetaBulkLoadService:
    def __init__(self, meta, max_concurrent: int = 2) -> None:
        self.meta = meta
        self.max_concurrent = max_concurrent
        # app_id -> {root, src_app, pending: [pidx], inflight: [pidx]}
        self._loads: Dict[int, dict] = {}
        self._failed: Dict[int, str] = {}  # app_id -> failure reason
        self._load_state()

    def _load_state(self) -> None:
        raw = self.meta.state._storage.get("/bulk_load/inflight") or {}
        self._loads = {int(k): v for k, v in raw.items()}
        fraw = self.meta.state._storage.get("/bulk_load/failed") or {}
        self._failed = {int(k): v for k, v in fraw.items()}

    def _save(self) -> None:
        self.meta.state._storage.set_batch({
            "/bulk_load/inflight": {str(k): v
                                    for k, v in self._loads.items()},
            "/bulk_load/failed": {str(k): v
                                  for k, v in self._failed.items()},
        })

    # ---- control surface ----------------------------------------------

    def start_bulk_load(self, app_name: str, root: str,
                        src_app: Optional[str] = None) -> int:
        from pegasus_tpu.server.bulk_load import BULK_LOAD_INFO

        app = self.meta.state.find_app(app_name)
        if app is None:
            raise PegasusError(ErrorCode.ERR_APP_NOT_EXIST, app_name)
        if app.app_id in self._loads:
            raise PegasusError(ErrorCode.ERR_BUSY, "bulk load in progress")
        src_app = src_app or app_name
        bs = block_service_for(root)
        info = json.loads(bs.read_file(f"{src_app}/{BULK_LOAD_INFO}"))
        if info["partition_count"] != app.partition_count:
            raise PegasusError(
                ErrorCode.ERR_INVALID_PARAMETERS,
                f"staged for {info['partition_count']} partitions, table "
                f"has {app.partition_count}")
        # clear the old failure record only now — a retry that fails
        # VALIDATION above must not make the old failure read as success
        self._failed.pop(app.app_id, None)
        self._loads[app.app_id] = {
            "root": root, "src_app": src_app,
            "load_id": int(self.meta.clock() * 1000),
            "pending": list(range(app.partition_count)), "inflight": []}
        self._save()
        self._drive(app.app_id)
        return app.app_id

    def bulk_load_status(self, app_name: str) -> dict:
        app = self.meta.state.find_app(app_name)
        if app is None:
            raise PegasusError(ErrorCode.ERR_APP_NOT_EXIST, app_name)
        if app.app_id in self._failed:
            return {"complete": False, "failed": True,
                    "reason": self._failed[app.app_id],
                    "pending": [], "inflight": []}
        info = self._loads.get(app.app_id)
        if info is None:
            return {"complete": True, "failed": False,
                    "pending": [], "inflight": []}
        return {"complete": False, "failed": False,
                "paused": bool(info.get("paused")),
                "pending": list(info["pending"]),
                "inflight": list(info["inflight"])}

    def _find_load(self, app_name: str) -> Tuple[int, dict]:
        app = self.meta.state.find_app(app_name)
        if app is None:
            raise PegasusError(ErrorCode.ERR_APP_NOT_EXIST, app_name)
        info = self._loads.get(app.app_id)
        if info is None:
            raise PegasusError(ErrorCode.ERR_INVALID_STATE,
                               f"no bulk load in progress on {app_name}")
        return app.app_id, info

    def pause_bulk_load(self, app_name: str) -> None:
        """Parity: pause_bulk_load — in-flight partition ingests finish,
        no new ones start until restart."""
        app_id, info = self._find_load(app_name)
        info["paused"] = True
        self._save()

    def restart_bulk_load(self, app_name: str) -> None:
        app_id, info = self._find_load(app_name)
        info["paused"] = False
        self._save()
        self._drive(app_id)

    def cancel_bulk_load(self, app_name: str) -> None:
        """Parity: cancel_bulk_load — abandon the remaining partitions.
        Already-ingested partitions keep their data (the reference's
        cancel likewise leaves ingested SSTs in place); the operator
        clears or re-runs as needed."""
        app_id, info = self._find_load(app_name)
        self._failed[app_id] = "canceled by operator"
        del self._loads[app_id]
        self._save()

    def clear_bulk_load(self, app_name: str) -> None:
        """Parity: clear_bulk_load — drop any load state / failure record
        so a fresh start_bulk_load begins clean."""
        app = self.meta.state.find_app(app_name)
        if app is None:
            raise PegasusError(ErrorCode.ERR_APP_NOT_EXIST, app_name)
        self._loads.pop(app.app_id, None)
        self._failed.pop(app.app_id, None)
        self._save()

    # ---- state machine -------------------------------------------------

    def _drive(self, app_id: int) -> None:
        """Fill the rolling window (parity: the ingestion context caps
        concurrent ingests so compaction debt stays bounded)."""
        info = self._loads.get(app_id)
        if info is None or info.get("paused"):
            return
        while (info["pending"]
               and len(info["inflight"]) < self.max_concurrent):
            pidx = info["pending"].pop(0)
            info["inflight"].append(pidx)
        for pidx in info["inflight"]:
            pc = self.meta.state.get_partition(app_id, pidx)
            if not pc.primary:
                continue
            self.meta.net.send(self.meta.name, pc.primary,
                               "trigger_ingest", {
                                   "gpid": (app_id, pidx),
                                   "load_id": info.get("load_id", 0),
                                   "root": info["root"],
                                   "src_app": info["src_app"]})
        self._save()

    def on_ingest_done(self, payload: dict) -> None:
        gpid = tuple(payload["gpid"])
        info = self._loads.get(gpid[0])
        if info is None:
            return
        if payload.get("err", 0) != 0:
            # permanent per-partition failure (e.g. version mismatch):
            # abort the whole load with a VISIBLE failure record,
            # matching the reference's BLS_FAILED state
            self._failed[gpid[0]] = (
                f"partition {gpid[1]} ingest failed "
                f"(err {payload['err']})")
            del self._loads[gpid[0]]
            self._save()
            return
        if gpid[1] in info["inflight"]:
            info["inflight"].remove(gpid[1])
        if not info["pending"] and not info["inflight"]:
            del self._loads[gpid[0]]
            self._save()
        else:
            self._drive(gpid[0])

    def tick(self) -> None:
        for app_id in list(self._loads):
            self._drive(app_id)
