"""Pegasus key schema: ``[hash_key_len(u16 BE)] [hash_key] [sort_key]``.

Parity: src/base/pegasus_key_schema.h —
- pegasus_generate_key (:41): 2-byte big-endian hashkey length prefix.
- pegasus_generate_next_blob (:64,:86): smallest key strictly greater than
  every key with the given prefix (strip trailing 0xFF, increment last byte).
- pegasus_restore_key (:102).
- pegasus_key_hash (:150): crc64 of hashkey, or of sortkey when the hashkey
  is empty.
- check_pegasus_key_hash (:176): `hash & partition_version == pidx` — the
  stale-key predicate after partition split.

Routing: partition_index = crc64 % partition_count
(src/client/partition_resolver.cpp:48-50).
"""

from __future__ import annotations

import struct
from typing import Tuple

from pegasus_tpu.base.crc import crc64

HASH_KEY_LEN_MAX = 0xFFFF - 1


def generate_key(hash_key: bytes, sort_key: bytes = b"") -> bytes:
    if len(hash_key) >= 0xFFFF:
        raise ValueError("hash key length must be < 65535")
    return struct.pack(">H", len(hash_key)) + hash_key + sort_key


def restore_key(key: bytes) -> Tuple[bytes, bytes]:
    if len(key) < 2:
        raise ValueError("key too short")
    (hash_key_len,) = struct.unpack_from(">H", key)
    if len(key) < 2 + hash_key_len:
        raise ValueError("key shorter than its hash_key_len header")
    return key[2:2 + hash_key_len], key[2 + hash_key_len:]


def generate_next_bytes(hash_key: bytes, sort_key: bytes | None = None) -> bytes:
    """Adjacent next key after every key prefixed by (hash_key[, sort_key]):
    drop trailing 0xFF bytes, then increment the last remaining byte."""
    buf = bytearray(generate_key(hash_key, sort_key or b""))
    i = len(buf) - 1
    while i >= 0 and buf[i] == 0xFF:
        i -= 1
    if i < 0:
        # all 0xFF: no strictly-greater key of this form; unbounded scan
        return b""
    buf[i] += 1
    return bytes(buf[:i + 1])


def key_hash(key: bytes) -> int:
    """Hash of an encoded key: crc64(hashkey), or crc64(sortkey) if the
    hashkey is empty (parity: pegasus_key_hash, pegasus_key_schema.h:150)."""
    if len(key) < 2:
        raise ValueError("key too short")
    (hash_key_len,) = struct.unpack_from(">H", key)
    if hash_key_len > 0:
        if len(key) < 2 + hash_key_len:
            raise ValueError("key shorter than its hash_key_len header")
        return crc64(key[2:2 + hash_key_len])
    return crc64(key[2:])


def hash_key_hash(hash_key: bytes) -> int:
    return crc64(hash_key)


def key_hash_parts(hash_key: bytes, sort_key: bytes = b"") -> int:
    """pegasus_key_hash(generate_key(hash_key, sort_key)) without building
    the encoded key: crc64 of the hashkey, or of the sortkey when the
    hashkey is empty (pegasus_key_schema.h:150)."""
    return crc64(hash_key) if hash_key else crc64(sort_key)


def partition_index(hash_key: bytes, partition_count: int,
                    sort_key: bytes = b"") -> int:
    """Routing: pegasus_key_hash(generate_key(hash_key, sort_key)) % count.

    The reference client routes every request by pegasus_key_hash of the
    full encoded key (pegasus_client_impl.cpp:124,273 for single-key ops;
    :212,:362 build generate_key(hash_key, "") for multi-key ops), so an
    empty hash key routes by the sort key — exactly the hash the
    post-split staleness check (check_key_hash) and the scan/compaction
    validation predicates use. Routing by crc64(hash_key) alone would
    scatter empty-hashkey records onto partitions whose validation hash
    disagrees, silently hiding them from validated scans.
    """
    return key_hash_parts(hash_key, sort_key) % partition_count


def check_key_hash(key: bytes, pidx: int, partition_version: int) -> bool:
    """True iff this partition should serve `key` (post-split stale check).
    Callers must ensure partition_version >= 0."""
    return (key_hash(key) & partition_version) == pidx
