"""Key/value schemas and hashing (reference: src/base/)."""

from pegasus_tpu.base.crc import crc32, crc64, crc64_batch
from pegasus_tpu.base.key_schema import (
    generate_key,
    generate_next_bytes,
    restore_key,
    key_hash,
    hash_key_hash,
    check_key_hash,
    partition_index,
)
from pegasus_tpu.base.value_schema import (
    generate_value,
    extract_expire_ts,
    extract_user_data,
    extract_timetag,
    update_expire_ts,
    check_if_ts_expired,
    check_if_record_expired,
    generate_timetag,
    extract_timestamp_from_timetag,
    epoch_now,
)
