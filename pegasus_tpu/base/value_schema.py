"""Pegasus value schema (v0/v1/v2).

Parity: src/base/pegasus_value_schema.h —
- v0 (:160): ``[expire_ts(u32 BE)] [user_data]``
- v1 (:212): ``[expire_ts(u32 BE)] [timetag(u64 BE)] [user_data]`` where
  timetag = timestamp_us(56b) | cluster_id(7b) | deleted_tag(1b) (:44-47),
  used by cross-cluster duplication for conflict resolution.
- v2 (src/base/value_schema_v2.cpp:89-94): same fields as v1 through the
  pluggable field-based schema classes; identical byte layout for our
  purposes.
- expiry predicate (:113): expired iff expire_ts > 0 and expire_ts <= now.

expire_ts is seconds since the Pegasus epoch. The reference stores
seconds-since-2016 ("epoch_begin" 1451606400 = 2016-01-01T00:00:00Z,
src/base/pegasus_utils.h); we keep the same epoch so TTL arithmetic and
on-disk headers are value-compatible.
"""

from __future__ import annotations

import struct
import time
from typing import Optional, Tuple

PEGASUS_EPOCH_BEGIN = 1451606400  # 2016-01-01 00:00:00 UTC (base/pegasus_utils.h)
DATA_VERSION_MAX = 1

_TIMESTAMP_MASK = (1 << 56) - 1


def epoch_now(unix_now: Optional[float] = None) -> int:
    """Seconds since the Pegasus epoch (parity: utils::epoch_now)."""
    t = time.time() if unix_now is None else unix_now
    return max(0, int(t) - PEGASUS_EPOCH_BEGIN)


def expire_ts_from_ttl(ttl_seconds: int, now: Optional[int] = None) -> int:
    """rrdb `expire_ts_seconds` semantics: 0 = no TTL; >0 = now + ttl."""
    if ttl_seconds <= 0:
        return 0
    return (epoch_now() if now is None else now) + ttl_seconds


def generate_timetag(timestamp_us: int, cluster_id: int, deleted: bool) -> int:
    return (timestamp_us << 8) | ((cluster_id & 0x7F) << 1) | int(deleted)


def extract_timestamp_from_timetag(timetag: int) -> int:
    return (timetag >> 8) & _TIMESTAMP_MASK


def generate_value(version: int, user_data: bytes, expire_ts: int,
                   timetag: int = 0) -> bytes:
    if version == 0:
        return struct.pack(">I", expire_ts) + user_data
    if version in (1, 2):
        return struct.pack(">IQ", expire_ts, timetag) + user_data
    raise ValueError(f"unsupported value schema version: {version}")


def header_length(version: int) -> int:
    return 4 if version == 0 else 12


def extract_expire_ts(version: int, raw_value: bytes) -> int:
    (expire_ts,) = struct.unpack_from(">I", raw_value)
    return expire_ts


def extract_timetag(version: int, raw_value: bytes) -> int:
    if version < 1:
        raise ValueError("timetag requires value schema v1+")
    (timetag,) = struct.unpack_from(">Q", raw_value, 4)
    return timetag


def extract_user_data(version: int, raw_value: bytes) -> bytes:
    return raw_value[header_length(version):]


def update_expire_ts(version: int, raw_value: bytes, new_expire_ts: int) -> bytes:
    if len(raw_value) < 4:
        raise ValueError("value must include expire_ts header")
    return struct.pack(">I", new_expire_ts) + raw_value[4:]


def check_if_ts_expired(epoch_now_s: int, expire_ts: int) -> bool:
    return expire_ts > 0 and expire_ts <= epoch_now_s


def check_if_record_expired(version: int, epoch_now_s: int,
                            raw_value: bytes) -> bool:
    return check_if_ts_expired(epoch_now_s, extract_expire_ts(version, raw_value))
