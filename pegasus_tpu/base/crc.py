"""crc64 / crc32 — bit-compatible with the reference's hashing.

The reference (src/utils/crc.cpp) uses reflected table-driven CRCs with
~init/~final conventions:
- crc32: the Castagnoli polynomial (CRC-32C).
- crc64: a custom rDSN polynomial given as the bit set
  {63,61,59,58,56,55,52,49,48,47,46,44,41,37,36,34,32,31,28,26,23,22,19,
   16,13,12,10,9,6,4,3,0} of x^(63-n) coefficients in reflected order
  (src/utils/crc.cpp:289-295).

crc64(hashkey) is THE routing hash: clients map records to partitions with
`crc64(hashkey) % partition_count` (src/client/partition_resolver.cpp:48-50)
and servers validate ownership with `crc64 & partition_version`
(src/base/pegasus_key_schema.h:176-183) — so this must be bit-identical
across host Python/numpy, the device kernel (ops/device_crc.py), and any
client implementation. Golden vectors in tests/test_crc.py were produced by
running the reference implementation.

Because ~init is applied on entry and ~crc on exit, chaining
crc(b, init=crc(a)) equals crc(a+b) — both the reference and this
implementation rely on that streaming property.
"""

from __future__ import annotations

import numpy as np

_M64 = (1 << 64) - 1
_M32 = (1 << 32) - 1

_CRC64_BITS = (63, 61, 59, 58, 56, 55, 52, 49, 48, 47, 46, 44, 41, 37, 36, 34,
               32, 31, 28, 26, 23, 22, 19, 16, 13, 12, 10, 9, 6, 4, 3, 0)
CRC64_POLY = 0
for _n in _CRC64_BITS:
    CRC64_POLY |= 1 << (63 - _n)

_CRC32_BITS = (28, 27, 26, 25, 23, 22, 20, 19, 18, 14, 13, 11, 10, 9, 8, 6, 0)
CRC32_POLY = 0
for _n in _CRC32_BITS:
    CRC32_POLY |= 1 << (31 - _n)


def _make_table(poly: int, width: int) -> list[int]:
    table = []
    for i in range(256):
        k = i
        for _ in range(8):
            k = (k >> 1) ^ poly if k & 1 else k >> 1
        table.append(k)
    return table


_TABLE64 = _make_table(CRC64_POLY, 64)
_TABLE32 = _make_table(CRC32_POLY, 32)

# numpy copies for the vectorized batch path
TABLE64_NP = np.array(_TABLE64, dtype=np.uint64)
TABLE32_NP = np.array(_TABLE32, dtype=np.uint32)
# split into 32-bit lanes for the device kernel (jax has no uint64 by default)
TABLE64_LO_NP = (TABLE64_NP & np.uint64(0xFFFFFFFF)).astype(np.uint32)
TABLE64_HI_NP = (TABLE64_NP >> np.uint64(32)).astype(np.uint32)


def _crc64_py(data: bytes, init_crc: int = 0) -> int:
    crc = ~init_crc & _M64
    for b in data:
        crc = _TABLE64[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return ~crc & _M64


_crc64_native = None
_crc64_native_tried = False


def crc64(data: bytes, init_crc: int = 0) -> int:
    """Scalar crc64, parity: dsn::utils::crc64_calc (src/utils/crc.cpp:464).

    Every point get routes through crc64(hashkey); the native C
    implementation takes over when built (init-chaining stays on the
    Python loop — the C ABI exposes init=0 only)."""
    global _crc64_native, _crc64_native_tried
    if not _crc64_native_tried:
        _crc64_native_tried = True
        try:
            from pegasus_tpu import native

            if native.available():
                _crc64_native = native.crc64_native
        except Exception:  # noqa: BLE001 - fall back to the Python loop
            _crc64_native = None
    if init_crc == 0 and _crc64_native is not None:
        return _crc64_native(bytes(data))
    return _crc64_py(data, init_crc)


_crc64_rows_native = None
_crc64_rows_tried = False


def crc64_rows(data: np.ndarray, lengths: np.ndarray) -> np.ndarray:
    """crc64 of each zero-padded byte row — uint64[B].

    The batched-probe form of `crc64`: one call hashes every probe key
    of a point-read flush for the bloom-filter pass. The native C loop
    (one ctypes round per BATCH) takes over when built; the numpy
    fallback is `crc64_batch`, whose per-byte-position dispatch cost
    only amortizes on large batches — both are bit-identical to the
    scalar spec (golden vectors in tests/test_crc.py).
    """
    global _crc64_rows_native, _crc64_rows_tried
    if not _crc64_rows_tried:
        _crc64_rows_tried = True
        try:
            from pegasus_tpu.native import crc64_rows_fn

            _crc64_rows_native = crc64_rows_fn()
        except Exception:  # noqa: BLE001 - fall back to the numpy loop
            _crc64_rows_native = None
    if _crc64_rows_native is not None:
        rows = np.ascontiguousarray(data, dtype=np.uint8)
        lens = np.ascontiguousarray(lengths, dtype=np.int64)
        out = np.empty(rows.shape[0], dtype=np.uint64)
        _crc64_rows_native(rows, lens, out)
        return out
    return crc64_batch(data, lengths)


def _crc32_py(data: bytes, init_crc: int = 0) -> int:
    """Pure-Python CRC-32C (the spec twin the native path is pinned to)."""
    crc = ~init_crc & _M32
    for b in data:
        crc = _TABLE32[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return ~crc & _M32


_crc32_native = None
_crc32_native_tried = False


def crc32(data: bytes, init_crc: int = 0) -> int:
    """Scalar crc32 (CRC-32C), parity: dsn::utils::crc32_calc.

    Framing checksums (WAL frames, plog frames, SST index, wire
    messages) run this over every payload byte — the Python table loop
    is ~2 MB/s and dominated the replicated write path, so the C
    implementation (native/packer.cpp, same polynomial spec, golden
    vectors shared) takes over when the toolchain built it."""
    global _crc32_native, _crc32_native_tried
    if not _crc32_native_tried:
        _crc32_native_tried = True
        try:
            from pegasus_tpu.native import crc32_fn

            _crc32_native = crc32_fn()
        except Exception:  # noqa: BLE001 - fall back to the Python loop
            _crc32_native = None
    if _crc32_native is not None:
        return _crc32_native(data, init_crc)
    return _crc32_py(data, init_crc)


def crc64_batch(data: np.ndarray, lengths: np.ndarray,
                start: np.ndarray | int = 0) -> np.ndarray:
    """Vectorized crc64 over a batch of byte rows.

    data:    uint8[B, K] padded byte rows
    lengths: int[B] number of valid bytes per row (from `start`)
    start:   int or int[B] byte offset where each row's region begins

    Returns uint64[B]. Iterates over byte positions (K_max steps), each step
    vectorized across the batch — the same loop-order trick the device
    kernel uses (ops/device_crc.py).
    """
    data = np.ascontiguousarray(data, dtype=np.uint8)
    b, k = data.shape
    lengths = np.asarray(lengths, dtype=np.int64)
    starts = np.broadcast_to(np.asarray(start, dtype=np.int64), (b,))
    crc = np.full(b, _M64, dtype=np.uint64)  # ~0
    max_len = int(lengths.max()) if b else 0
    cols = np.arange(b)
    eight = np.uint64(8)
    for j in range(max_len):
        active = j < lengths
        pos = np.minimum(starts + j, k - 1)
        byte = data[cols, pos].astype(np.uint64)
        idx = ((crc ^ byte) & np.uint64(0xFF)).astype(np.int64)
        nxt = TABLE64_NP[idx] ^ (crc >> eight)
        crc = np.where(active, nxt, crc)
    return ~crc
