"""At-rest encryption: KMS client + per-server data-key provider.

TPU-native re-design of the reference's KMS-backed encryption
(src/security/kms_client.h, src/replica/kms_key_provider.h): a replica
server fetches/unwraps one data key at boot and every data file is
stream-encrypted with it. The reference delegates the cipher to an
encrypted rocksdb Env (AES-CTR); here the cipher is a seekable
SHAKE-256 counter-mode keystream XOR — pure stdlib (this image has no
crypto package), random-access capable (SST block reads seek), and
vectorized through numpy so file IO stays bulk work.

Integrity note: like the reference's CTR env, the file cipher itself
carries no MAC — the storage formats above it (SST index/frame crc32)
detect corruption. The *wrapped key* IS authenticated: a tampered or
wrong-root unwrap fails loudly rather than decrypting garbage.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import secrets
from typing import Optional

import numpy as np

# keystream is generated per fixed-size chunk so any byte offset can be
# served by regenerating only the covering chunks (random-access reads)
CHUNK = 4096
KEY_LEN = 32
NONCE_LEN = 16


def keystream(key: bytes, nonce: bytes, offset: int, length: int) -> bytes:
    """Seekable keystream bytes [offset, offset+length)."""
    if length <= 0:
        return b""
    first = offset // CHUNK
    last = (offset + length - 1) // CHUNK
    parts = []
    base = key + nonce
    for c in range(first, last + 1):
        parts.append(hashlib.shake_256(
            base + c.to_bytes(8, "big")).digest(CHUNK))
    blob = b"".join(parts)
    start = offset - first * CHUNK
    return blob[start:start + length]


def xor_crypt(key: bytes, nonce: bytes, offset: int, data: bytes) -> bytes:
    """Encrypt == decrypt: XOR with the keystream at `offset`."""
    if not data:
        return b""
    ks = keystream(key, nonce, offset, len(data))
    a = np.frombuffer(data, dtype=np.uint8)
    b = np.frombuffer(ks, dtype=np.uint8)
    return (a ^ b).tobytes()


class KmsError(Exception):
    pass


class LocalKmsClient:
    """Envelope KMS backed by a local root key.

    Stands in for the reference's remote KMS HTTP client
    (security/kms_client.h:GenerateEncryptionKey/DecryptEncryptionKey):
    the interface is identical — generate a (plaintext, wrapped) data
    key pair, and unwrap a stored wrapped key — so a real remote KMS can
    replace it without touching any caller.
    """

    def __init__(self, root_key: bytes) -> None:
        if len(root_key) < 16:
            raise KmsError("root key must be at least 16 bytes")
        self._root = hashlib.sha256(b"pegasus-kms-root|" + root_key).digest()

    def generate_data_key(self) -> tuple[bytes, bytes]:
        key = secrets.token_bytes(KEY_LEN)
        return key, self._wrap(key)

    def _wrap(self, key: bytes) -> bytes:
        nonce = secrets.token_bytes(NONCE_LEN)
        ct = xor_crypt(self._root, nonce, 0, key)
        tag = hmac.new(self._root, b"wrap|" + nonce + ct,
                       hashlib.sha256).digest()
        return nonce + ct + tag

    def unwrap(self, wrapped: bytes) -> bytes:
        if len(wrapped) != NONCE_LEN + KEY_LEN + 32:
            raise KmsError("malformed wrapped key")
        nonce = wrapped[:NONCE_LEN]
        ct = wrapped[NONCE_LEN:NONCE_LEN + KEY_LEN]
        tag = wrapped[NONCE_LEN + KEY_LEN:]
        want = hmac.new(self._root, b"wrap|" + nonce + ct,
                        hashlib.sha256).digest()
        if not hmac.compare_digest(tag, want):
            raise KmsError("wrapped key authentication failed "
                           "(tampered file or wrong root key)")
        return xor_crypt(self._root, nonce, 0, ct)


KEY_FILE = ".pegasus_data_key"


def _write_wrapped(path: str, wrapped: bytes) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(wrapped)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class KeyProvider:
    """Loads-or-creates the server data key under a data root.

    Parity: replica/kms_key_provider.h — the wrapped key lives next to
    the data it protects; the plaintext key exists only in memory.
    """

    def __init__(self, data_root: str, kms: LocalKmsClient) -> None:
        self.data_root = data_root
        os.makedirs(data_root, exist_ok=True)
        path = os.path.join(data_root, KEY_FILE)
        if os.path.exists(path):
            with open(path, "rb") as f:
                self.data_key = kms.unwrap(f.read())
        else:
            self.data_key, wrapped = kms.generate_data_key()
            _write_wrapped(path, wrapped)

    @classmethod
    def for_dirs(cls, dirs: list, kms: LocalKmsClient) -> "KeyProvider":
        """One provider for a multi-disk server: find the wrapped key in
        ANY of the dirs (so losing or reordering disk 0 cannot orphan
        the other disks' data), then replicate it to every dir."""
        found = None
        for d in dirs:
            if os.path.exists(os.path.join(d, KEY_FILE)):
                found = d
                break
        prov = cls(found if found is not None else dirs[0], kms)
        with open(os.path.join(prov.data_root, KEY_FILE), "rb") as f:
            wrapped = f.read()
        for d in dirs:
            os.makedirs(d, exist_ok=True)
            p = os.path.join(d, KEY_FILE)
            if not os.path.exists(p):
                _write_wrapped(p, wrapped)
        return prov


def root_key_from_env(fallback: Optional[bytes] = None) -> Optional[bytes]:
    """PEGASUS_KMS_ROOT_KEY (hex) > PEGASUS_KMS_ROOT_KEY_FILE > fallback."""
    hexkey = os.environ.get("PEGASUS_KMS_ROOT_KEY")
    if hexkey:
        return bytes.fromhex(hexkey)
    path = os.environ.get("PEGASUS_KMS_ROOT_KEY_FILE")
    if path and os.path.exists(path):
        with open(path, "rb") as f:
            return f.read().strip()
    return fallback
