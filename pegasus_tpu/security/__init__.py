from pegasus_tpu.security.auth import make_credentials, sign, verify

__all__ = ["make_credentials", "sign", "verify"]
