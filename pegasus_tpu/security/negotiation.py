"""Connection auth negotiation state machine.

Parity: src/security/negotiation.h:37 + negotiation_manager — the
SASL-style multi-step handshake every authenticated RPC session runs
before application traffic: LIST_MECHANISMS -> SELECT_MECHANISMS ->
INITIATE -> CHALLENGE/RESPONSE -> SUCC, with any out-of-order message
failing the whole negotiation (negotiation.cpp rejects invalid
transitions outright).

The reference's mechanism is SASL/GSSAPI (Kerberos). This image has no
KDC, so the one registered mechanism is HMAC-SHA256 challenge/response
over the cluster secret: the server issues a fresh nonce and the client
proves possession of the secret with HMAC(secret, user || nonce) —
unlike the static per-request token, the proof is UNREPLAYABLE (a
sniffed proof is useless for any other nonce).

On SUCC the server binds the authenticated identity to the peer's
CONNECTION (the stub keys peers as (src, transport session id) — a
self-reported frame name alone would be forgeable); later requests on
that connection may omit per-request credentials and inherit the
session identity, and the identity dies with the connection (the
reference likewise attaches the negotiated user to the RPC session).
Per-request tokens keep working — negotiation is an upgrade, not a
break.
"""

from __future__ import annotations

import hashlib
import hmac
import os
from typing import Dict, Optional, Tuple

MECH_HMAC = "HMAC-SHA256"

# negotiation_status parity (negotiation.h enum): the server enforces
# this exact order per peer; anything else -> FAIL + state reset
_ORDER = ("list_mechanisms", "select", "respond")


def _proof(secret: str, user: str, nonce: bytes) -> str:
    return hmac.new(secret.encode(), user.encode() + nonce,
                    hashlib.sha256).hexdigest()


class NegotiationServer:
    """Per-node server side: one in-flight state machine per peer
    address, plus the table of negotiated identities."""

    def __init__(self, secret: str) -> None:
        self._secret = secret
        # peer -> (stage_reached, user, nonce)
        self._inflight: Dict[str, Tuple[str, str, bytes]] = {}
        self._identities: Dict[str, str] = {}

    def identity(self, peer: str) -> Optional[str]:
        return self._identities.get(peer)

    def on_message(self, peer: str, payload: dict) -> dict:
        """Advance the peer's negotiation; returns the reply payload.
        Any out-of-order or malformed stage FAILS the negotiation and
        clears the peer's state (invalid-transition rejection)."""
        stage = payload.get("stage")
        rid = payload.get("rid")
        st = self._inflight.get(peer)
        if stage == "list_mechanisms":
            # always a legal (re)start; a new handshake voids any
            # previously negotiated identity for this peer
            self._identities.pop(peer, None)
            self._inflight[peer] = ("list_mechanisms", "", b"")
            return {"stage": "mechanisms", "mechanisms": [MECH_HMAC],
                    "rid": rid}
        if stage == "select":
            if st is None or st[0] != "list_mechanisms":
                return self._fail(peer, rid, "select before list")
            if payload.get("mechanism") != MECH_HMAC:
                return self._fail(peer, rid, "unsupported mechanism")
            user = payload.get("user") or ""
            if not user:
                return self._fail(peer, rid, "empty user")
            nonce = os.urandom(16)
            self._inflight[peer] = ("select", user, nonce)
            return {"stage": "challenge", "nonce": nonce, "rid": rid}
        if stage == "respond":
            if st is None or st[0] != "select":
                return self._fail(peer, rid, "respond before challenge")
            _stage, user, nonce = st
            want = _proof(self._secret, user, nonce)
            if not hmac.compare_digest(want,
                                       payload.get("proof") or ""):
                return self._fail(peer, rid, "bad proof")
            self._inflight.pop(peer, None)
            self._identities[peer] = user
            return {"stage": "succ", "user": user, "rid": rid}
        return self._fail(peer, rid, f"unknown stage {stage!r}")

    def _fail(self, peer: str, rid, reason: str) -> dict:
        self._inflight.pop(peer, None)
        self._identities.pop(peer, None)
        return {"stage": "fail", "reason": reason, "rid": rid}

    def forget(self, peer) -> None:
        """Connection teardown: a reconnected peer must renegotiate."""
        self._inflight.pop(peer, None)
        self._identities.pop(peer, None)

    def forget_session(self, sess: str) -> None:
        """Drop every identity/handshake bound to a closed connection
        (peers are keyed (src, session) by the stub)."""
        for d in (self._inflight, self._identities):
            for key in [k for k in d
                        if isinstance(k, tuple) and len(k) == 2
                        and k[1] == sess]:
                d.pop(key, None)


class NegotiationClient:
    """Client side: drives the three steps through a send/await pair.

    `call(dst, payload) -> reply` is the transport adapter (the cluster
    client binds its request plumbing here)."""

    def __init__(self, user: str, secret: str) -> None:
        self.user = user
        self._secret = secret

    def negotiate(self, call) -> bool:
        reply = call({"stage": "list_mechanisms"})
        if (reply.get("stage") != "mechanisms"
                or MECH_HMAC not in reply.get("mechanisms", [])):
            return False
        reply = call({"stage": "select", "mechanism": MECH_HMAC,
                      "user": self.user})
        if reply.get("stage") != "challenge":
            return False
        proof = _proof(self._secret, self.user, reply["nonce"])
        reply = call({"stage": "respond", "proof": proof})
        return reply.get("stage") == "succ"
