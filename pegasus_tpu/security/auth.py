"""Authentication + table ACLs.

Parity role: src/security/negotiation.h:37 (the RPC-connection auth
negotiation — SASL/Kerberos there; a shared-secret HMAC here, since
this environment has no KDC) and the Ranger-style per-table allow-list
(src/ranger/ranger_resource_policy_manager.h:67, enforced at the
replica's client gates like replica_2pc.cpp:117 / replica.cpp:388).

Model: the cluster holds one secret. A client identity is
(user, HMAC(secret, user)); servers verify the token and then check the
table's `replica.allowed_users` app-env (empty / absent = open table).
Inter-node traffic authenticates as the reserved NODE_USER.
"""

from __future__ import annotations

import hashlib
import hmac
from typing import Optional, Tuple

NODE_USER = "__node__"


def sign(user: str, secret: str) -> str:
    return hmac.new(secret.encode(), user.encode(),
                    hashlib.sha256).hexdigest()


def verify(user: str, token: str, secret: str) -> bool:
    return hmac.compare_digest(sign(user, secret), token)


def make_credentials(user: str, secret: str) -> Tuple[str, str]:
    return user, sign(user, secret)


# per-verb access classes (parity: src/ranger/access_type.h — READ /
# WRITE / and the control-plane classes collapsed to "a" here; meta
# admin verbs run under the operator identity)
ACCESS_READ = "r"
ACCESS_WRITE = "w"
ACCESS_ADMIN = "a"


def parse_policy(policy: str) -> dict:
    """`replica.access_policy` app-env: "alice=rw;bob=r;*=r" ->
    {user: set-of-access-chars}. "*" is the any-authenticated-user
    entry. Malformed segments are ignored (a typo must not open the
    table)."""
    out = {}
    for seg in policy.split(";"):
        seg = seg.strip()
        if not seg or "=" not in seg:
            continue
        user, grants = seg.split("=", 1)
        out[user.strip()] = {c for c in grants.strip()
                             if c in (ACCESS_READ, ACCESS_WRITE,
                                      ACCESS_ADMIN)}
    return out


def check_client(auth: Optional[tuple], secret: Optional[str],
                 allowed_users: str = "", policy: str = "",
                 access: str = "") -> bool:
    """The gate servers run per request: authentication (when the
    cluster has a secret), then the per-verb access policy, then the
    legacy table allow-list.

    `allowed_users`: comma-separated env value; empty = every
    authenticated user (parity: tables without ranger policies are
    governed by legacy allowed-user lists; empty list = open).

    `policy` + `access`: the Ranger-style per-verb layer
    (access_type.h) — when the table carries a `replica.access_policy`
    env, the request's access class ("r"/"w"/"a") must be granted to
    the user (or to "*"); inter-node traffic (NODE_USER) is exempt, as
    the reference exempts intra-cluster RPCs."""
    if secret:
        if not auth:
            return False
        user, token = auth[0], auth[1]
        if not verify(user, token, secret):
            return False
    else:
        user = auth[0] if auth else ""
    if policy and access and user != NODE_USER:
        grants = parse_policy(policy)
        g = grants.get(user, grants.get("*"))
        if g is None or access not in g:
            return False
    if allowed_users:
        allowed = {u.strip() for u in allowed_users.split(",") if u.strip()}
        return user in allowed or user == NODE_USER
    return True
