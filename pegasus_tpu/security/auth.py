"""Authentication + table ACLs.

Parity role: src/security/negotiation.h:37 (the RPC-connection auth
negotiation — SASL/Kerberos there; a shared-secret HMAC here, since
this environment has no KDC) and the Ranger-style per-table allow-list
(src/ranger/ranger_resource_policy_manager.h:67, enforced at the
replica's client gates like replica_2pc.cpp:117 / replica.cpp:388).

Model: the cluster holds one secret. A client identity is
(user, HMAC(secret, user)); servers verify the token and then check the
table's `replica.allowed_users` app-env (empty / absent = open table).
Inter-node traffic authenticates as the reserved NODE_USER.
"""

from __future__ import annotations

import hashlib
import hmac
from typing import Optional, Tuple

NODE_USER = "__node__"


def sign(user: str, secret: str) -> str:
    return hmac.new(secret.encode(), user.encode(),
                    hashlib.sha256).hexdigest()


def verify(user: str, token: str, secret: str) -> bool:
    return hmac.compare_digest(sign(user, secret), token)


def make_credentials(user: str, secret: str) -> Tuple[str, str]:
    return user, sign(user, secret)


def check_client(auth: Optional[tuple], secret: Optional[str],
                 allowed_users: str = "") -> bool:
    """The gate servers run per request: authentication (when the
    cluster has a secret) then the table allow-list.

    `allowed_users`: comma-separated env value; empty = every
    authenticated user (parity: tables without ranger policies are
    governed by legacy allowed-user lists; empty list = open)."""
    if secret:
        if not auth:
            return False
        user, token = auth[0], auth[1]
        if not verify(user, token, secret):
            return False
    else:
        user = auth[0] if auth else ""
    if allowed_users:
        allowed = {u.strip() for u in allowed_users.split(",") if u.strip()}
        return user in allowed or user == NODE_USER
    return True
