"""Transparent at-rest encryption for data files.

The storage layers (sstable, mutation_log, file_transfer) open every
data file through `open_data_file()`. When the file's path falls under
a registered encryption zone (enabled per data root at server boot —
the analogue of the reference swapping in an encrypted rocksdb Env
under FLAGS_encrypt_data_at_rest), writes go through a seekable
XOR-keystream cipher (security/kms.py) and reads sniff the header:

    [8B magic "PEGSENC1"][16B nonce][8B reserved]   = 32-byte header

Files without the magic are served as plaintext even inside a zone, so
a cluster can turn encryption on and still read its pre-existing data;
every file written after that is encrypted (parity with the reference's
mixed-env migration story, common/fs_utils encrypt-on-rewrite).
"""

from __future__ import annotations

import os
from typing import Dict, Optional

from pegasus_tpu.security.kms import KeyProvider, xor_crypt

MAGIC = b"PEGSENC1"
HEADER = 32

_zones: Dict[str, KeyProvider] = {}


def enable_encryption(data_root: str, provider: KeyProvider) -> None:
    _zones[os.path.abspath(data_root)] = provider


def disable_encryption(data_root: str) -> None:
    _zones.pop(os.path.abspath(data_root), None)


def zone_for(path: str) -> Optional[KeyProvider]:
    if not _zones:  # fast path: feature off, zero overhead
        return None
    p = os.path.abspath(path)
    for root, prov in _zones.items():
        if p.startswith(root + os.sep) or p == root:
            return prov
    return None


class CipherFile:
    """File-like XOR-stream view over an encrypted file.

    Logical offsets exclude the 32-byte header. Supports the exact
    surface the storage layer uses: read/write/seek/tell/truncate/
    flush/fileno/close and context management. Reads are random-access
    (the keystream is seekable). Writes must only ever extend the
    file: rewriting bytes at a previously-written offset would reuse
    that offset's keystream (two-time pad) — crash repair goes through
    repair_truncate(), which rewrites under a fresh nonce instead.
    """

    def __init__(self, f, key: bytes, nonce: bytes) -> None:
        self._f = f
        self._key = key
        self._nonce = nonce

    # -- positioning (logical <-> physical is a fixed +HEADER shift)
    def seek(self, off: int, whence: int = os.SEEK_SET) -> int:
        if whence == os.SEEK_SET:
            return self._f.seek(off + HEADER) - HEADER
        if whence == os.SEEK_END:
            return self._f.seek(off, os.SEEK_END) - HEADER
        return self._f.seek(off, whence) - HEADER

    def tell(self) -> int:
        return self._f.tell() - HEADER

    # -- data
    def read(self, n: int = -1) -> bytes:
        pos = self.tell()
        raw = self._f.read(n)
        return xor_crypt(self._key, self._nonce, pos, raw)

    def write(self, data: bytes) -> int:
        pos = self.tell()
        self._f.write(xor_crypt(self._key, self._nonce, pos, data))
        return len(data)

    def truncate(self, size: Optional[int] = None) -> int:
        if size is None:
            return self._f.truncate() - HEADER
        return self._f.truncate(size + HEADER) - HEADER

    # -- passthrough
    def flush(self) -> None:
        self._f.flush()

    def fileno(self) -> int:
        return self._f.fileno()

    def close(self) -> None:
        self._f.close()

    @property
    def closed(self) -> bool:
        return self._f.closed

    def __enter__(self) -> "CipherFile":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def open_data_file(path: str, mode: str = "rb"):
    """Drop-in replacement for open() on data files.

    Outside any encryption zone this IS open(). Inside a zone:
    - new writes ("wb", "ab" on a missing/empty file) get a fresh
      nonce + header and encrypt;
    - existing files are sniffed — encrypted ones are wrapped,
      legacy plaintext ones pass through untouched.
    """
    prov = zone_for(path)
    if prov is None:
        return open(path, mode)
    key = prov.data_key
    if mode == "wb":
        f = open(path, "wb")
        nonce = os.urandom(16)
        f.write(MAGIC + nonce + b"\0" * (HEADER - len(MAGIC) - 16))
        return CipherFile(f, key, nonce)
    if mode == "ab":
        size = os.path.getsize(path) if os.path.exists(path) else 0
        if size == 0:
            f = open(path, "wb")
            nonce = os.urandom(16)
            f.write(MAGIC + nonce + b"\0" * (HEADER - len(MAGIC) - 16))
            return CipherFile(f, key, nonce)
        nonce = _sniff(path)
        if nonce is None:
            return open(path, mode)  # legacy plaintext log: keep appending
        # "ab" pins every write to EOF regardless of seek, which would
        # desync the position-keyed stream if the header read moved the
        # cursor; r+b positioned at EOF has identical append semantics
        f = open(path, "r+b")
        f.seek(0, os.SEEK_END)
        return CipherFile(f, key, nonce)
    if mode in ("rb", "r+b"):
        nonce = _sniff(path)
        if nonce is None:
            return open(path, mode)
        f = open(path, mode)
        f.seek(HEADER)
        return CipherFile(f, key, nonce)
    raise ValueError(f"unsupported data-file mode {mode!r}")


def _sniff(path: str) -> Optional[bytes]:
    try:
        with open(path, "rb") as f:
            hdr = f.read(HEADER)
    except OSError:
        return None
    if len(hdr) == HEADER and hdr[:len(MAGIC)] == MAGIC:
        return hdr[len(MAGIC):len(MAGIC) + 16]
    return None


def is_encrypted(path: str) -> bool:
    return _sniff(path) is not None


def logical_size(path: str) -> int:
    """Plaintext byte count of a data file (physical minus the cipher
    header when encrypted) — what a reader of open_data_file() will
    actually serve. File-transfer metadata must use THIS, not
    os.path.getsize, or receivers wait for header bytes that the
    decrypting reader never yields."""
    size = os.path.getsize(path)
    return size - HEADER if _sniff(path) is not None else size


def repair_truncate(path: str, valid_end: int) -> None:
    """Crash-repair a framed log: keep logical bytes [0, valid_end).

    Plaintext files are truncated in place. Encrypted files are
    REWRITTEN to a temp file under a fresh nonce and renamed over —
    truncating and then appending at the same logical offsets with the
    original nonce would emit two ciphertexts under one keystream
    position (a two-time pad), letting anyone holding a pre-crash copy
    XOR out the plaintext."""
    if _sniff(path) is None:
        with open(path, "r+b") as f:
            f.truncate(valid_end)
        return
    with open_data_file(path, "rb") as f:
        keep = f.read(valid_end)
    tmp = path + ".repair.tmp"
    with open_data_file(tmp, "wb") as f:
        f.write(keep)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    dir_fd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
    try:
        os.fsync(dir_fd)
    finally:
        os.close(dir_fd)


def copy_data_tree(src_dir: str, dst_dir: str) -> None:
    """Copy a data directory decrypt-at-source / re-encrypt-at-dest.

    A raw byte copy (shutil.copytree) of encrypted files is only valid
    when source and destination share a data key; a shared-fs learn
    copies the PRIMARY's checkpoint into the LEARNER's zone, and each
    server has its own key. Reading through open_data_file and writing
    through it again makes the copy key-correct in every combination
    (plain->plain, plain->encrypted, encrypted->re-encrypted)."""
    os.makedirs(dst_dir, exist_ok=True)
    for base, dirs, files in os.walk(src_dir):
        rel = os.path.relpath(base, src_dir)
        out_base = (dst_dir if rel == os.curdir
                    else os.path.join(dst_dir, rel))
        for d in dirs:
            os.makedirs(os.path.join(out_base, d), exist_ok=True)
        for name in files:
            src = os.path.join(base, name)
            if _sniff(src) is not None and zone_for(src) is None:
                # an encrypted file we hold no key for: copying it (raw
                # OR re-encrypted) can only produce garbage at the
                # destination — fail here with the real cause instead
                raise RuntimeError(
                    f"{src} is encrypted but no key is registered for "
                    "its path; cross-server shared-fs copies need the "
                    "transfer path (which re-encrypts), not a file copy")
            with open_data_file(src, "rb") as fin:
                data = fin.read()
            with open_data_file(os.path.join(out_base, name), "wb") as fout:
                fout.write(data)
                fout.flush()
                os.fsync(fout.fileno())
