"""Background scrubber: paced verification of at-rest SSTable bytes.

Parity: the role RocksDB's `CheckConsistency` + background verification
plays under the reference (and the scrub loops of LSM-OPD/CompassDB's
integrity layers, PAPERS.md): latent disk corruption must be FOUND
before a read trips over it — a secondary serves no client reads, so
without a scrub its flipped bit survives until the replica is promoted
and starts returning garbage. The scrubber walks every hosted store's
runs re-reading raw block bytes against their index CRCs
(`SSTable.verify_block` — no decode, no block-cache pollution) plus a
structural pass (fence ordering, bloom-answers-resident-keys, and
phash-locates-resident-keys: every block's first key must map to
exactly (that block, slot 0) through the perfect-hash index — a
corrupt or stale index would turn into silent NotFound under probe
pruning) per table, a bounded number of blocks per tick so a multi-GB
store never monopolizes the dispatcher.

Compaction awareness: a scrub position is keyed to the store's
`(store_uid, generation)`; any publish (flush / compaction / ingest /
engine swap) restarts that replica's pass — the old runs are unlinked
and the new ones deserve a fresh walk. A tick also skips replicas whose
engine is mid-compaction (`compact_lock` held): the merge is already
re-reading and re-writing every block, and disk bandwidth is better
spent on it.

A corrupt block raises the owner's quarantine callback (the stub wires
`on_corruption` to its detect → quarantine → re-learn loop) and ticks
`scrub_corrupt_blocks` on the node storage entity.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional, Tuple

from pegasus_tpu.utils.errors import StorageCorruptionError
from pegasus_tpu.utils.metrics import METRICS

Gpid = Tuple[int, int]

_SCRUB_CORRUPT = METRICS.entity("storage", "node").counter(
    "scrub_corrupt_blocks")
# one tick per mid-pass restart caused by a publish (flush / compaction
# / ingest changed the run set under the cursor): under PIPELINED
# compaction a single logical compaction bumps the generation more than
# once (freeze-flush, then the publish cut-over), and the restart logic
# must collapse that into ONE restart per publish observation — this
# counter is how the test proves it does
_SCRUB_RESTART = METRICS.entity("storage", "node").counter(
    "scrub_restart_count")


class ReplicaScrubber:
    """One per node; walks the node's replicas round-robin.

    `replicas()` returns the live {gpid -> replica} map each tick (the
    set changes under cures/splits); `on_corruption(gpid, exc)` is the
    quarantine hook. `blocks_per_tick` bounds one tick's IO."""

    def __init__(self, replicas: Callable[[], Dict[Gpid, object]],
                 on_corruption: Callable[[Gpid, Exception], None],
                 blocks_per_tick: int = 256,
                 clock: Optional[Callable[[], float]] = None) -> None:
        self._replicas = replicas
        self._on_corruption = on_corruption
        self.blocks_per_tick = blocks_per_tick
        # minimum quiet time between full passes of one replica: a
        # small store must not be re-walked every tick (disk bandwidth
        # belongs to serving); manual `scrub_now` bypasses this
        self.pass_interval = 10.0
        # how long a departed replica's last result stays reportable
        # before it ages out of the status map
        self.result_ttl = 600.0
        self._clock = clock or time.time
        # gpid -> {store, gen, table_i, block_i, scanned, started}
        self._cursor: Dict[Gpid, dict] = {}
        # rotating start position so one big store's pass cannot starve
        # its neighbors of tick budget forever
        self._rr = 0
        # gpid -> last completed pass result (the shell's `scrub`
        # progress/last-result surface)
        self.results: Dict[Gpid, dict] = {}

    # ---- one paced tick ------------------------------------------------

    def tick(self) -> None:
        reps = self._replicas()
        if not reps:
            return
        budget = self.blocks_per_tick
        order = sorted(reps)
        self._rr = (self._rr + 1) % len(order)
        for gpid in order[self._rr:] + order[:self._rr]:
            if budget <= 0:
                return
            r = reps.get(gpid)
            if r is None:
                continue  # quarantined earlier in this very tick
            budget -= self._advance(gpid, r, budget)
        # drop cursors of replicas no longer hosted; their last results
        # stay visible for a grace window (the shell's `scrub --status`
        # should still show WHY a quarantined replica left) and then
        # age out so a long-lived node's churn cannot grow the map
        # without bound
        now = self._clock()
        for gpid in list(self._cursor):
            if gpid not in reps:
                del self._cursor[gpid]
        for gpid, last in list(self.results.items()):
            if gpid not in reps and \
                    now - last.get("finished", now) > self.result_ttl:
                del self.results[gpid]

    def scrub_now(self, gpid: Gpid, replica) -> dict:
        """One full pass, synchronously (the shell trigger + tests);
        returns the pass result. Detection still routes through the
        quarantine callback."""
        self._cursor.pop(gpid, None)
        while self._advance(gpid, replica, 1_000_000_000,
                            force=True) > 0:
            if gpid not in self._cursor:
                break
        return self.results.get(gpid, {"state": "idle"})

    # ---- internals -----------------------------------------------------

    def _tables_of(self, replica) -> list:
        lsm = replica.server.engine.lsm
        return list(lsm.l0) + list(lsm.l1_runs)

    def _advance(self, gpid: Gpid, replica, budget: int,
                 force: bool = False) -> int:
        """Scrub up to `budget` blocks of one replica; returns blocks
        actually verified."""
        engine = replica.server.engine
        lsm = engine.lsm
        if engine.compact_lock.locked():
            return 0  # the merge owns the disk right now
        cur = self._cursor.get(gpid)
        if cur is None and not force:
            last = self.results.get(gpid)
            if (last is not None and "finished" in last
                    and self._clock() - last["finished"]
                    < self.pass_interval):
                return 0  # pass-interval pacing: recently walked
        if cur is not None and (cur["store"] != lsm.store_uid
                                or cur["gen"] != lsm.generation):
            # the run set changed mid-pass: restart — the old cursor
            # points into unlinked files. ONE restart per observed
            # publish, however many generation bumps the publish's
            # pipeline stages produced while the scrubber was parked
            # on the compact_lock skip (freeze-flush + cut-over is
            # still one logical publish)
            _SCRUB_RESTART.increment()
            cur = None
        if cur is None:
            cur = {"store": lsm.store_uid, "gen": lsm.generation,
                   "table_i": 0, "block_i": 0, "scanned": 0,
                   "started": self._clock(), "structural_done": False}
            self._cursor[gpid] = cur
        tables = self._tables_of(replica)
        done = 0
        try:
            while done < budget:
                if engine.compact_lock.locked():
                    # a compaction started under us: PAUSE, keep the
                    # cursor — if its publish changes the generation
                    # the entry check above restarts exactly once;
                    # if it aborts, the pass resumes where it stopped
                    return done
                if lsm.generation != cur["gen"]:
                    # a publish landed between blocks: stop here with
                    # the stale cursor in place — the next tick's
                    # entry check restarts (and counts) it exactly
                    # once, the same path as a publish observed
                    # between ticks
                    return done
                if cur["table_i"] >= len(tables):
                    # pass complete
                    self.results[gpid] = {
                        "state": "clean",
                        "blocks_scanned": cur["scanned"],
                        "tables": len(tables),
                        "started": cur["started"],
                        "finished": self._clock(),
                    }
                    del self._cursor[gpid]
                    return done
                table = tables[cur["table_i"]]
                if not cur["structural_done"]:
                    table.verify_index_consistency()
                    cur["structural_done"] = True
                if cur["block_i"] >= len(table.blocks):
                    cur["table_i"] += 1
                    cur["block_i"] = 0
                    cur["structural_done"] = False
                    continue
                table.verify_block(cur["block_i"])
                cur["block_i"] += 1
                cur["scanned"] += 1
                done += 1
        except StorageCorruptionError as e:
            _SCRUB_CORRUPT.increment()
            self.results[gpid] = {
                "state": "corrupt",
                "detail": str(e),
                "blocks_scanned": cur["scanned"],
                "started": cur["started"],
                "finished": self._clock(),
            }
            self._cursor.pop(gpid, None)
            self._on_corruption(gpid, e)
            return done + 1
        return done

    def status(self, app_id: Optional[int] = None) -> list:
        """Progress + last result per hosted partition (shell `scrub`)."""
        out = []
        gpids = set(self._cursor) | set(self.results)
        for gpid in sorted(gpids):
            if app_id is not None and gpid[0] != app_id:
                continue
            entry = {"gpid": list(gpid)}
            cur = self._cursor.get(gpid)
            if cur is not None:
                entry["in_progress"] = {
                    "table_i": cur["table_i"], "block_i": cur["block_i"],
                    "blocks_scanned": cur["scanned"],
                    "started": cur["started"],
                }
            last = self.results.get(gpid)
            if last is not None:
                entry["last_result"] = dict(last)
            out.append(entry)
        return out
