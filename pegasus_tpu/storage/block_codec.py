"""Per-block SST compression with direct compute on the encoded form.

The LSM-OPD design point (PAPERS.md): compression must not tax the
vectorized read path, so the encoded layout keeps every PREDICATE
column directly addressable — the batched scan/filter kernels evaluate
TTL masks, partition-hash ownership, and hashkey/sortkey pattern
filters against the encoded representation, and the expensive
materialization (padded key matrix + value heap inflate) is deferred
to row assembly of surviving records.

Codec ``dcz`` (dictionary + columnar + zlib):

    header      fixed 48-byte struct (section geometry + mode bytes)
    expire_ts   uint32[n]   RAW — the per-second TTL mask reads it in
                            place (omitted when every row is TTL-free)
    hash_lo     uint32[n]   RAW — stale-split / ownership checks and
                            scan hash validation need no key decode
    dict_offs   uint32[D+1] hashkey dictionary offsets
    key_len     n x {1,2,4} narrowed ints
    value_len   n x {1,2,4} narrowed ints (offsets rebuild by cumsum)
    hk_idx      n x {2,4}   per-row dictionary slot (sorted keys make
                            equal hashkeys adjacent, so D << n;
                            sentinel = malformed row stored raw)
    flags       uint8[n]    omitted when all zero (L1 blocks carry no
                            tombstones)
    dict bytes  D unique hashkeys, concatenated
    sortkey heap            per-row sortkey bytes, concatenated (the
                            pow2-padded key matrix is NOT stored — the
                            padding and the repeated hashkeys are the
                            bulk of the key-side waste)
    value heap  zstd(level 1) (zlib level 1 when libzstd is absent)
                            when an entropy + sample-compress probe
                            proves the heap compressible, RAW
                            otherwise (see _maybe_deflate: even fast
                            compressors waste work on data they cannot
                            shrink, and the incompressible case must
                            not pay decompress on every cold read; the
                            heap_mode byte records which compressor
                            wrote the heap, so zlib- and zstd-heap
                            blocks serve side by side)

Decoding reproduces the raw block's columns byte-for-byte (zero
padding, dtypes, offsets), so every downstream consumer — predicate
kernels, native page assembly, point probes — sees exactly the block
it would have seen from an uncompressed file. The per-block CRC is
computed over the ON-DISK (encoded) bytes, which keeps the PR 5
scrubber's raw re-read path working unchanged.

Codec ``dcz2`` (the PR 8 follow-on): same family, two column upgrades
on the until-now-raw uint32 predicate columns, stamped per BLOCK via
the header's format byte so one dcz2 FILE may verbatim-carry legacy v1
blocks (compaction copies untouched blocks without transcoding):

    expire_ts   FOR/delta: u32 base (min nonzero) + {u8,u16} per-row
                delta_plus1 (0 keeps meaning "no TTL"); falls back to
                raw u32 when the spread overflows u16, omitted when
                all-zero — exactly the old ets_mode=0 case
    hash_lo     dictionary-indexed: rows sharing a hashkey share its
                crc64 lane, so the column stores one u32 PER DICT SLOT
                plus a row-ordered overflow array for rows whose hash
                is not slot-derivable (malformed keys, empty hashkeys
                — an empty hashkey hashes the per-row SORTKEY region)

Format versioning follows the PR 7 rule: new files stamp codec "dcz2"
in the index (builds without it refuse at open, never misparse);
legacy "dcz" files keep serving; "none" stays bit-for-bit; and a "dcz"
WRITER never emits a v2 block (down-transcoding instead), so a file's
named codec always bounds what is inside it.
"""

from __future__ import annotations

import struct
import zlib
from typing import List, Optional, Tuple

import numpy as np

CODEC_NONE = "none"
CODEC_DCZ = "dcz"
CODEC_DCZ2 = "dcz2"
KNOWN_CODECS = (CODEC_DCZ, CODEC_DCZ2)

# block format versions the dcz-family codecs may contain: a file's
# index-named codec BOUNDS the block versions inside it, so an old
# build that knows only "dcz" can never meet a v2 block it would
# misparse (it refuses "dcz2" files at open)
_CODEC_VERSIONS = {CODEC_DCZ: (1,), CODEC_DCZ2: (1, 2)}


def codec_accepts(codec: str, version: int) -> bool:
    """May a file stamped `codec` contain a block of `version`? The
    verbatim-copy / encoded-subset fast paths gate on this: an
    incompatible block transcodes through decode->re-encode instead."""
    return version in _CODEC_VERSIONS.get(codec, ())


def block_version(buf) -> int:
    """Format version of one encoded block's bytes (header fmt byte;
    pre-dcz2 writers zeroed it, so 0 reads as version 1)."""
    return 2 if buf[46] == 2 else 1


# n, key_width, raw_heap, comp_heap, sk_bytes, dict_n, dict_bytes,
# klen_w, vlen_w, idx_w, flags_mode, ets_mode, heap_mode, fmt, pad
# (fmt was a zeroed pad byte before dcz2 — 0 therefore means v1)
_CBLK_HDR = struct.Struct("<IIQQQIIBBBBBBBx")

_HEAP_RAW = 0
_HEAP_ZLIB = 1
_HEAP_ZSTD = 2
_ZLIB_LEVEL = 1  # compressor speed is on the compaction critical path
_ZSTD_LEVEL = 1


class _Zstd:
    """ctypes binding to the system libzstd (the stdlib has no zstd
    before 3.14 and the container must not gain pip deps). Level-1
    zstd compresses ~6x faster than zlib-1 at a similar ratio — on the
    compaction critical path that difference is the whole game — so
    encode prefers it and falls back to zlib only when the shared
    library is missing. Decode supports both heap modes regardless."""

    _lib = None
    _tried = False

    @classmethod
    def lib(cls):
        if not cls._tried:
            cls._tried = True
            import ctypes

            for name in ("libzstd.so.1", "libzstd.so"):
                try:
                    lib = ctypes.CDLL(name)
                except OSError:
                    continue
                try:
                    lib.ZSTD_compressBound.restype = ctypes.c_size_t
                    lib.ZSTD_compressBound.argtypes = [ctypes.c_size_t]
                    lib.ZSTD_compress.restype = ctypes.c_size_t
                    lib.ZSTD_compress.argtypes = [
                        ctypes.c_void_p, ctypes.c_size_t,
                        ctypes.c_void_p, ctypes.c_size_t, ctypes.c_int]
                    lib.ZSTD_decompress.restype = ctypes.c_size_t
                    lib.ZSTD_decompress.argtypes = [
                        ctypes.c_void_p, ctypes.c_size_t,
                        ctypes.c_void_p, ctypes.c_size_t]
                    lib.ZSTD_isError.restype = ctypes.c_uint
                    lib.ZSTD_isError.argtypes = [ctypes.c_size_t]
                except AttributeError:
                    continue
                cls._lib = lib
                break
        return cls._lib

    @classmethod
    def compress(cls, data: bytes, level: int = _ZSTD_LEVEL):
        lib = cls.lib()
        if lib is None:
            return None
        import ctypes

        bound = lib.ZSTD_compressBound(len(data))
        out = ctypes.create_string_buffer(bound)
        n = lib.ZSTD_compress(out, bound, data, len(data), level)
        if lib.ZSTD_isError(n):
            return None
        return out.raw[:n]

    @classmethod
    def decompress(cls, comp, raw_len: int) -> bytes:
        lib = cls.lib()
        if lib is None:
            raise RuntimeError(
                "block heap is zstd-compressed but libzstd is not "
                "resolvable on this host")
        import ctypes

        comp = bytes(comp)
        out = ctypes.create_string_buffer(raw_len if raw_len else 1)
        n = lib.ZSTD_decompress(out, raw_len, comp, len(comp))
        if lib.ZSTD_isError(n) or n != raw_len:
            raise ValueError("zstd heap decompression failed")
        return out.raw[:raw_len]

# compressor throughput COLLAPSES on the data it cannot shrink
# (measured on this box with zlib-1: 13 MB/s on random bytes, 20 MB/s
# at ratio 0.835 on printable-random — vs 350 MB/s at ratio 0.3 on
# structured payloads and a ~300 MB/s disk it is trying to outrun;
# zstd-1 degrades far less but an incompressible heap stored
# compressed still taxes every cold read with a pointless decompress),
# so the full pass runs only when two cheap probes prove the heap
# genuinely compressible: a byte-histogram entropy estimate on a 16 KB
# sample (near-8-bit heaps store raw, ~40 µs), then a sample compress
# that must clear a 30% gain — the marginal regime between 5% and 30%
# is a net loss on the compaction critical path, where a small byte
# saving loses to just writing them at disk speed.
_PROBE_SAMPLE = 1 << 14
_PROBE_MAX_ENTROPY_BITS = 7.5
_PROBE_MAX_RATIO = 0.70
_KEEP_MAX_RATIO = 0.95


def _compress_heap(data: bytes) -> Tuple[int, bytes]:
    comp = _Zstd.compress(data)
    if comp is not None:
        return _HEAP_ZSTD, comp
    return _HEAP_ZLIB, zlib.compress(data, _ZLIB_LEVEL)


def _maybe_deflate(heap_bytes: bytes) -> Tuple[int, bytes]:
    """(heap_mode, stored bytes) — compression gated by
    compressibility."""
    n = len(heap_bytes)
    if n > _PROBE_SAMPLE:
        a = np.frombuffer(heap_bytes, dtype=np.uint8,
                          count=_PROBE_SAMPLE)
        cnt = np.bincount(a, minlength=256).astype(np.float64)
        p = cnt[cnt > 0] / a.size
        if float(-(p * np.log2(p)).sum()) >= _PROBE_MAX_ENTROPY_BITS:
            return _HEAP_RAW, heap_bytes
        sample = heap_bytes[:_PROBE_SAMPLE]
        if len(_compress_heap(sample)[1]) \
                > len(sample) * _PROBE_MAX_RATIO:
            return _HEAP_RAW, heap_bytes
    elif n == 0:
        return _HEAP_RAW, heap_bytes
    mode, comp = _compress_heap(heap_bytes)
    if len(comp) < n * _KEEP_MAX_RATIO:
        return mode, comp
    return _HEAP_RAW, heap_bytes


def _width_for(maxv: int) -> int:
    if maxv < (1 << 8):
        return 1
    if maxv < (1 << 16):
        return 2
    return 4


_NARROW = {1: np.uint8, 2: np.uint16, 4: np.uint32}


def _ragged_gather(flat: np.ndarray, starts: np.ndarray,
                   lens: np.ndarray) -> np.ndarray:
    """Concatenate flat[starts[i] : starts[i]+lens[i]] for all i in one
    vectorized pass (the per-row loop this replaces is the encode hot
    loop)."""
    total = int(lens.sum())
    if total == 0:
        return np.zeros(0, dtype=np.uint8)
    cum = np.zeros(len(lens) + 1, dtype=np.int64)
    np.cumsum(lens, out=cum[1:])
    pos = (np.repeat(starts - cum[:-1], lens)
           + np.arange(total, dtype=np.int64))
    return flat[pos]


def _ragged_scatter(dst: np.ndarray, dst_starts: np.ndarray,
                    src: np.ndarray, src_starts: np.ndarray,
                    lens: np.ndarray) -> None:
    """dst[dst_starts[i]:+lens[i]] = src[src_starts[i]:+lens[i]]."""
    total = int(lens.sum())
    if total == 0:
        return
    cum = np.zeros(len(lens) + 1, dtype=np.int64)
    np.cumsum(lens, out=cum[1:])
    intra = np.arange(total, dtype=np.int64) - np.repeat(cum[:-1], lens)
    dst[np.repeat(dst_starts, lens) + intra] = \
        src[np.repeat(src_starts, lens) + intra]


def _ets_for_encode(ets: np.ndarray):
    """(ets_mode, [section bytes]) for the v2 FOR/delta expire_ts
    column: mode 0 = all-zero (omitted), 1/2 = u32 base + per-row
    delta_plus1 narrowed to u8/u16 (0 stays 0 — "no TTL"), 4 = raw
    u32 fallback when the nonzero spread overflows u16."""
    if not ets.any():
        return 0, []
    nz = ets[ets != 0]
    base = int(nz.min())
    spread = int(nz.max()) - base + 1
    if spread <= 0xFF:
        w = 1
    elif spread <= 0xFFFF:
        w = 2
    else:
        return 4, [ets.tobytes()]
    d = np.where(ets == 0, 0,
                 ets.astype(np.int64) - base + 1).astype(_NARROW[w])
    return w, [struct.pack("<I", base), d.tobytes()]


def _ets_for_decode(mode: int, raw, pos: int, n: int):
    """Inverse of _ets_for_encode: (expire_ts uint32[n], bytes read)."""
    if mode == 0:
        return np.zeros(n, dtype=np.uint32), 0
    if mode == 4:
        return np.frombuffer(raw, dtype=np.uint32, count=n,
                             offset=pos), 4 * n
    (base,) = struct.unpack_from("<I", raw, pos)
    d = np.frombuffer(raw, dtype=_NARROW[mode], count=n,
                      offset=pos + 4).astype(np.int64)
    ets = np.where(d == 0, 0, base + d - 1).astype(np.uint32)
    return ets, 4 + mode * n


def encode_block(keys: np.ndarray, key_len: np.ndarray, ets: np.ndarray,
                 hash_lo: np.ndarray, flags: np.ndarray,
                 value_offs: np.ndarray, heap,
                 version: int = 1) -> bytes:
    """Raw columnar block -> dcz bytes. `keys` is the zero-padded
    uint8[n, W] matrix exactly as the raw format would store it.
    `version` 1 writes the original dcz layout bit-for-bit; 2 writes
    the dcz2 layout (FOR expire_ts + dictionary-indexed hash_lo)."""
    keys = np.ascontiguousarray(keys, dtype=np.uint8)
    n, width = keys.shape
    key_len = np.asarray(key_len, dtype=np.int32)
    ets = np.asarray(ets, dtype=np.uint32)
    hash_lo = np.asarray(hash_lo, dtype=np.uint32)
    flags = np.asarray(flags, dtype=np.uint8)
    value_offs = np.asarray(value_offs, dtype=np.uint32)
    if int(value_offs[0]) != 0:
        raise ValueError("value_offs must start at 0")
    if isinstance(heap, np.ndarray):
        heap_bytes = np.ascontiguousarray(heap, dtype=np.uint8).tobytes()
    else:
        heap_bytes = bytes(heap)

    kl64 = key_len.astype(np.int64)
    hkl = np.where(
        kl64 >= 2,
        (keys[:, 0].astype(np.int64) << 8) | keys[:, 1].astype(np.int64),
        np.int64(-1))
    normal = (kl64 >= 2) & (hkl >= 0) & (hkl <= kl64 - 2)

    # group adjacent rows sharing one hashkey (keys are sorted, and the
    # 2-byte length header sorts same-length hashkeys together, so equal
    # hashkeys are always adjacent): a row continues its predecessor's
    # group iff both are well-formed, the headers agree, and the first
    # differing byte lies past the hashkey region
    if n > 1:
        diff = keys[1:] != keys[:-1]
        any_diff = diff.any(axis=1)
        first_diff = np.where(any_diff, diff.argmax(axis=1),
                              np.int64(width))
        same_hk = ((hkl[1:] == hkl[:-1])
                   & (first_diff >= 2 + hkl[1:])
                   & normal[1:] & normal[:-1])
    else:
        same_hk = np.zeros(0, dtype=bool)
    new_group = np.ones(n, dtype=bool)
    new_group[1:] = ~same_hk
    gid = np.cumsum(new_group) - 1                  # group id per row
    leaders = np.flatnonzero(new_group)             # leader row per group
    leader_normal = normal[leaders]
    # dictionary slots number the normal-leader groups in order; a
    # normal row always sits in a group whose leader is normal (a
    # malformed predecessor can never chain into same_hk)
    dict_of_group = np.cumsum(leader_normal) - 1
    dict_rows = leaders[leader_normal]
    dict_n = int(dict_rows.size)

    idx_w = 2 if dict_n < 0xFFFF else 4
    sentinel = (1 << (8 * idx_w)) - 1
    hk_idx = np.where(normal, dict_of_group[gid], np.int64(sentinel))

    flat = keys.reshape(-1)
    dict_lens = hkl[dict_rows]
    dict_heap = _ragged_gather(flat, dict_rows * width + 2, dict_lens)
    dict_offs = np.zeros(dict_n + 1, dtype=np.uint32)
    if dict_n:
        dict_offs[1:] = np.cumsum(dict_lens)

    sk_start = np.where(normal, 2 + hkl, np.int64(0))
    sk_len = np.where(normal, kl64 - 2 - hkl, kl64)
    sk_heap = _ragged_gather(flat, np.arange(n, dtype=np.int64) * width
                             + sk_start, sk_len)

    vlens = np.diff(value_offs.astype(np.int64))
    klen_w = _width_for(int(kl64.max()) if n else 0)
    vlen_w = _width_for(int(vlens.max()) if n else 0)
    flags_mode = 1 if flags.any() else 0

    heap_mode, heap_out = _maybe_deflate(heap_bytes)

    if version == 2:
        ets_mode, ets_parts = _ets_for_encode(ets)
        # hash_lo is crc64 of the HASHKEY region, constant across a
        # dictionary group — store one u32 per slot. Rows whose hash
        # is not slot-derivable (malformed keys, and empty hashkeys,
        # whose hash covers the per-row SORTKEY region) append to a
        # row-ordered overflow array the decoder consumes in turn.
        slot_ok = normal & (hkl > 0)
        slot_hash = hash_lo[dict_rows]
        overflow = hash_lo[~slot_ok]
        parts = [_CBLK_HDR.pack(
            n, width, len(heap_bytes), len(heap_out),
            int(sk_len.sum()), dict_n, int(dict_offs[-1]), klen_w,
            vlen_w, idx_w, flags_mode, ets_mode, heap_mode, 2)]
        parts.extend(ets_parts)
        parts.append(dict_offs.tobytes())
        parts.append(key_len.astype(_NARROW[klen_w]).tobytes())
        parts.append(vlens.astype(_NARROW[vlen_w]).tobytes())
        parts.append(hk_idx.astype(_NARROW[idx_w]).tobytes())
        if flags_mode:
            parts.append(flags.tobytes())
        parts.append(slot_hash.tobytes())
        parts.append(overflow.tobytes())
        parts.append(dict_heap.tobytes())
        parts.append(sk_heap.tobytes())
        parts.append(heap_out)
        return b"".join(parts)

    ets_mode = 4 if ets.any() else 0
    parts: List[bytes] = [_CBLK_HDR.pack(
        n, width, len(heap_bytes), len(heap_out), int(sk_len.sum()),
        dict_n, int(dict_offs[-1]), klen_w, vlen_w, idx_w, flags_mode,
        ets_mode, heap_mode, 0)]
    if ets_mode:
        parts.append(ets.tobytes())
    parts.append(hash_lo.tobytes())
    parts.append(dict_offs.tobytes())
    parts.append(key_len.astype(_NARROW[klen_w]).tobytes())
    parts.append(vlens.astype(_NARROW[vlen_w]).tobytes())
    parts.append(hk_idx.astype(_NARROW[idx_w]).tobytes())
    if flags_mode:
        parts.append(flags.tobytes())
    parts.append(dict_heap.tobytes())
    parts.append(sk_heap.tobytes())
    parts.append(heap_out)
    return b"".join(parts)


def raw_block_size(n: int, width: int, heap_len: int) -> int:
    """On-disk size the RAW format would use for the same block — the
    'logical bytes' side of the compression-ratio accounting."""
    # _BLOCK_HDR(16) + keys + key_len + ets + hash_lo + flags + offs
    return 16 + n * width + 4 * n + 4 * n + 4 * n + n + 4 * (n + 1) \
        + heap_len


class EncodedBlock:
    """Parsed (NOT decoded) dcz block: every predicate column is a
    zero-copy view over the on-disk bytes; the key matrix and value
    heap materialize only on demand."""

    __slots__ = ("raw", "n", "key_width", "key_len", "expire_ts",
                 "hash_lo", "flags", "hk_idx", "dict_offs", "dict_heap",
                 "sk_heap", "sk_offs", "hk_len", "value_offs",
                 "_heap_comp", "heap_mode", "raw_heap_len",
                 "has_malformed", "_sentinel", "version")

    @property
    def count(self) -> int:
        return self.n

    @staticmethod
    def parse(raw) -> "EncodedBlock":
        self = EncodedBlock()
        self.raw = raw
        buf = np.frombuffer(raw, dtype=np.uint8)
        (n, width, raw_heap, comp_heap, sk_bytes, dict_n, dict_bytes,
         klen_w, vlen_w, idx_w, flags_mode, ets_mode, heap_mode,
         fmt) = _CBLK_HDR.unpack_from(raw, 0)
        self.version = 2 if fmt == 2 else 1
        self.n, self.key_width = n, width
        self.raw_heap_len = raw_heap
        self.heap_mode = heap_mode
        self._sentinel = (1 << (8 * idx_w)) - 1
        pos = _CBLK_HDR.size
        if self.version == 2:
            self.expire_ts, adv = _ets_for_decode(ets_mode, raw, pos, n)
            pos += adv
        elif ets_mode:
            self.expire_ts = np.frombuffer(raw, dtype=np.uint32,
                                           count=n, offset=pos)
            pos += 4 * n
        else:
            self.expire_ts = np.zeros(n, dtype=np.uint32)
        if self.version == 1:
            self.hash_lo = np.frombuffer(raw, dtype=np.uint32, count=n,
                                         offset=pos)
            pos += 4 * n
        self.dict_offs = np.frombuffer(raw, dtype=np.uint32,
                                       count=dict_n + 1, offset=pos)
        pos += 4 * (dict_n + 1)
        self.key_len = np.frombuffer(
            raw, dtype=_NARROW[klen_w], count=n,
            offset=pos).astype(np.int32)
        pos += klen_w * n
        vlens = np.frombuffer(raw, dtype=_NARROW[vlen_w], count=n,
                              offset=pos)
        pos += vlen_w * n
        offs = np.zeros(n + 1, dtype=np.uint32)
        if n:
            offs[1:] = np.cumsum(vlens, dtype=np.int64).astype(np.uint32)
        self.value_offs = offs
        self.hk_idx = np.frombuffer(raw, dtype=_NARROW[idx_w], count=n,
                                    offset=pos).astype(np.int64)
        pos += idx_w * n
        if flags_mode:
            self.flags = np.frombuffer(raw, dtype=np.uint8, count=n,
                                       offset=pos)
            pos += n
        else:
            self.flags = np.zeros(n, dtype=np.uint8)

        normal = self.hk_idx != self._sentinel
        self.has_malformed = bool((~normal).any())
        do64 = self.dict_offs.astype(np.int64)
        hk_len = np.zeros(n, dtype=np.int64)
        ni = self.hk_idx[normal]
        hk_len[normal] = do64[ni + 1] - do64[ni]
        self.hk_len = hk_len

        if self.version == 2:
            # dictionary-indexed hash column: one u32 per slot, plus a
            # row-ordered overflow for rows whose hash is not
            # slot-derivable (sentinel / empty hashkey — the hash then
            # covers the per-row sortkey region, unique per row)
            slot_ok = normal & (hk_len > 0)
            n_over = n - int(slot_ok.sum())
            slot_hash = np.frombuffer(raw, dtype=np.uint32,
                                      count=dict_n, offset=pos)
            pos += 4 * dict_n
            overflow = np.frombuffer(raw, dtype=np.uint32,
                                     count=n_over, offset=pos)
            pos += 4 * n_over
            hash_lo = np.empty(n, dtype=np.uint32)
            hash_lo[slot_ok] = slot_hash[self.hk_idx[slot_ok]]
            hash_lo[~slot_ok] = overflow
            self.hash_lo = hash_lo

        self.dict_heap = np.frombuffer(raw, dtype=np.uint8,
                                       count=dict_bytes, offset=pos)
        pos += dict_bytes
        self.sk_heap = np.frombuffer(raw, dtype=np.uint8,
                                     count=sk_bytes, offset=pos)
        pos += sk_bytes
        self._heap_comp = buf[pos:pos + comp_heap]

        kl64 = self.key_len.astype(np.int64)
        sk_len = np.where(normal, kl64 - 2 - hk_len, kl64)
        so = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(sk_len, out=so[1:])
        self.sk_offs = so
        return self

    # ---- direct compute ------------------------------------------------

    def key_at(self, i: int) -> bytes:
        """One key materialized from the dictionary + sortkey heap —
        the bisect/fence primitive, no block decode."""
        sk = self.sk_heap[self.sk_offs[i]:self.sk_offs[i + 1]].tobytes()
        if int(self.hk_idx[i]) == self._sentinel:
            return sk
        d = int(self.hk_idx[i])
        hk = self.dict_heap[
            self.dict_offs[d]:self.dict_offs[d + 1]].tobytes()
        return struct.pack(">H", len(hk)) + hk + sk

    def dict_entries(self) -> List[bytes]:
        """The block's unique hashkeys (pattern filters evaluate once
        per entry instead of once per row)."""
        do = self.dict_offs
        return [self.dict_heap[do[d]:do[d + 1]].tobytes()
                for d in range(len(do) - 1)]

    # ---- materialization ----------------------------------------------

    def key_matrix(self) -> np.ndarray:
        """Rebuild the zero-padded uint8[n, W] key matrix (native
        kernel when available) WITHOUT touching the value heap — bloom
        builds and key-only paths stay inflate-free."""
        from pegasus_tpu import native

        n, width = self.n, self.key_width
        out = np.zeros((n, width), dtype=np.uint8)
        if n == 0:
            return out
        fn = native.cblock_decode_keys_fn()
        idx32 = np.ascontiguousarray(
            np.where(self.hk_idx == self._sentinel,
                     np.int64(0xFFFFFFFF), self.hk_idx)
            .astype(np.uint32))
        if fn is not None:
            fn(np.ascontiguousarray(self.dict_heap),
               np.ascontiguousarray(self.dict_offs), idx32,
               np.ascontiguousarray(self.sk_heap),
               np.ascontiguousarray(self.sk_offs),
               np.ascontiguousarray(self.key_len), n, width, out)
            return out
        # numpy fallback: two ragged scatters + vectorized headers
        flat = out.reshape(-1)
        rows = np.arange(n, dtype=np.int64)
        normal = self.hk_idx != self._sentinel
        hk_len = self.hk_len
        nrm = np.flatnonzero(normal)
        if nrm.size:
            hl = hk_len[nrm]
            out[nrm, 0] = (hl >> 8).astype(np.uint8)
            out[nrm, 1] = (hl & 0xFF).astype(np.uint8)
            _ragged_scatter(flat, nrm * width + 2, self.dict_heap,
                            self.dict_offs.astype(np.int64)[
                                self.hk_idx[nrm]], hl)
        sk_start = np.where(normal, 2 + hk_len, np.int64(0))
        sk_len = self.sk_offs[1:] - self.sk_offs[:-1]
        _ragged_scatter(flat, rows * width + sk_start, self.sk_heap,
                        self.sk_offs[:-1], sk_len)
        return out

    def inflate_heap(self) -> np.ndarray:
        if self.heap_mode == _HEAP_ZLIB:
            return np.frombuffer(zlib.decompress(self._heap_comp),
                                 dtype=np.uint8)
        if self.heap_mode == _HEAP_ZSTD:
            return np.frombuffer(
                _Zstd.decompress(self._heap_comp, self.raw_heap_len),
                dtype=np.uint8)
        return self._heap_comp

    def decode(self):
        """Full materialization to the standard columnar Block — the
        value heap stays a lazy thunk until a survivor's bytes are
        actually read."""
        from pegasus_tpu.storage.sstable import Block

        return Block(self.key_matrix(), self.key_len, self.expire_ts,
                     self.hash_lo, self.flags, self.value_offs,
                     self._heap_comp if self.heap_mode == _HEAP_RAW
                     else self.inflate_heap)

    def mem_bytes(self) -> int:
        """Resident-byte estimate of the DECODED block (cache
        accounting: a decoded compressed block is real allocation, not
        an mmap view; the +64/row covers the lazily materialized
        key_list / probe table a resident block grows)."""
        n = self.n
        return (n * (self.key_width + 64) + 13 * n
                + self.raw_heap_len + 512)


# ---- wire-payload compression (shared with cross-cluster duplication) ----
#
# The same zstd-1/zlib-1 machinery the block value heap uses, exposed for
# RPC payload blobs: duplication ships batched mutation envelopes across
# the WAN and must not pay per-envelope codec plumbing of its own. The
# compressibility probe gates exactly like the heap path — an
# incompressible envelope ships raw and never taxes the follower with a
# pointless decompress.

PAYLOAD_RAW = _HEAP_RAW
PAYLOAD_ZLIB = _HEAP_ZLIB
PAYLOAD_ZSTD = _HEAP_ZSTD


def deflate_payload(data: bytes) -> Tuple[int, bytes]:
    """(mode, stored bytes) for a wire payload blob."""
    return _maybe_deflate(data)


def inflate_payload(mode: int, stored, raw_len: int) -> bytes:
    """Inverse of deflate_payload; both compressors decode forever."""
    if mode == _HEAP_RAW:
        return bytes(stored)
    if mode == _HEAP_ZLIB:
        out = zlib.decompress(bytes(stored))
    elif mode == _HEAP_ZSTD:
        out = _Zstd.decompress(stored, raw_len)
    else:
        raise ValueError(f"unknown payload compression mode {mode}")
    if len(out) != raw_len:
        raise ValueError("payload length mismatch after inflate")
    return out
