"""Background-compaction governor: the node half of the cluster-level
background-I/O scheduler.

RESYSTANCE's observation (PAPERS.md) is that uncontrolled background
bandwidth — not slow compaction — is what wrecks foreground p99: a
compactor running at disk speed steals exactly the IOPS the serving
path needs at the worst moment. The governor closes that loop on each
node:

- every byte the compaction pipeline reads passes through one
  process-wide token bucket (`acquire`), so background disk bandwidth
  has a single knob;
- the knob is driven by the PR 2 foreground-pressure counters
  (`deadline_expired_count` + `read_shed_count` on the rpc dispatch
  entity) with AIMD feedback: any growth since the last look halves
  the allowance (engaging a cap at half the measured recent rate when
  previously uncapped), quiet intervals recover it multiplicatively
  until the cap disengages — compaction always keeps the configured
  floor, so it makes forward progress even on a shedding node (a
  stalled compaction eventually hurts reads MORE via deep L0);
- the cluster half (meta/compaction_scheduler.CompactionCoordinator)
  staggers which nodes may run HEAVY (env-triggered manual)
  compactions concurrently: nodes report demand on the config-sync
  channel, meta replies with a leased grant, and an ungranted node
  simply defers its trigger to the next config-sync delivery —
  blocking nothing, fencing nothing, and degrading to "everyone may
  run" whenever no coordinator answers (standalone engines, tests,
  meta down: availability beats stagger).

Metrics (node storage entity): `compaction_bytes_per_s` (gauge, paced
read rate), `compact_throttle_mbps` (gauge, 0 = uncapped),
`compact_backoff_count`, `compact_throttle_stall_ms`,
`compact_defer_count` (heavy compactions deferred ungranted).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from pegasus_tpu.utils.flags import FLAGS, define_flag
from pegasus_tpu.utils.metrics import METRICS

define_flag("pegasus.storage", "compact_max_mbps", 0,
            "hard background-compaction read-bandwidth cap in MB/s; "
            "0 = uncapped until foreground pressure engages the AIMD "
            "backoff", mutable=True)
define_flag("pegasus.storage", "compact_min_mbps", 32,
            "floor the pressure backoff never throttles below — "
            "background compaction must keep making forward progress "
            "(a stalled compaction eventually hurts reads more than "
            "the bandwidth it frees)", mutable=True)
define_flag("pegasus.storage", "compact_feedback_interval_s", 1.0,
            "seconds between foreground-pressure samples driving the "
            "AIMD rate adaptation", mutable=True)
define_flag("pegasus.storage", "compact_grant_lease_s", 30.0,
            "seconds a meta-issued heavy-compaction grant stays valid "
            "without renewal (config-sync renews it every tick; a dead "
            "meta therefore releases the cluster stagger rather than "
            "wedging compaction)", mutable=True)


def _default_pressure() -> int:
    ent = METRICS.entity("rpc", "dispatch", {})
    return (ent.counter("deadline_expired_count").value()
            + ent.counter("read_shed_count").value())


class CompactionGovernor:
    """One per process (module singleton GOVERNOR); engines share it
    the way replicas share the node row cache."""

    # multiplicative recovery per quiet feedback interval, and the
    # throttle level (relative to the engage point) at which an
    # AIMD-engaged cap disengages back to uncapped
    RECOVER_FACTOR = 1.5
    UNCAP_FACTOR = 2.0

    def __init__(self,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 pressure_source: Callable[[], int] = _default_pressure,
                 ) -> None:
        self._clock = clock
        self._sleep = sleep
        self._pressure = pressure_source
        self._lock = threading.Lock()
        # throttle: MB/s currently enforced; 0 = uncapped. AIMD state
        # distinguishes an OPERATOR cap (compact_max_mbps, permanent)
        # from a PRESSURE-engaged cap (recovers to uncapped)
        self._throttle_mbps = 0.0
        self._engaged_at_mbps = 0.0  # rate when pressure first engaged
        self._tokens = 0.0
        self._tok_t = self._clock()
        self._pressure_last: Optional[int] = None
        self._feedback_t = self._clock()
        # measured recent read rate (1s windows -> gauge)
        self._win_t = self._clock()
        self._win_bytes = 0
        self._rate_bps = 0.0
        # heavy-compaction demand + cluster grant lease
        self.heavy_running = 0
        self._heavy_waiting = False
        self._grant: Optional[tuple] = None  # (granted, expires_at)
        ent = METRICS.entity("storage", "node")
        self._g_rate = ent.gauge("compaction_bytes_per_s")
        self._g_throttle = ent.gauge("compact_throttle_mbps")
        self._c_backoff = ent.counter("compact_backoff_count")
        self._c_stall_ms = ent.counter("compact_throttle_stall_ms")
        self._c_defer = ent.counter("compact_defer_count")

    # ---- pacing (called by the pipeline's read stage) ------------------

    def acquire(self, nbytes: int) -> None:
        """Account `nbytes` of background compaction IO, sleeping as
        needed to hold the current throttle. Uncapped mode costs two
        clock reads."""
        now = self._clock()
        sleep_s = 0.0
        with self._lock:
            self._feedback_locked(now)
            # rate window for the gauge
            self._win_bytes += nbytes
            dt = now - self._win_t
            if dt >= 1.0:
                self._rate_bps = self._win_bytes / dt
                self._g_rate.set(self._rate_bps)
                self._win_t = now
                self._win_bytes = 0
            rate = self._throttle_mbps
            if rate > 0:
                bps = rate * 1e6
                # token bucket with a 250ms burst allowance; debt is
                # allowed (a block is atomic) and paid off by sleeping
                self._tokens = min(self._tokens + (now - self._tok_t)
                                   * bps, bps * 0.25)
                self._tok_t = now
                self._tokens -= nbytes
                if self._tokens < 0:
                    sleep_s = -self._tokens / bps
                    self._tokens = 0.0
        if sleep_s > 0:
            self._c_stall_ms.increment(int(sleep_s * 1000))
            # a traced request stalled behind the governor (e.g. an
            # ingest riding the compaction pipeline) records WHERE the
            # time went; one attr check when untraced
            from pegasus_tpu.utils.tracing import annotate

            annotate("governor_stall")
            self._sleep(sleep_s)

    def _feedback_locked(self, now: float) -> None:
        interval = float(FLAGS.get("pegasus.storage",
                                   "compact_feedback_interval_s"))
        if now - self._feedback_t < interval:
            return
        self._feedback_t = now
        try:
            p = self._pressure()
        except Exception:  # noqa: BLE001 - a broken source never throttles
            return
        prev, self._pressure_last = self._pressure_last, p
        max_mbps = float(FLAGS.get("pegasus.storage",
                                   "compact_max_mbps"))
        min_mbps = float(FLAGS.get("pegasus.storage",
                                   "compact_min_mbps"))
        if self._throttle_mbps == 0 and max_mbps > 0:
            self._throttle_mbps = max_mbps  # operator cap always on
        if prev is None:
            return
        if p > prev:
            # foreground is shedding / expiring deadlines: halve the
            # allowance (engage a cap at half the measured recent rate
            # when previously uncapped)
            cur = self._throttle_mbps
            if cur == 0:
                cur = max(self._rate_bps / 1e6, min_mbps * 2)
                self._engaged_at_mbps = cur
            self._throttle_mbps = max(cur / 2, min_mbps)
            self._c_backoff.increment()
            self._g_throttle.set(self._throttle_mbps)
            return
        # quiet interval: multiplicative recovery toward the operator
        # cap, or toward disengaging a pressure-engaged cap
        cur = self._throttle_mbps
        if cur == 0:
            return
        cur *= self.RECOVER_FACTOR
        if max_mbps > 0:
            self._throttle_mbps = min(cur, max_mbps)
        elif self._engaged_at_mbps > 0 and \
                cur >= self._engaged_at_mbps * self.UNCAP_FACTOR:
            self._throttle_mbps = 0.0  # fully recovered: uncap
            self._engaged_at_mbps = 0.0
        else:
            self._throttle_mbps = cur
        self._g_throttle.set(self._throttle_mbps)

    def poke(self) -> None:
        """Run a feedback step if the interval elapsed (timer hook for
        nodes where no compaction is currently paying `acquire`)."""
        with self._lock:
            self._feedback_locked(self._clock())

    # ---- cluster stagger (grants ride config-sync) ---------------------

    def heavy_allowed(self) -> bool:
        """May an env-triggered (heavy) compaction start NOW? True
        when no coordinator has ever answered (standalone / tests /
        meta down — availability over stagger) or the lease is live
        and granted; an expired lease fails OPEN for the same reason."""
        g = self._grant
        if g is None:
            return True
        granted, expires = g
        if self._clock() > expires:
            return True
        return granted

    def set_cluster_grant(self, granted: bool) -> None:
        lease = float(FLAGS.get("pegasus.storage",
                                "compact_grant_lease_s"))
        self._grant = (bool(granted), self._clock() + lease)

    def note_deferred(self) -> None:
        """An env trigger found heavy_allowed() False and deferred to
        the next config-sync delivery: record the demand so the node's
        report asks the coordinator for a slot."""
        self._heavy_waiting = True
        self._c_defer.increment()

    def begin_heavy(self) -> None:
        self._heavy_waiting = False
        with self._lock:
            self.heavy_running += 1

    def end_heavy(self) -> None:
        with self._lock:
            self.heavy_running = max(0, self.heavy_running - 1)

    # ---- observability --------------------------------------------------

    def report(self) -> dict:
        """The node's compaction block in the config-sync report."""
        return {
            "running": self.heavy_running,
            "waiting": bool(self._heavy_waiting),
            "bytes_per_s": int(self._rate_bps),
        }

    def status(self) -> dict:
        g = self._grant
        return {
            "throttle_mbps": round(self._throttle_mbps, 1),
            "bytes_per_s": int(self._rate_bps),
            "heavy_running": self.heavy_running,
            "heavy_waiting": bool(self._heavy_waiting),
            "grant": (None if g is None else {
                "granted": g[0],
                "lease_remaining_s": round(g[1] - self._clock(), 1),
            }),
            "backoff_count": self._c_backoff.value(),
            "defer_count": self._c_defer.value(),
        }


GOVERNOR = CompactionGovernor()
