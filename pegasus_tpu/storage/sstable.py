"""Columnar SSTable — TPU-friendly sorted runs on disk.

Role parity: RocksDB SST files in the reference. The layout difference IS
the design: instead of row-oriented key/value entries, each block stores

    keys        uint8[count, key_width]  (padded rows, width bucketed pow2)
    key_len     int32[count]
    expire_ts   uint32[count]            (decoded from the value header)
    hash_lo     uint32[count]            (low lane of crc64(pegasus_key_hash),
                                          precomputed at write time so the
                                          scan path validates partition
                                          ownership with ONE compare instead
                                          of a per-byte crc loop on device)
    flags       uint8[count]             (bit0 = tombstone)
    value_offs  uint32[count+1]
    value_heap  bytes                    (full pegasus-encoded values)

so a scan or compaction hands `keys/key_len/expire_ts` straight to the
device predicate kernels (ops/record_block.block_from_columns) with zero
per-record host decoding — the reference instead re-parses every key/value
in scalar C++ per record (src/server/pegasus_server_impl.cpp:643).

File layout:  magic | block* | index(JSON) | footer.
The JSON index carries per-block offsets + first/last keys and a `meta`
dict (data_version, last_flushed_decree, ...) — the meta-column-family
analogue (src/base/meta_store.h:41).
"""

from __future__ import annotations

import bisect
import json
import os
import struct
from dataclasses import dataclass
from typing import Iterator, List, Optional, Tuple

import numpy as np

from pegasus_tpu.storage.vfs import fsync_dir, fsync_file, open_data_file

from pegasus_tpu.base.crc import crc32, crc64, crc64_batch, crc64_rows
from pegasus_tpu.ops.record_block import next_bucket
from pegasus_tpu.storage.block_codec import (
    CODEC_DCZ2,
    CODEC_NONE,
    KNOWN_CODECS,
    EncodedBlock,
    block_version,
    codec_accepts,
    encode_block,
    raw_block_size,
)
from pegasus_tpu.storage.bloom import (
    BloomFilter,
    bloom_build_bits,
    bloom_probe_enabled,
)
from pegasus_tpu.storage.phash import (
    KNOWN_PHASH_VERSIONS,
    PHASH_BUILD_FAIL,
    PHASH_HIT,
    PHASH_USEFUL,
    PHashIndex,
    phash_build_enabled,
    phash_probe_enabled,
)
from pegasus_tpu.utils.errors import StorageCorruptionError
from pegasus_tpu.utils.flags import FLAGS, define_flag
from pegasus_tpu.utils.metrics import METRICS

define_flag("pegasus.storage", "block_crc", True,
            "write a crc32 per data block into new SST files and "
            "verify it on every block decode (cache misses only — "
            "cached hits already paid); files written without block "
            "CRCs keep serving unverified", mutable=True)

define_flag("pegasus.storage", "block_codec", "dcz2",
            "per-block compression codec stamped into new SST files "
            "at every writer finish site (flush / merge-compact / "
            "bulk-compact / ingest): 'dcz2' = dictionary-coded hashkey "
            "column + packed sortkeys + compressed value heap (zstd-1, "
            "zlib-1 fallback) + FOR/delta expire_ts + dict-indexed "
            "hash_lo, with direct compute on the encoded form; 'dcz' = "
            "the PR 7 layout (raw uint32 predicate columns); 'none' = "
            "the legacy raw columnar layout, bit-for-bit. Files "
            "written before this flag existed (or with an unknown "
            "codec) keep serving / are refused at open respectively",
            mutable=True)

define_flag("pegasus.storage", "block_cache_bytes", 33_554_432,
            "per-table decoded-block cache budget in bytes (LRU). "
            "Replaces the old fixed 256-block count cap: compressed "
            "blocks decode into real allocations of wildly varying "
            "size, so only a byte budget bounds memory", mutable=True)


def block_crc_enabled() -> bool:
    return bool(FLAGS.get("pegasus.storage", "block_crc"))


def block_codec() -> str:
    codec = str(FLAGS.get("pegasus.storage", "block_codec"))
    if codec != CODEC_NONE and codec not in KNOWN_CODECS:
        raise ValueError(f"unknown block_codec {codec!r}")
    return codec


def block_cache_budget() -> int:
    return int(FLAGS.get("pegasus.storage", "block_cache_bytes"))


# Block checksums use zlib's slice-by-8 CRC-32 (~1 GB/s) rather than
# the repo's table-loop CRC-32C (~235 MB/s): the block CRC is a private
# file-format field with no wire-parity constraint — unlike the routing
# crc64 / framing crc32, which stay bit-compatible with the reference —
# and it sits on every cold block decode, where a 4x cheaper check is
# the difference between "noise" and a measurable read regression
# (rocksdb likewise offers kxxHash behind the same per-block slot).
from zlib import crc32 as _block_crc32  # noqa: E402

# node-wide storage observability (parity: the rocksdb block-cache /
# filter tickers the reference exports per server): relaxed counters —
# these tick once per block read / filter probe, the hottest loops in
# the process, so they trade perfect cross-thread accuracy for zero
# lock traffic
_STORAGE_METRICS = METRICS.entity("storage", "node")
_BLOCK_CACHE_HIT = _STORAGE_METRICS.relaxed_counter("block_cache_hit")
_BLOCK_CACHE_MISS = _STORAGE_METRICS.relaxed_counter("block_cache_miss")
_BLOOM_USEFUL = _STORAGE_METRICS.relaxed_counter("bloom_useful_count")
# codec observability: how often the read path pays a full decode of a
# compressed block, and how many bytes the byte-capped cache evicts
_COMPRESSED_DECODE = _STORAGE_METRICS.relaxed_counter(
    "compressed_block_decode_count")
_BLOCK_EVICT_BYTES = _STORAGE_METRICS.relaxed_counter(
    "block_cache_evict_bytes")

from pegasus_tpu.utils.tracing import annotate as _trace_annotate  # noqa: E402
from pegasus_tpu.utils.perf_context import current as _perf_current  # noqa: E402

MAGIC = b"PGT2"
MAGIC_V1 = b"PGT1"  # pre-hash_lo format, still readable
FOOTER = struct.Struct("<QII4s")  # index_offset, index_size, index_crc, magic
_BLOCK_HDR = struct.Struct("<IIQ")  # count, key_width, value_heap_size

BLOCK_CAPACITY = 1024

FLAG_TOMBSTONE = 1


@dataclass
class BlockMeta:
    offset: int
    size: int
    count: int
    key_width: int
    first_key: bytes
    last_key: bytes
    # crc32 of the block's on-disk bytes (header + columns + heap);
    # None for files written before the block-checksum layer — those
    # keep serving unverified (parity: rocksdb's per-block checksum,
    # which the reference trusts for every data block read)
    crc: Optional[int] = None


class Block:
    """A decoded columnar block; arrays are views over the file bytes\n    (plus, for blocks that prove hot, one lazily materialized Python\n    key list — see key_list()).

    Blocks decoded from COMPRESSED files may carry their value heap as
    a zero-arg thunk: the heap decompression runs on first value access, so
    key-only work (point probes, bloom builds, fence walks, no-value
    scans) over a compressed block never pays the heap decode —
    materialization is deferred to the rows that actually serve."""

    __slots__ = ("keys", "key_len", "expire_ts", "hash_lo", "flags",
                 "value_offs", "_vh", "_key_list", "_gets",
                 "_nat", "_cmp", "_probe")

    def __init__(self, keys, key_len, expire_ts, hash_lo, flags, value_offs,
                 value_heap):
        self._key_list = None
        self._gets = 0
        self._probe = None  # point-probe entry table (page.probe_nat)
        self.keys = keys              # uint8[N, W]
        self.key_len = key_len        # int32[N]
        self.expire_ts = expire_ts    # uint32[N]
        self.hash_lo = hash_lo        # uint32[N]
        self.flags = flags            # uint8[N]
        self.value_offs = value_offs  # uint32[N+1]
        self._vh = value_heap         # uint8[heap] view, or lazy thunk

    @property
    def value_heap(self):
        vh = self._vh
        if callable(vh):
            vh = self._vh = vh()
        return vh

    @property
    def count(self) -> int:
        return self.keys.shape[0]

    def key_at(self, i: int) -> bytes:
        return self.keys[i, :self.key_len[i]].tobytes()

    def alive_mask(self, now: int):
        """bool[count] TTL-alive mask, cached per `now` second — every
        batch in the same second reuses it (TTL validity granularity is
        one second)."""
        cached = getattr(self, "_cmp", None)
        if cached is not None and cached[0] == now:
            return cached[1]
        from pegasus_tpu.ops.predicates import host_alive_mask

        mask = host_alive_mask(self.expire_ts, now)
        self._cmp = (now, mask)
        return mask

    def key_list(self) -> list:
        """All keys as a sorted Python list, materialized at most once
        per cached block (trades ~key bytes of heap for slice-free
        bisects — worth it only on blocks that are read repeatedly, so
        callers on one-shot paths should not force it)."""
        kl = self._key_list
        if kl is None:
            keys, lens = self.keys, self.key_len
            kl = [keys[i, :lens[i]].tobytes()
                  for i in range(keys.shape[0])]
            self._key_list = kl
        return kl

    def value_at(self, i: int) -> bytes:
        return self.value_heap[
            self.value_offs[i]:self.value_offs[i + 1]].tobytes()

    def is_tombstone(self, i: int) -> bool:
        return bool(self.flags[i] & FLAG_TOMBSTONE)


class SSTableWriter:
    """Writes a sorted record stream into a columnar SST.

    `async_io=True` moves file writes onto a background thread (bounded
    queue): the caller's (single) core keeps gathering/evaluating while
    the kernel drains the write stream — the IO half of the compaction
    double-buffering. Ordering per writer is preserved (one thread, one
    FIFO); finish() joins the queue before writing the index, so the
    durability contract (data before index before rename) is unchanged."""

    def __init__(self, path: str, block_capacity: int = BLOCK_CAPACITY,
                 meta: Optional[dict] = None,
                 async_io: bool = False) -> None:
        self.path = path
        self._block_capacity = block_capacity
        self._meta = dict(meta or {})
        self._f = open_data_file(path + ".tmp", "wb")
        self._blocks: List[BlockMeta] = []
        self._pending: List[Tuple[bytes, bytes, int, int]] = []
        self._last_key: Optional[bytes] = None
        self._count = 0
        self._offset = 0  # logical file position (writes may be queued)
        self._io_q = None
        self._io_thread = None
        self._io_err: List[BaseException] = []
        # SIDECAR structures (bloom filter + perfect-hash index) both
        # consume the same full-key crc64 hash columns, accumulated
        # per block by ONE shared helper (_sidecar_note) at every add
        # path — flush, merge-compact, bulk-compact and ingest all
        # route through these four adds, so the accumulation cannot
        # drift across writer-finish sites. Both build knobs are
        # latched HERE so a mutable flag flip mid-write cannot tear
        # one table's sidecars
        self._bloom_bits_per_key = bloom_build_bits()
        self.bloom_enabled = self._bloom_bits_per_key > 0
        self.phash_enabled = phash_build_enabled()
        self.sidecar_hashes = self.bloom_enabled or self.phash_enabled
        # block-checksum latch, same reasoning: one table is either
        # fully checksummed or fully legacy, never mixed
        self._block_crc = block_crc_enabled()
        # codec latch: one file is wholly one codec (the index names it
        # once); a mutable flag flip mid-write cannot tear a table
        self.codec = block_codec()
        # block format version this writer EMITS; the file may still
        # verbatim-carry older versions its codec accepts
        self.codec_version = 2 if self.codec == CODEC_DCZ2 else 1
        self._codec_raw_bytes = 0     # logical (raw-format) bytes
        self._codec_stored_bytes = 0  # bytes actually written
        self._key_hashes: List[np.ndarray] = []
        if async_io:
            import queue
            import threading

            self._io_q = queue.Queue(maxsize=8)
            self._io_thread = threading.Thread(
                target=self._io_loop, name="sst-io", daemon=True)
            self._io_thread.start()
        self._write(MAGIC)

    def _io_loop(self) -> None:
        while True:
            buf = self._io_q.get()
            if buf is None:
                return
            try:
                if not self._io_err:
                    self._f.write(buf)
            except BaseException as e:  # noqa: BLE001 - surfaced at join
                self._io_err.append(e)

    def _write(self, buf) -> None:
        self._offset += len(buf)
        if self._io_q is not None:
            self._io_q.put(buf)
        else:
            self._f.write(buf)

    def _join_io(self) -> None:
        if self._io_thread is not None:
            self._io_q.put(None)
            self._io_thread.join()
            self._io_thread = None
            if self._io_err:
                raise self._io_err[0]

    def _sidecar_note(self, keys: np.ndarray, key_len: np.ndarray,
                      hashes: Optional[np.ndarray] = None) -> None:
        """Record one block's full-key crc64 column for the sidecar
        structures built at finish() (bloom + phash share the ONE
        vectorized hash pass). `hashes` lets callers that already
        derived the column (the native subset kernel) skip the
        crc64_rows pass. The per-block arrays stay segmented — their
        boundaries ARE the (block, slot) numbering the phash maps to."""
        if not self.sidecar_hashes:
            return
        self._key_hashes.append(hashes if hashes is not None
                                else crc64_rows(keys, key_len))

    def add(self, key: bytes, value: bytes, expire_ts: int = 0,
            tombstone: bool = False) -> None:
        if self._last_key is not None and key <= self._last_key:
            raise ValueError("keys must be added in strictly increasing order")
        self._last_key = key
        self._pending.append((key, value, expire_ts,
                              FLAG_TOMBSTONE if tombstone else 0))
        self._count += 1
        if len(self._pending) >= self._block_capacity:
            self._flush_block()

    def _flush_block(self) -> None:
        if not self._pending:
            return
        recs = self._pending
        self._pending = []
        n = len(recs)
        width = next_bucket(max(len(k) for k, *_ in recs))
        keys = np.zeros((n, width), dtype=np.uint8)
        key_len = np.zeros(n, dtype=np.int32)
        ets = np.zeros(n, dtype=np.uint32)
        flags = np.zeros(n, dtype=np.uint8)
        offs = np.zeros(n + 1, dtype=np.uint32)
        heap_parts = []
        pos = 0
        for i, (k, v, e, fl) in enumerate(recs):
            keys[i, :len(k)] = np.frombuffer(k, dtype=np.uint8)
            key_len[i] = len(k)
            ets[i] = e
            flags[i] = fl
            offs[i] = pos
            heap_parts.append(v)
            pos += len(v)
        offs[n] = pos
        heap = b"".join(heap_parts)

        # pegasus_key_hash lo lane: crc64 of the hashkey region (or the
        # sortkey region when the hashkey is empty) — write-time work that
        # removes the crc loop from every future scan of this block
        hkl = (keys[:, 0].astype(np.int64) << 8) | keys[:, 1].astype(np.int64)
        region_len = np.where(hkl > 0, hkl, key_len.astype(np.int64) - 2)
        hash_lo = (crc64_batch(keys, region_len, start=2)
                   & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        # full-key hash column for the sidecars (bloom + phash): one
        # vectorized pass per block, folded into both at finish
        self._sidecar_note(keys, key_len)

        offset = self._offset
        # ONE buffer per block: a single kernel copy + syscall instead of
        # eight, and a single unit for the async-IO queue — and the one
        # pass the end-to-end block checksum rides (crc32 over exactly
        # the bytes that hit the disk)
        if self.codec == CODEC_NONE:
            buf = b"".join((
                _BLOCK_HDR.pack(n, width, len(heap)), keys.tobytes(),
                key_len.tobytes(), ets.tobytes(), hash_lo.tobytes(),
                flags.tobytes(), offs.tobytes(), heap))
        else:
            buf = encode_block(keys, key_len, ets, hash_lo, flags,
                               offs, heap, version=self.codec_version)
            self._codec_raw_bytes += raw_block_size(n, width, len(heap))
            self._codec_stored_bytes += len(buf)
        self._write(buf)
        self._blocks.append(BlockMeta(
            offset=offset, size=self._offset - offset, count=n,
            key_width=width, first_key=recs[0][0], last_key=recs[-1][0],
            crc=_block_crc32(buf) if self._block_crc else None))

    def add_block_columnar(self, keys: np.ndarray, key_len: np.ndarray,
                           ets: np.ndarray, hash_lo: np.ndarray,
                           flags: np.ndarray, value_offs: np.ndarray,
                           heap: bytes) -> None:
        """Append a block from ALREADY-COLUMNAR arrays (bulk compaction's
        rewrite path): no per-record Python, and hash_lo is carried over
        from the source block instead of recomputed."""
        n = int(keys.shape[0])
        if n == 0:
            return
        self._flush_block()
        first_key = bytes(keys[0, :int(key_len[0])])
        last_key = bytes(keys[-1, :int(key_len[-1])])
        if self._last_key is not None and first_key <= self._last_key:
            raise ValueError("blocks must be added in key order")
        width = int(keys.shape[1])
        self._sidecar_note(keys, key_len)
        offset = self._offset
        if self.codec == CODEC_NONE:
            buf = b"".join((
                _BLOCK_HDR.pack(n, width, len(heap)),
                np.ascontiguousarray(keys, dtype=np.uint8).tobytes(),
                np.ascontiguousarray(key_len, dtype=np.int32).tobytes(),
                np.ascontiguousarray(ets, dtype=np.uint32).tobytes(),
                np.ascontiguousarray(hash_lo, dtype=np.uint32).tobytes(),
                np.ascontiguousarray(flags, dtype=np.uint8).tobytes(),
                np.ascontiguousarray(value_offs,
                                     dtype=np.uint32).tobytes(),
                heap))
        else:
            buf = encode_block(keys, key_len, ets, hash_lo, flags,
                               value_offs, heap,
                               version=self.codec_version)
            self._codec_raw_bytes += raw_block_size(n, width, len(heap))
            self._codec_stored_bytes += len(buf)
        self._write(buf)
        self._blocks.append(BlockMeta(
            offset=offset, size=self._offset - offset, count=n,
            key_width=width, first_key=first_key, last_key=last_key,
            crc=_block_crc32(buf) if self._block_crc else None))
        self._count += n
        self._last_key = last_key

    def add_block_encoded(self, enc: EncodedBlock) -> None:
        """Append an ALREADY-ENCODED block verbatim — bulk compaction's
        untouched-block fast path on compressed stores: the on-disk
        bytes stream straight to the output with no value-heap inflate,
        no re-encode, and no re-deflate; only the bloom filter's
        full-key hashes re-derive (from the cheap key-matrix rebuild,
        which never touches the heap)."""
        if self.codec == CODEC_NONE:
            raise ValueError("writer codec is 'none'; encoded blocks "
                             "must decode first")
        n = enc.n
        if n == 0:
            return
        if not codec_accepts(self.codec, enc.version):
            # a 'dcz' writer may not embed a v2 block (an old build
            # reading the file would misparse it): transcode down
            # through the columnar path — decode never inflates the
            # value heap until the encoder's compress probe reads it
            blk = enc.decode()
            self.add_block_columnar(blk.keys, blk.key_len,
                                    blk.expire_ts, blk.hash_lo,
                                    blk.flags, blk.value_offs,
                                    blk.value_heap)
            return
        self._flush_block()
        first_key = enc.key_at(0)
        last_key = enc.key_at(n - 1)
        if self._last_key is not None and first_key <= self._last_key:
            raise ValueError("blocks must be added in key order")
        buf = enc.raw if isinstance(enc.raw, bytes) else bytes(enc.raw)
        hashes = (crc64_rows(enc.key_matrix(), enc.key_len)
                  if self.sidecar_hashes else None)
        self.add_block_encoded_raw(buf, n, enc.key_width,
                                   enc.raw_heap_len, first_key,
                                   last_key, hashes)

    def add_block_encoded_raw(self, buf: bytes, n: int, key_width: int,
                              raw_heap_len: int, first_key: bytes,
                              last_key: bytes, key_hashes) -> None:
        """Append pre-encoded block bytes with the metadata the index
        needs already in hand — the native subset kernel's exit
        (pegasus_cblock_subset emits the bloom hashes and fence keys
        in its gather pass, so nothing here re-parses the block on the
        GIL)."""
        if self.codec == CODEC_NONE:
            raise ValueError("writer codec is 'none'; encoded blocks "
                             "must decode first")
        if n == 0:
            return
        if not codec_accepts(self.codec, block_version(buf)):
            # callers (lsm's subset fast path) pre-check compatibility;
            # reaching here means a version this file's named codec
            # cannot legally contain — refuse rather than write a file
            # that other builds would misparse
            raise ValueError(
                f"block format v{block_version(buf)} cannot be stored "
                f"in a {self.codec!r} file")
        self._flush_block()
        if self._last_key is not None and first_key <= self._last_key:
            raise ValueError("blocks must be added in key order")
        if self.sidecar_hashes:
            if key_hashes is None:
                raise ValueError("sidecar build needs key hashes")
            self._sidecar_note(None, None, hashes=key_hashes)
        offset = self._offset
        self._write(buf)
        self._blocks.append(BlockMeta(
            offset=offset, size=len(buf), count=n,
            key_width=key_width, first_key=first_key,
            last_key=last_key,
            crc=_block_crc32(buf) if self._block_crc else None))
        self._codec_raw_bytes += raw_block_size(n, key_width,
                                                raw_heap_len)
        self._codec_stored_bytes += len(buf)
        self._count += n
        self._last_key = last_key

    def finish(self) -> None:
        self._flush_block()
        self._join_io()
        index = {
            "blocks": [
                {"off": b.offset, "size": b.size, "count": b.count,
                 "kw": b.key_width, "first": b.first_key.hex(),
                 "last": b.last_key.hex(),
                 **({"crc": b.crc} if b.crc is not None else {})}
                for b in self._blocks
            ],
            "meta": self._meta,
            "total_count": self._count,
        }
        if self.codec != CODEC_NONE:
            # format versioning exactly like the PR 5 block CRC: the
            # codec is named once per file; readers without the codec
            # refuse at open (never misparse), and codec=none files
            # stay bit-for-bit the legacy layout (no key at all)
            index["codec"] = self.codec
            index["codec_stats"] = {
                "raw_bytes": self._codec_raw_bytes,
                "stored_bytes": self._codec_stored_bytes,
            }
        self._build_sidecars(index)
        blob = json.dumps(index).encode()
        index_offset = self._f.tell()
        self._f.write(blob)
        self._f.write(FOOTER.pack(index_offset, len(blob), crc32(blob), MAGIC))
        self._f.flush()
        fsync_file(self._f)
        self._f.close()
        os.replace(self.path + ".tmp", self.path)
        # the rename itself must be durable BEFORE the caller truncates the
        # WAL, or a power failure can lose the SST while the WAL is already
        # empty — fsync the containing directory
        fsync_dir(os.path.dirname(self.path))

    def _build_sidecars(self, index: dict) -> None:
        """Build + persist the run's sidecar structures from the
        accumulated per-block hash columns — the ONE place every
        writer-finish site (flush / merge-compact / bulk-compact /
        ingest) derives them, so a new sidecar cannot drift across
        paths. Sections sit between the data blocks and the index; the
        index names offsets/geometry, so sidecar-less files (and
        sidecar-less READERS of the bloom) stay compatible. The phash
        entry carries a format VERSION: readers refuse versions they
        do not know at open (never misparse), exactly like the block
        codec key."""
        if not self._key_hashes:
            return
        if self.bloom_enabled:
            bf = BloomFilter.build(np.concatenate(self._key_hashes),
                                   self._bloom_bits_per_key)
            bloom_off = self._f.tell()
            blob = bf.to_bytes()
            self._f.write(blob)
            index["bloom"] = {"off": bloom_off, "size": len(blob),
                              "m": bf.m, "k": bf.k}
        if self.phash_enabled:
            # construction can fail (adversarial keys, hash
            # collisions, oversized geometry, the forced fail point):
            # a perf event — the run serves via bloom + bisect
            ph = PHashIndex.build(
                np.concatenate(self._key_hashes)
                if len(self._key_hashes) > 1 else self._key_hashes[0],
                [b.count for b in self._blocks])
            if ph is None:
                PHASH_BUILD_FAIL.increment()
            else:
                # pad the blob start to a 4-byte boundary: the mmap
                # read path hands the native probe raw u32/u16
                # pointers into the file, and the mmap base is
                # page-aligned, so an aligned file offset IS an
                # aligned address (misaligned loads are UB)
                pad = (-self._f.tell()) % 4
                if pad:
                    self._f.write(b"\x00" * pad)
                ph_off = self._f.tell()
                blob = ph.to_bytes()
                self._f.write(blob)
                index["phash"] = {"off": ph_off, "size": len(blob),
                                  **ph.meta()}

    def abandon(self) -> None:
        try:
            self._join_io()
        except BaseException:  # noqa: BLE001 - abandoning anyway
            pass
        self._f.close()
        try:
            os.remove(self.path + ".tmp")
        except OSError:
            pass


class SSTable:
    """Reader with an in-memory index and a byte-capped block cache."""

    def __init__(self, path: str,
                 cache_bytes: Optional[int] = None) -> None:
        # the decoded-block cache is BYTE-capped (LRU, like the node
        # row cache): a raw-file Block is zero-copy numpy views over
        # the mmap and charges only bookkeeping, but a block decoded
        # from a COMPRESSED file is a real allocation whose size the
        # old fixed 256-block count cap could not see. `cache_bytes`
        # None -> the mutable [pegasus.storage] block_cache_bytes flag.
        import io as _io
        import mmap as _mmap

        self.path = path
        self._f = open_data_file(path, "rb")
        # plaintext files are mmapped: read_block decodes ZERO-COPY numpy
        # views straight over the page cache (no read() copy, no seek
        # syscalls). Encrypted files (CipherFile) keep the read() path.
        # The map is never explicitly closed — cached Blocks hold views
        # into it, and Linux keeps the mapping alive past close()/unlink
        # until the last view dies.
        self._mv: Optional[memoryview] = None
        if isinstance(self._f, _io.BufferedReader):
            try:
                self._mv = memoryview(_mmap.mmap(
                    self._f.fileno(), 0, access=_mmap.ACCESS_READ))
            except (ValueError, OSError):
                self._mv = None  # empty file or no-mmap fs
        self._f.seek(0, os.SEEK_END)
        file_size = self._f.tell()
        if file_size < len(MAGIC) + FOOTER.size:
            raise StorageCorruptionError(path, "not an sstable (too small)")
        self._f.seek(file_size - FOOTER.size)
        index_offset, index_size, index_crc, magic = FOOTER.unpack(
            self._f.read(FOOTER.size))
        if magic not in (MAGIC, MAGIC_V1):
            raise StorageCorruptionError(path, "bad footer magic")
        self._has_hash_lo = magic == MAGIC
        self._f.seek(index_offset)
        blob = self._f.read(index_size)
        if crc32(blob) != index_crc:
            raise StorageCorruptionError(path, "index crc mismatch")
        try:
            index = json.loads(blob)
        except ValueError as e:
            # crc passed but the JSON doesn't parse: a write bug, not a
            # disk flip — still corruption at the serving surface
            raise StorageCorruptionError(path, f"index unparsable: {e}")
        self.blocks: List[BlockMeta] = [
            BlockMeta(offset=e["off"], size=e["size"], count=e["count"],
                      key_width=e["kw"], first_key=bytes.fromhex(e["first"]),
                      last_key=bytes.fromhex(e["last"]),
                      crc=e.get("crc"))
            for e in index["blocks"]
        ]
        self.meta: dict = index.get("meta", {})
        self.total_count: int = index.get("total_count", 0)
        # per-file codec negotiation: legacy files carry no key and
        # serve the raw layout unmodified; a codec this build does not
        # know is REFUSED at open (a misparse would serve garbage)
        codec = index.get("codec")
        if codec is not None and codec not in KNOWN_CODECS:
            raise StorageCorruptionError(
                path, f"unsupported block codec {codec!r} "
                      f"(known: {', '.join(KNOWN_CODECS)})")
        self.codec: Optional[str] = codec
        self.codec_stats: Optional[dict] = index.get("codec_stats")
        # pre-filter files simply miss the "bloom" entry and degrade to
        # the unfiltered path (may_contain == always True)
        self.bloom: Optional[BloomFilter] = None
        bl = index.get("bloom")
        if bl:
            if self._mv is not None:
                raw = self._mv[bl["off"]:bl["off"] + bl["size"]]
            else:
                self._f.seek(bl["off"])
                raw = self._f.read(bl["size"])
            self.bloom = BloomFilter.from_bytes(raw, bl["m"], bl["k"])
        # perfect-hash (block, slot) index: pre-index files miss the
        # entry and keep serving via bloom + bisect; an index VERSION
        # this build does not know is refused at open (a misparse
        # would locate the wrong rows), mirroring the codec rule
        self.phash: Optional[PHashIndex] = None
        ph = index.get("phash")
        if ph:
            if ph.get("version") not in KNOWN_PHASH_VERSIONS:
                raise StorageCorruptionError(
                    path, f"unsupported phash index version "
                          f"{ph.get('version')!r} (known: "
                          f"{', '.join(map(str, KNOWN_PHASH_VERSIONS))})")
            if self._mv is not None:
                raw = self._mv[ph["off"]:ph["off"] + ph["size"]]
            else:
                self._f.seek(ph["off"])
                raw = self._f.read(ph["size"])
            # torn/mismatched blob: from_bytes returns None and the
            # file degrades to the bisect path (like a torn bloom)
            self.phash = PHashIndex.from_bytes(raw, ph)
        from collections import OrderedDict as _OD

        import threading

        # idx -> (Block, charged_bytes); bytes tracked alongside so
        # eviction never recomputes sizes. Insert/evict accounting runs
        # under a lock: serving and compaction threads share run caches,
        # and an interleaved += / -= on _cache_bytes would drift the
        # budget for the file's whole lifetime (hits stay lock-free)
        self._cache: "_OD[int, Tuple[Block, int]]" = _OD()
        self._cache_bytes = 0
        self._cache_lock = threading.Lock()
        self._cache_budget = cache_bytes  # None -> flag at use
        self._off2idx: Optional[dict] = None  # block_index lookup
        self._last_keys: Optional[List[bytes]] = None  # iter_blocks bisect
        # fence columns as plain attributes: the block list is immutable
        # for the file's lifetime, and the point-read planner compares
        # fences for every (key, table) candidate — property dispatch
        # was measurable there
        self.first_key: Optional[bytes] = (
            self.blocks[0].first_key if self.blocks else None)
        self.last_key: Optional[bytes] = (
            self.blocks[-1].last_key if self.blocks else None)

    def close(self) -> None:
        self._f.close()

    def clear_block_cache(self) -> None:
        """Drop every decoded block (and its byte accounting) — tests
        and cache-pressure tooling; the serving path never needs it."""
        with self._cache_lock:
            self._cache.clear()
            self._cache_bytes = 0

    def may_contain(self, key: bytes, key_hash: Optional[int] = None
                    ) -> bool:
        """False means definitively absent (bloom-filtered); tables
        without a filter (or with probing switched off) answer True.
        `key_hash` lets callers that already hashed the key (the
        batched probe path, or a multi-table solo get) skip the crc."""
        bf = self.bloom
        if bf is None or not bloom_probe_enabled():
            return True
        hit = (bf.may_contain_hash(key_hash) if key_hash is not None
               else bf.may_contain(key))
        if not hit:
            _BLOOM_USEFUL.increment()
            pc = _perf_current()
            if pc is not None:
                pc.bloom_pruned += 1
        return hit

    def _read_raw_block(self, idx: int):
        """(raw bytes of block `idx`, its BlockMeta), crc-verified —
        the shared cold-read step of decode / encoded-probe paths."""
        bm = self.blocks[idx]
        if self._mv is not None:
            raw = self._mv[bm.offset:bm.offset + bm.size]
        else:
            self._f.seek(bm.offset)
            raw = self._f.read(bm.size)
        # verify-on-read BEHIND the block cache: a decoded block is
        # checked exactly once per residency, so cached hits (the hot
        # path) pay nothing. Legacy blocks (crc None) serve unverified.
        if bm.crc is not None and _block_crc32(raw) != bm.crc:
            raise StorageCorruptionError(
                self.path,
                f"block {idx} crc mismatch (offset {bm.offset}, "
                f"{bm.size} bytes)")
        return raw, bm

    def read_block_encoded(self, idx: int) -> Optional[EncodedBlock]:
        """The ENCODED form of block `idx` (predicate columns parsed,
        key matrix and value heap untouched) — the direct-compute entry
        point for compaction drop masks and scan probes. None for
        uncompressed files. No cache: callers stream sequentially or
        probe once per (block, flavor) miss, and parsing is a handful
        of section views."""
        if self.codec is None:
            return None
        raw, _bm = self._read_raw_block(idx)
        return EncodedBlock.parse(raw)

    def block_index(self, bm: BlockMeta) -> int:
        """BlockMeta -> its position (offset-keyed; block offsets are
        unique and immutable for the file's lifetime)."""
        o2i = self._off2idx
        if o2i is None:
            o2i = self._off2idx = {
                b.offset: i for i, b in enumerate(self.blocks)}
        return o2i[bm.offset]

    def read_block(self, idx: int) -> Block:
        pc = _perf_current()  # the op's PerfContext (None = untracked)
        hit = self._cache.get(idx)
        if hit is not None:
            # true LRU: a hit refreshes recency (the old FIFO eviction
            # popped insertion order, so resident-forever hot blocks
            # were evicted by any cold streak)
            try:
                self._cache.move_to_end(idx)
            except KeyError:
                pass  # raced a concurrent eviction (serving vs
                # compaction threads share run caches); the decoded
                # block in hand stays valid
            _BLOCK_CACHE_HIT.increment()
            if pc is not None:
                pc.block_cache_hit += 1
            return hit[0]
        _BLOCK_CACHE_MISS.increment()
        if pc is not None:
            pc.blocks_decoded += 1
            pc.bytes_read += self.blocks[idx].size
        raw, bm = self._read_raw_block(idx)
        if self.codec is not None:
            enc = EncodedBlock.parse(raw)
            blk = enc.decode()
            _COMPRESSED_DECODE.increment()
            # storage join point: a traced request that paid a cold
            # compressed-block decode records it on its span
            _trace_annotate("block_decode")
            # a decoded compressed block is real allocation (the raw
            # path below is mmap views): charge its materialized size
            nbytes = enc.mem_bytes()
        else:
            n, width, heap_size = _BLOCK_HDR.unpack_from(raw, 0)
            pos = _BLOCK_HDR.size
            keys = np.frombuffer(raw, dtype=np.uint8, count=n * width,
                                 offset=pos).reshape(n, width)
            pos += n * width
            key_len = np.frombuffer(raw, dtype=np.int32, count=n,
                                    offset=pos)
            pos += 4 * n
            ets = np.frombuffer(raw, dtype=np.uint32, count=n, offset=pos)
            pos += 4 * n
            if self._has_hash_lo:
                hash_lo = np.frombuffer(raw, dtype=np.uint32, count=n,
                                        offset=pos)
                pos += 4 * n
            else:
                hash_lo = None  # v1 file: predicate path computes on device
            flags = np.frombuffer(raw, dtype=np.uint8, count=n, offset=pos)
            pos += n
            offs = np.frombuffer(raw, dtype=np.uint32, count=n + 1,
                                 offset=pos)
            pos += 4 * (n + 1)
            heap = np.frombuffer(raw, dtype=np.uint8, count=heap_size,
                                 offset=pos)
            blk = Block(keys, key_len, ets, hash_lo, flags, offs, heap)
            # raw blocks start as zero-copy views over the page cache
            # (or a real read() copy on encrypted stores), but a
            # resident block lazily materializes real memory the views
            # don't show — key_list() (~a bytes object per row) and the
            # point-probe table — so the charge models that worst-case
            # resident footprint, not the view bookkeeping. Charging
            # only ~2KB would let the 32MiB default admit ~16k blocks
            # (the old count cap held 256) whose hidden side tables
            # could grow unchecked.
            lazy = n * (width + 64)
            nbytes = (512 + lazy if self._mv is not None
                      else bm.size + 512 + lazy)
        if pc is not None:
            # materialized bytes after the codec: the decoded size for
            # compressed blocks, the on-disk (zero-copy view) size for
            # raw ones — against bytes_read this is the decode ratio
            pc.bytes_decoded += (nbytes if self.codec is not None
                                 else bm.size)
        budget = (self._cache_budget if self._cache_budget is not None
                  else block_cache_budget())
        evicted = 0
        with self._cache_lock:
            prev = self._cache.get(idx)
            if prev is not None:
                # two threads raced the same cold block (serving +
                # compaction share run caches): the overwrite must
                # release the first insert's charge or the budget
                # drifts up by one block per race, forever
                self._cache_bytes -= prev[1]
            self._cache[idx] = (blk, nbytes)
            self._cache_bytes += nbytes
            while self._cache_bytes > budget and len(self._cache) > 1:
                _k, (_b, nb) = self._cache.popitem(last=False)
                self._cache_bytes -= nb
                evicted += nb
        if evicted:
            _BLOCK_EVICT_BYTES.increment(evicted)
        return blk

    def verify_block(self, idx: int) -> bool:
        """Scrub entry point: re-read block `idx`'s raw bytes and check
        them against the index CRC — no decode, no block-cache
        pollution (a scrub walking a cold table must not evict the
        serving working set). Returns False for legacy blocks (nothing
        to verify); raises StorageCorruptionError on a mismatch."""
        bm = self.blocks[idx]
        if bm.crc is None:
            return False
        if self._mv is not None:
            raw = self._mv[bm.offset:bm.offset + bm.size]
        else:
            self._f.seek(bm.offset)
            raw = self._f.read(bm.size)
        if len(raw) != bm.size or _block_crc32(raw) != bm.crc:
            raise StorageCorruptionError(
                self.path,
                f"scrub: block {idx} crc mismatch (offset {bm.offset}, "
                f"{bm.size} bytes)")
        return True

    def verify_index_consistency(self) -> None:
        """Scrub's structural pass: block fences must be internally
        ordered and monotonic across the file; (when a filter exists)
        every block's first key must answer 'maybe' from the bloom
        filter; and (when a perfect-hash index exists) every block's
        first key must locate to exactly (that block, slot 0) — a
        sidecar that denies or mislocates a present key would turn
        into silent NotFound under probe pruning, which is data loss
        without a single flipped data byte. A corrupt/stale phash is
        therefore caught by the same quarantine/re-learn loop the
        block CRCs feed."""
        prev_last: Optional[bytes] = None
        for i, bm in enumerate(self.blocks):
            if bm.first_key > bm.last_key:
                raise StorageCorruptionError(
                    self.path, f"scrub: block {i} fence inverted")
            if prev_last is not None and bm.first_key <= prev_last:
                raise StorageCorruptionError(
                    self.path, f"scrub: block {i} overlaps block {i - 1}")
            prev_last = bm.last_key
            if self.bloom is not None and \
                    not self.bloom.may_contain(bm.first_key):
                raise StorageCorruptionError(
                    self.path,
                    f"scrub: bloom filter denies resident key "
                    f"(block {i} first key)")
            if self.phash is not None:
                loc = self.phash.lookup_hash(crc64(bm.first_key))
                if loc < 0 or self.phash.unpack(loc) != (i, 0):
                    raise StorageCorruptionError(
                        self.path,
                        f"scrub: phash index denies or mislocates "
                        f"resident key (block {i} first key)")

    def index_memory(self) -> dict:
        """Resident sidecar bytes: {"bloom": ..., "phash": ...} — the
        per-structure split behind the node's index-memory signal."""
        return {
            "bloom": (self.bloom.bits.nbytes
                      if self.bloom is not None else 0),
            "phash": (self.phash.mem_bytes()
                      if self.phash is not None else 0),
        }

    def get(self, key: bytes, key_hash: Optional[int] = None
            ) -> Optional[Tuple[Optional[bytes], int]]:
        """Returns (value|None-for-tombstone, expire_ts), or None if absent.

        `key_hash` (crc64 of the full key, the same hash every sidecar
        shares) lets callers that already hashed skip the crc. Indexed
        files answer via the perfect-hash index: a miss costs one slot
        gather and ZERO block touches; a hit reads its (block, slot)
        row directly — no fence bisect, no in-block bisect — and one
        row compare rejects the rare fingerprint collision."""
        ph = self.phash
        if ph is not None and phash_probe_enabled():
            pc = _perf_current()
            h = key_hash if key_hash is not None else crc64(key)
            loc = ph.lookup_hash(h)
            if loc < 0:
                PHASH_USEFUL.increment()
                if pc is not None:
                    pc.phash_pruned += 1
                return None
            bi, slot = ph.unpack(loc)
            if bi < len(self.blocks) and slot < self.blocks[bi].count:
                blk = self.read_block(bi)
                if blk.key_at(slot) == key:
                    PHASH_HIT.increment()
                    if pc is not None:
                        pc.phash_located += 1
                    if blk.is_tombstone(slot):
                        return (None, 0)
                    return (blk.value_at(slot),
                            int(blk.expire_ts[slot]))
                PHASH_USEFUL.increment()
                if pc is not None:
                    pc.phash_pruned += 1
                return None  # fp collision: definitively absent
            # out-of-range loc (corrupt index): serve via the bisect
            # below; the scrub structural pass flags the file
        idx = self._block_for_key(key)
        if idx is None:
            return None
        blk = self.read_block(idx)
        kl = blk._key_list
        if kl is None and blk._gets >= 4:
            kl = blk.key_list()  # hot block: slice-free bisects from now on
        if kl is not None:
            lo = bisect.bisect_left(kl, key)
            found = lo < blk.count and kl[lo] == key
        else:
            # cold block: O(log N) row probes, no full materialization
            blk._gets += 1
            lo, hi = 0, blk.count
            while lo < hi:
                mid = (lo + hi) // 2
                if blk.key_at(mid) < key:
                    lo = mid + 1
                else:
                    hi = mid
            found = lo < blk.count and blk.key_at(lo) == key
        if found:
            if blk.is_tombstone(lo):
                return (None, 0)
            return (blk.value_at(lo), int(blk.expire_ts[lo]))
        return None

    def _block_for_key(self, key: bytes) -> Optional[int]:
        lo, hi = 0, len(self.blocks)
        while lo < hi:
            mid = (lo + hi) // 2
            if self.blocks[mid].last_key < key:
                lo = mid + 1
            else:
                hi = mid
        if lo == len(self.blocks):
            return None
        return lo if self.blocks[lo].first_key <= key else None

    def iterate(self, start: bytes = b"", stop: Optional[bytes] = None,
                reverse: bool = False
                ) -> Iterator[Tuple[bytes, Optional[bytes], int]]:
        """Yield (key, value|None-for-tombstone, expire_ts) in range."""
        if not self.blocks:
            return
        if reverse:
            block_range = range(len(self.blocks) - 1, -1, -1)
        else:
            block_range = range(len(self.blocks))
        for bi in block_range:
            bm = self.blocks[bi]
            if stop is not None and bm.first_key >= stop:
                if reverse:
                    continue
                break
            if start and bm.last_key < start:
                if reverse:
                    break
                continue
            blk = self.read_block(bi)
            idxs = range(blk.count - 1, -1, -1) if reverse else range(blk.count)
            for i in idxs:
                k = blk.key_at(i)
                if start and k < start:
                    continue
                if stop is not None and k >= stop:
                    continue
                v = None if blk.is_tombstone(i) else blk.value_at(i)
                yield k, v, int(blk.expire_ts[i])

    def iter_blocks(self, start: bytes = b"", stop: Optional[bytes] = None
                    ) -> Iterator[Tuple[BlockMeta, Block]]:
        """Yield whole blocks intersecting [start, stop) — the device fast
        path: callers feed Block columns directly to the predicate kernels.
        The first candidate is found by bisect over the cached last-key
        column (scans start mid-table constantly; a linear walk from
        block 0 was the planner's hottest loop)."""
        lk = self._last_keys
        if lk is None:
            lk = self._last_keys = [b.last_key for b in self.blocks]
        bi = bisect.bisect_left(lk, start) if start else 0
        for bi in range(bi, len(self.blocks)):
            bm = self.blocks[bi]
            if stop is not None and bm.first_key >= stop:
                break
            yield bm, self.read_block(bi)
