"""StorageEngine: WAL + LSM with decree watermark discipline.

Parity: src/server/rocksdb_wrapper.{h,cpp} + src/base/meta_store.{h,cpp} —
every committed write batch atomically carries its decree into engine
metadata (rocksdb_wrapper.cpp:205 puts `pegasus_last_flushed_decree` into
the meta CF inside the same WriteBatch), so any flushed/checkpointed state
knows exactly which decree it contains. Here:

- write_batch(items, decree): one WAL frame (decree-stamped) + memtable
  apply; last_committed_decree advances.
- flush(): memtable -> L0 SST whose footer meta records
  {last_flushed_decree, data_version}; WAL truncates after the SST is
  durable (replay contract preserved).
- boot: recover last_flushed_decree = max over SST metas, then replay WAL
  frames with decree > last_flushed_decree into the memtable.
- manual_compact(): full merge through the device TTL/stale-split filter
  (ops/compaction.compaction_filter_block) — the manual-compaction path
  (src/server/pegasus_manual_compact_service.h:48).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from pegasus_tpu.base.value_schema import epoch_now
from pegasus_tpu.ops.compaction import compaction_filter_block
from pegasus_tpu.ops.record_block import build_record_block
# imported for their flag definitions (compact_pipeline /
# compact_max_mbps etc. must exist before any config file applies)
from pegasus_tpu.storage import compact_governor  # noqa: F401
from pegasus_tpu.storage import compact_pipeline  # noqa: F401
from pegasus_tpu.storage.lsm import LSMStore
from pegasus_tpu.storage.wal import OP_DEL, OP_PUT, WalRecord, WriteAheadLog


@dataclass
class WriteBatchItem:
    op: int                 # OP_PUT | OP_DEL
    key: bytes
    value: bytes = b""      # full pegasus-encoded value for puts
    expire_ts: int = 0


class StorageEngine:
    def __init__(self, data_dir: str, data_version: int = 1,
                 block_capacity: int = 1024,
                 values_carry_expire_header: bool = False) -> None:
        self.data_dir = data_dir
        os.makedirs(data_dir, exist_ok=True)
        self.data_version = data_version
        # the engine's expire_ts COLUMN is authoritative; values are
        # opaque bytes here. The server layer stores pegasus-encoded
        # values whose leading BE-u32 duplicates the TTL — it sets this
        # flag so compaction TTL rewrites also patch the embedded header
        # (keeping forensic readers of the raw value consistent).
        self.values_carry_expire_header = values_carry_expire_header
        self.lsm = LSMStore(os.path.join(data_dir, "sst"),
                            block_capacity=block_capacity)

        # recover the decree watermark from SST metas; data_version comes
        # from the table with the NEWEST watermark (an older L1 must not
        # revert a schema upgrade recorded by a newer L0 flush)
        self.last_flushed_decree = 0
        for table in list(self.lsm.l0) + list(self.lsm.l1_runs):
            d = int(table.meta.get("last_flushed_decree", 0))
            if d >= self.last_flushed_decree and "data_version" in table.meta:
                self.data_version = int(table.meta["data_version"])
            self.last_flushed_decree = max(self.last_flushed_decree, d)
        self.last_committed_decree = self.last_flushed_decree

        # auto-maintenance knobs (the usage-scenario env rewires them:
        # normal / prefer_write / bulk_load — common/replica_envs.h:81)
        self.memtable_flush_trigger = 100_000  # records
        self.auto_compact = True
        self.auto_compact_ctx = None  # server installs its filter context
        # write-through invalidation hook: called with the key list of
        # every applied batch BEFORE the write returns, so row-cache
        # owners (PartitionServer) can never serve a value this batch
        # replaced
        self.on_write_keys = None
        # serializes compactions: the env-triggered manual path holds it
        # across its (unlocked) merge; the write path's auto-compaction
        # try-acquires and SKIPS when a manual run is in flight (the
        # running compaction covers the trigger) — blocking there would
        # deadlock write-lock->compact-lock against the manual path's
        # compact-lock->write-lock publish ordering
        import threading as _threading

        self.compact_lock = _threading.Lock()

        # flush/compaction event metrics (parity: pegasus_event_listener)
        from pegasus_tpu.utils.metrics import METRICS

        ev = METRICS.entity("engine", data_dir, {"dir": data_dir})
        self._ev_flush_count = ev.counter("flush_count")
        self._ev_flush_bytes = ev.counter("flush_bytes")
        self._ev_flush_ms = ev.percentile("flush_duration_ms")
        self._ev_compact_count = ev.counter("compaction_count")
        self._ev_compact_bytes = ev.counter("compaction_bytes")
        self._ev_compact_ms = ev.percentile("compaction_duration_ms")

        # replay WAL beyond the flushed watermark
        self._wal_path = os.path.join(data_dir, "wal.log")
        for decree, records in WriteAheadLog.replay(self._wal_path):
            if decree <= self.last_flushed_decree:
                continue
            for r in records:
                if r.op == OP_DEL:
                    self.lsm.delete(r.key)
                else:
                    self.lsm.put(r.key, r.value, r.expire_ts)
            self.last_committed_decree = max(self.last_committed_decree, decree)
        self.wal = WriteAheadLog(self._wal_path)

    def close(self) -> None:
        self.wal.close()
        self.lsm.close()

    # ---- write path ---------------------------------------------------

    def write_batch(self, items: Sequence[WriteBatchItem], decree: int,
                    sync: bool = False, wal_flush: bool = True) -> None:
        """Apply one decree's mutations atomically (WAL first).
        `wal_flush=False` leaves the WAL frame in the IO buffer instead
        of flushing per decree — only valid under replication, where
        the private log (hardened by the group-commit window before any
        ack) covers everything this WAL could recover."""
        if decree <= self.last_committed_decree:
            raise ValueError(
                f"decree {decree} <= last committed {self.last_committed_decree}")
        self.wal.append_batch(
            decree,
            [WalRecord(i.op, i.key, i.value, i.expire_ts) for i in items],
            sync=sync, flush=wal_flush)
        for i in items:
            if i.op == OP_DEL:
                self.lsm.delete(i.key)
            else:
                self.lsm.put(i.key, i.value, i.expire_ts)
        self.last_committed_decree = decree
        hook = self.on_write_keys
        if hook is not None and items:
            hook([i.key for i in items])
        self._maybe_maintain()

    def _maybe_maintain(self) -> None:
        """Auto flush + compaction (parity: rocksdb's write-buffer flush
        and level-0 compaction trigger, tuned by the usage-scenario env,
        pegasus_server_impl.cpp:1758): without this a write-heavy table
        never flushes — unbounded memtable, unbounded WAL replay.
        Callers hold the single-writer context already."""
        if len(self.lsm.memtable) < self.memtable_flush_trigger:
            return
        self.flush()
        if self.auto_compact and self.lsm.should_compact():
            if not self.compact_lock.acquire(blocking=False):
                return  # manual compaction in flight covers this trigger
            try:
                ctx = (self.auto_compact_ctx() if self.auto_compact_ctx
                       else {})
                self.manual_compact(**ctx)
            finally:
                self.compact_lock.release()

    def flush(self) -> bool:
        """Memtable -> durable L0 SST stamped with the decree watermark."""
        import time as _time

        t0 = _time.perf_counter()
        table = self.lsm.flush(meta={
            "last_flushed_decree": self.last_committed_decree,
            "data_version": self.data_version,
        })
        if table is None:
            return False
        self.last_flushed_decree = self.last_committed_decree
        self.wal.truncate()
        # event-listener hooks (parity: pegasus_event_listener —
        # rocksdb flush/compaction events -> metrics)
        self._ev_flush_count.increment()
        self._ev_flush_ms.set((_time.perf_counter() - t0) * 1000.0)
        self._ev_flush_bytes.increment(os.path.getsize(table.path))
        return True

    # ---- read path ----------------------------------------------------

    def get(self, key: bytes) -> Optional[Tuple[bytes, int]]:
        return self.lsm.get(key)

    def iterate(self, start: bytes = b"", stop: Optional[bytes] = None,
                reverse: bool = False):
        return self.lsm.iterate(start, stop, reverse)

    # ---- checkpoint (parity: replication_app_base.h:171-236 +
    # rocksdb Checkpoint::CreateCheckpoint usage in pegasus_server_impl) --

    def checkpoint(self, dest_dir: str) -> int:
        """Flush, then materialize a consistent snapshot of the store into
        `dest_dir` (the checkpoint.<decree> analogue). Returns the decree
        the checkpoint contains."""
        import shutil

        self.flush()
        os.makedirs(dest_dir, exist_ok=True)
        sst_dir = os.path.join(self.data_dir, "sst")
        for name in os.listdir(sst_dir):
            # the manifest MUST travel with the runs: without it a
            # restored multi-run store would fall into the legacy
            # newest-l1-wins recovery and silently drop runs
            if name.endswith(".sst") or name == "MANIFEST.json":
                shutil.copy2(os.path.join(sst_dir, name),
                             os.path.join(dest_dir, name))
        return self.last_flushed_decree

    @staticmethod
    def restore_from_checkpoint(checkpoint_dir: str, data_dir: str
                                ) -> "StorageEngine":
        """Open a fresh engine whose state is the checkpoint's content
        (parity: storage_apply_checkpoint / restore-from-backup branch,
        pegasus_server_impl.cpp:1624)."""
        import shutil

        sst_dir = os.path.join(data_dir, "sst")
        shutil.rmtree(sst_dir, ignore_errors=True)
        os.makedirs(sst_dir, exist_ok=True)
        for name in os.listdir(checkpoint_dir):
            if name.endswith(".sst") or name == "MANIFEST.json":
                shutil.copy2(os.path.join(checkpoint_dir, name),
                             os.path.join(sst_dir, name))
        wal = os.path.join(data_dir, "wal.log")
        if os.path.exists(wal):
            os.remove(wal)
        return StorageEngine(data_dir)

    # ---- ingestion (parity: rocksdb_wrapper.cpp:248-266 IngestExternalFile
    # with the decree watermark carried atomically) ----------------------

    def ingest_sst_file(self, path: str, decree: int) -> None:
        """Adopt an externally-built columnar SST as the newest L0 run.

        The ingested file's meta is rewritten to carry the ingesting
        decree (the reference puts last_flushed_decree into the meta CF in
        the same atomic step as the ingestion), so checkpoints and
        learning know exactly what state they contain. The memtable is
        flushed FIRST: the ingest decree becomes the flushed watermark,
        and unflushed earlier writes must not be skipped by WAL recovery
        nor outrank the (newer-decree) ingested run in merge order.
        """
        from pegasus_tpu.storage.sstable import SSTable, SSTableWriter

        if decree <= self.last_committed_decree:
            raise ValueError(
                f"ingest decree {decree} <= last committed "
                f"{self.last_committed_decree}")
        self.flush()
        src = SSTable(path)

        def build(dest: str, meta) -> None:
            writer = SSTableWriter(dest, meta=meta)
            for key, value, ets in src.iterate():
                writer.add(key, value or b"", ets, tombstone=value is None)
            writer.finish()

        try:
            self.lsm.ingest(build, meta={
                "last_flushed_decree": decree,
                "data_version": self.data_version,
            })
        finally:
            src.close()
        self.last_committed_decree = decree
        self.last_flushed_decree = decree

    # ---- compaction ---------------------------------------------------

    def _manual_compact_bulk(self, now_s: int, default_ttl: int,
                             pidx: int, partition_version: int,
                             do_validate: bool, operations,
                             publish_lock=None) -> None:
        """Block-level compaction over a pure-L1 store.

        Pipelined (default): the block-read, filter-eval, and
        compressed-write stages run on dedicated threads connected by
        bounded queues (storage/compact_pipeline.py) — disk reads,
        device/XLA filter programs, the native subset kernel, and the
        output writers all overlap, and the read stage pays the
        CompactionGovernor's token bucket so background bandwidth
        answers foreground pressure. Serial (flag off): the original
        windowed loop with one-window device lookahead. Both produce
        the identical (block, mask) stream, so output bytes match.

        Mesh-filtered: when the table's blocks are resident on the
        device mesh (parallel/mesh_resident.py), the whole store's drop
        masks come back from ONE SPMD dispatch shared across every
        sibling partition compacting under the same filter params —
        submit_window then serves each window from the mask dict with
        no per-window device program at all. Declines (gate, watchdog
        trip, non-resident blocks) fall through to the host/XLA stages
        above, byte-identical by construction."""
        from pegasus_tpu.ops.compaction import (
            choose_eval_device,
            compaction_eval_drain,
            compaction_eval_submit,
            encoded_drop_mask,
            rules_workload,
        )
        from pegasus_tpu.storage.compact_governor import GOVERNOR
        from pegasus_tpu.storage.compact_pipeline import (
            CompactPipeline,
            pipeline_depth,
            pipeline_enabled,
            pipeline_window,
            stage_threads_enabled,
            transform_workers,
            window_count,
        )

        ttl_may_change = bool(default_ttl) or bool(
            operations and any(op.op == "update_ttl" for op in operations))
        eval_device = choose_eval_device(workload=rules_workload(operations))
        entries = self.lsm.bulk_compact_entries()
        # mesh FILTER pre-pass: one whole-table dispatch (or a sibling's
        # cached one) hands back every block's drop mask up front; the
        # READ stage below still pays the governor, the WRITE stage is
        # untouched
        mesh_masks = None
        if entries:
            try:
                from pegasus_tpu.parallel.mesh_resident import MESH_SERVING
                mesh_masks = MESH_SERVING.try_compact_masks(
                    self.lsm, entries, now_s, default_ttl, pidx,
                    partition_version, do_validate, operations,
                    want_ets=ttl_may_change,
                    n_windows=window_count(len(entries)))
            except Exception:
                mesh_masks = None
        meta = {
            # snapshot mode: the output only covers decrees flushed at
            # freeze time — claiming last_committed would make boot skip
            # the WAL frames of writes that raced the merge
            "last_flushed_decree": (
                self.last_flushed_decree if publish_lock is not None
                else self.last_committed_decree),
            "data_version": self.data_version,
            "manual_compact_finish_time": epoch_now(),
        }

        # direct compute on compressed blocks: a ruleset that touches
        # no key bytes (TTL + default-TTL rewrite + stale-split)
        # evaluates straight off the encoded block's raw predicate
        # columns — no key-matrix rebuild, no value-heap inflate, no
        # device program; unchanged blocks then copy verbatim
        def direct(run) -> bool:
            return (operations is None
                    and getattr(run, "codec", None) is not None)

        def load(entry):
            """READ stage: one block off disk, paced by the governor
            (this is the only place background compaction touches the
            disk for input)."""
            run, i, bm = entry
            GOVERNOR.acquire(bm.size)
            if direct(run):
                return (run, i, run.read_block_encoded(i), True)
            return (run, i, run.read_block(i), False)

        def submit_window(items):
            """FILTER stage phase 1: dispatch without waiting."""
            if mesh_masks is not None:
                served = {}
                for run, i, _blk, _d in items:
                    m = mesh_masks.get((run, i))
                    if m is None:
                        break
                    served[(run, i)] = m
                else:
                    # whole window pre-filtered on the mesh: nothing
                    # in flight, eager-forward straight to WRITE
                    return items, [], served
            blocks = [((run, i), blk, pidx)
                      for run, i, blk, is_direct in items
                      if not is_direct]
            host_done = {}
            for run, i, blk, is_direct in items:
                if is_direct:
                    host_done[(run, i)] = encoded_drop_mask(
                        blk, now_s, default_ttl, pidx,
                        partition_version, do_validate,
                        want_ets=ttl_may_change)
            pend = compaction_eval_submit(
                blocks, now_s, default_ttl, partition_version,
                do_validate, operations=operations,
                eval_device=eval_device,
                want_ets=ttl_may_change) if blocks else []
            return items, pend, host_done

        def drain_window(token):
            """FILTER stage phase 2: materialize one window's masks."""
            items, pend, host_done = token
            got = {}
            for tag, drop, new_ets in compaction_eval_drain(
                    pend, want_ets=ttl_may_change):
                got[tag] = (drop, new_ets)
            out = []
            for run, i, blk, is_direct in items:
                # host_done holds both direct-on-encoded masks and
                # mesh-served ones; device programs land in got
                m = host_done.get((run, i))
                if m is None:
                    m = got[(run, i)]
                drop, new_ets = m
                out.append((run, i, blk, drop, new_ets))
            return out

        if pipeline_enabled() and stage_threads_enabled():
            pipe = CompactPipeline(
                entries, load, submit_window, drain_window,
                window=pipeline_window(), depth=pipeline_depth(),
                # a window whose masks all computed host-direct at
                # submit has no in-flight device program to hide:
                # forward it immediately instead of holding the
                # one-window lookahead
                eager=lambda token: not token[1])
            results = pipe.results()
        else:
            def serial_results():
                # one-window lookahead ONLY for windows with an
                # in-flight device program: while window w's masks
                # drain and its survivors rewrite, window w+1 is
                # already uploaded and evaluating. Host-direct windows
                # (every mask computed at submit) yield immediately —
                # holding them back starves the write stage for a full
                # window of reads with nothing async to hide.
                W = pipeline_window()
                pending = None
                for off in range(0, len(entries), W):
                    token = submit_window(
                        [load(e) for e in entries[off:off + W]])
                    if pending is not None:
                        yield from drain_window(pending)
                        pending = None
                    if not token[1]:
                        yield from drain_window(token)
                    else:
                        pending = token
                if pending is not None:
                    yield from drain_window(pending)

            results = serial_results()

        self.lsm.bulk_compact_rewrite(
            results, meta, ttl_may_change=ttl_may_change,
            patch_headers=self.values_carry_expire_header,
            publish_lock=publish_lock,
            transform_workers=(transform_workers()
                               if pipeline_enabled() else 0))

    def manual_compact(self, default_ttl: int = 0, pidx: int = 0,
                       partition_version: int = -1,
                       validate_hash: bool = False,
                       rules_filter=None,
                       now: Optional[int] = None,
                       publish_lock=None) -> None:
        """Full compaction with the device TTL/stale-split filter.

        `rules_filter(keys, expire_ts, now) -> (drop, new_ets)` is the
        optional user-specified compaction hook (compaction_rules.py),
        applied after the default-TTL rewrite, before expiry — matching the
        reference's Filter() ordering (key_ttl_compaction_filter.h:71-90).

        `publish_lock` (narrow-critical-section mode): the caller froze
        the memtable with a flush and holds engine.compact_lock; the
        merge runs over the immutable file snapshot with writes flowing
        and the lock is taken only for the publish cut-over.
        """
        now_s = epoch_now() if now is None else now
        # pv<0 / pidx>pv -> no stale-split dropping (keep), per
        # check_if_stale_split_data.
        do_validate = bool(validate_hash and partition_version >= 0
                           and pidx <= partition_version)

        # bulk block-level path (the GB/s shape): a pure-L1 store needs
        # no merge, so whole columnar blocks are evaluated in a handful
        # of stacked programs and surviving rows rewritten with numpy
        # gathers — no per-record Python. Custom rules callables without
        # a parsed ruleset fall back to the merge path.
        operations = getattr(rules_filter, "operations", None)
        if (self.lsm.bulk_compact_eligible()
                and (rules_filter is None or operations is not None)):
            self._compact_with_epilogue(
                lambda: self._manual_compact_bulk(
                    now_s, default_ttl, pidx, partition_version,
                    do_validate, operations, publish_lock=publish_lock),
                advance_watermark=publish_lock is None)
            return

        def record_filter(keys: List[bytes], ets: List[int]):
            n = len(keys)
            # Stage 1 — default-TTL rewrite (reference does this FIRST and
            # hands the rewritten value to the user rules, Filter():72-79).
            ets_arr = np.asarray(ets, dtype=np.uint32)
            if default_ttl:
                ets_arr = np.where(ets_arr == 0,
                                   np.uint32(now_s + default_ttl), ets_arr)
            # Stage 2 — user-specified rules see the rewritten TTLs.
            if rules_filter is not None:
                rule_drop, ets_arr = rules_filter(keys, ets_arr, now_s)
                ets_arr = np.asarray(ets_arr, dtype=np.uint32)
            else:
                rule_drop = np.zeros(n, dtype=bool)
            # Stage 3 — expiry + stale-split drop on device (default_ttl=0:
            # the rewrite already happened; a rule that cleared a TTL must
            # not be re-stamped).
            # power-of-two capacity bucket: arbitrary tail-batch sizes
            # would each compile their own XLA program
            cap = 1024
            while cap < n:
                cap <<= 1
            block = build_record_block(keys, ets_arr, capacity=cap)
            drop, new_ets = compaction_filter_block(
                np.asarray(block.keys), np.asarray(block.key_len),
                np.asarray(block.hashkey_len), np.asarray(block.expire_ts),
                np.asarray(block.valid),
                np.uint32(now_s), np.uint32(0),
                np.uint32(pidx),
                np.uint32(max(partition_version, 0)),
                do_validate)
            # stay LAZY: combining on device keeps the result an async
            # jax value, so the LSM's double-buffered compaction really
            # overlaps this batch's device work with the next batch's
            # host gathering (materialization happens at drain)
            import jax.numpy as jnp

            drop = jnp.logical_or(drop[:n], jnp.asarray(rule_drop))
            return drop, new_ets[:n]

        self._compact_with_epilogue(
            lambda: self.lsm.compact(
                record_filter=record_filter,
                patch_headers=self.values_carry_expire_header,
                publish_lock=publish_lock,
                meta={
                    # see _manual_compact_bulk: snapshot mode covers
                    # only the freeze-time watermark
                    "last_flushed_decree": (
                        self.last_flushed_decree
                        if publish_lock is not None
                        else self.last_committed_decree),
                    "data_version": self.data_version,
                    "manual_compact_finish_time": epoch_now(),
                }),
            advance_watermark=publish_lock is None)

    def _compact_with_epilogue(self, body,
                               advance_watermark: bool = True) -> None:
        """Shared post-compaction bookkeeping for both compaction paths:
        advance the flushed watermark (everything committed is now in
        the SSTs), truncate the WAL, and record metrics.

        `advance_watermark=False` (snapshot-mode compaction): writes
        flowed DURING the merge, so committed > covered — the freeze
        flush already advanced the watermark and truncated the WAL for
        everything the compaction merged, and the newer writes' WAL
        frames must survive for crash recovery."""
        import time as _time

        t0 = _time.perf_counter()
        body()
        if advance_watermark:
            self.last_flushed_decree = self.last_committed_decree
            self.wal.truncate()
        self._ev_compact_count.increment()
        self._ev_compact_ms.set((_time.perf_counter() - t0) * 1000.0)
        self._ev_compact_bytes.increment(sum(
            os.path.getsize(t.path) for t in self.lsm.l1_runs))
