"""Per-SSTable bloom filters — the missing filter layer under point reads.

Role parity: RocksDB's full-file bloom filters behind
`pegasus_server_impl` (the reference rides
`BlockBasedTableOptions::filter_policy`); CompassDB (PAPERS.md) is the
measured case for how far a per-run membership structure moves
point-read tails. Every SST writer builds one filter over the table's
FULL keys at finish — vectorized: the per-block key matrices are hashed
with ONE `crc64_rows` pass each (the same batched crc64 the hash_lo
column and the probe path use), and the k bit positions per key derive
by double hashing from that single 64-bit value, so no per-key Python
runs at any table size.

Probe contract: `may_contain*` returning False is definitive (the key
is NOT in the table — a run/block lookup can be skipped); True means
"maybe" at the configured false-positive rate (~0.8% at the default
10 bits/key with k=7). Files written before this layer existed carry no
filter and degrade to the unfiltered path.

Knobs (`[pegasus.server]`): `bloom_bits_per_key` (build-time; 0 turns
filter building off), `bloom_probe` (mutable probe-time kill switch —
bench baselines measure against it).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from pegasus_tpu.base.crc import crc64
from pegasus_tpu.utils.flags import FLAGS, define_flag

define_flag("pegasus.server", "bloom_bits_per_key", 10,
            "bloom filter bits per key for new SST files (0 = no filters)",
            mutable=True)
define_flag("pegasus.server", "bloom_probe", True,
            "consult SST bloom filters on the point-read path",
            mutable=True)


def bloom_build_bits() -> int:
    return int(FLAGS.get("pegasus.server", "bloom_bits_per_key"))


def bloom_probe_enabled() -> bool:
    return bool(FLAGS.get("pegasus.server", "bloom_probe"))


def _num_probes(bits_per_key: int) -> int:
    # k = bits_per_key * ln2, the standard optimum; clamped like RocksDB
    return max(1, min(30, int(round(bits_per_key * 0.69))))


class BloomFilter:
    """m bits + k double-hashed probes per key.

    Bit positions: g_i = (h + i * delta) mod m with h = crc64(full key)
    and delta = (h >> 17) | 1 (odd — coprime with the power-of-two m,
    so the probe sequence walks the whole bit space). m is rounded UP
    to a power of two: every mod becomes a mask, and the scalar probe
    (the 1-4-key flush shape) walks `idx = (idx + delta) & mask` with
    no multiplies — measured ~3x cheaper per probe than the general-m
    form, and the extra bits only lower the false-positive rate. Both
    the build and the batch probe are single vectorized numpy passes
    over uint64 hash columns.
    """

    __slots__ = ("bits", "m", "k", "_scalar_bits")

    def __init__(self, bits: np.ndarray, m: int, k: int) -> None:
        self.bits = bits  # uint8[m // 8]
        self.m = m
        self.k = k
        # lazily-materialized bytes twin for scalar probes (python
        # bytes indexing returns an int with no numpy boxing — the
        # 1-4-key flush shape probes scalar)
        self._scalar_bits: Optional[bytes] = None

    @staticmethod
    def build(hashes: np.ndarray, bits_per_key: int) -> "BloomFilter":
        """One filter over `hashes` (uint64[n] crc64 of each full key)."""
        n = int(hashes.shape[0])
        m = 64
        while m < n * bits_per_key:  # next power of two >= n * bpk
            m <<= 1
        k = _num_probes(bits_per_key)
        bits = np.zeros(m // 8, dtype=np.uint8)
        h = hashes.astype(np.uint64, copy=False)
        delta = (h >> np.uint64(17)) | np.uint64(1)
        mask = np.uint64(m - 1)
        for i in range(k):
            idx = (h + np.uint64(i) * delta) & mask
            np.bitwise_or.at(
                bits, (idx >> np.uint64(3)).astype(np.int64),
                (np.uint8(1) << (idx & np.uint64(7)).astype(np.uint8)))
        return BloomFilter(bits, m, k)

    def may_contain_hashes(self, hashes: np.ndarray) -> np.ndarray:
        """bool[n] for a batch of full-key crc64 hashes — ONE vectorized
        pass answers every probe of a read flush against this table.
        All k probe positions evaluate as one [k, n] broadcast chain
        (~8 numpy dispatches total, k-independent — the per-k loop form
        paid ~5 dispatches per probe and lost to scalar code below
        ~50 keys)."""
        h = hashes.astype(np.uint64, copy=False)
        delta = (h >> np.uint64(17)) | np.uint64(1)
        ks = np.arange(self.k, dtype=np.uint64)
        idx = (h[None, :] + ks[:, None] * delta[None, :]) \
            & np.uint64(self.m - 1)
        probes = (self.bits[(idx >> np.uint64(3)).astype(np.int64)]
                  >> (idx & np.uint64(7)).astype(np.uint8)) & 1
        return probes.all(axis=0)

    def may_contain_hash(self, h: int) -> bool:
        """Scalar probe (solo gets and small flush prunes;
        h = crc64(full key) as a python int). The masked incremental
        walk is the same g_i sequence as the vectorized form: with m a
        power of two, (h + i*delta) mod m == ((h mod m) + i*(delta mod
        m)) mod m."""
        h = int(h)
        mask = self.m - 1
        delta = ((h >> 17) | 1) & mask
        idx = h & mask
        bits = self._scalar_bits
        if bits is None:
            bits = self._scalar_bits = self.bits.tobytes()
        for _ in range(self.k):
            if not (bits[idx >> 3] >> (idx & 7)) & 1:
                return False
            idx = (idx + delta) & mask
        return True

    def may_contain(self, key: bytes) -> bool:
        return self.may_contain_hash(crc64(key))

    def to_bytes(self) -> bytes:
        return self.bits.tobytes()

    @property
    def contiguous_bits(self) -> np.ndarray:
        """C-contiguous bits for the native multi-probe (a view over an
        encrypted-store read buffer may be fine already; mmap-backed
        frombuffer views are contiguous by construction)."""
        if not self.bits.flags["C_CONTIGUOUS"]:
            self.bits = np.ascontiguousarray(self.bits)
        return self.bits

    @staticmethod
    def from_bytes(raw, m: int, k: int) -> Optional["BloomFilter"]:
        bits = np.frombuffer(raw, dtype=np.uint8)
        if bits.shape[0] * 8 != m or k < 1:
            return None  # torn/mismatched filter: degrade to unfiltered
        return BloomFilter(bits, m, k)


class MultiProbe:
    """Every filter of one partition's run set, probed in ONE pass.

    The planner's flush carries 1-4 disk-bound keys per partition, and
    a deep-L0 store holds 8-16+ filters — per-(key, filter) python
    probe walks cost ~1.4 us each, rivaling the block probes they
    exist to skip. This precomputes the filters' geometry columns
    (bit-array addresses, masks, k's) once per store generation, and
    `probe` answers the whole (keys x filters) matrix with ONE native
    call (`pegasus_bloom_probe_multi`, ~20 ns per pair). Holding
    `filters` keeps every bit array alive for the address column.

    Returns row-major bytes: result[key_i * n + filter_t] is 1 iff
    key i may be present in filter t (indexable at python-int speed).
    """

    __slots__ = ("filters", "n", "_native", "_addrs", "_masks", "_ks")

    def __init__(self, filters) -> None:
        self.filters = list(filters)
        self.n = len(self.filters)
        try:
            from pegasus_tpu.native import bloom_probe_multi_fn

            self._native = bloom_probe_multi_fn()
        except Exception:  # noqa: BLE001 - scalar fallback below
            self._native = None
        if self._native is not None:
            self._addrs = np.array(
                [f.contiguous_bits.ctypes.data for f in self.filters],
                dtype=np.uint64)
            self._masks = np.array([f.m - 1 for f in self.filters],
                                   dtype=np.uint64)
            self._ks = np.array([f.k for f in self.filters],
                                dtype=np.int32)

    def probe(self, hashes: np.ndarray) -> bytes:
        n_keys = len(hashes)
        if self._native is not None:
            out = np.empty(n_keys * self.n, dtype=np.uint8)
            self._native(self._addrs, self._masks, self._ks, self.n,
                         np.ascontiguousarray(hashes, dtype=np.uint64),
                         n_keys, out)
            return out.tobytes()
        out = bytearray(n_keys * self.n)
        for i in range(n_keys):
            h = int(hashes[i])
            base = i * self.n
            for t, f in enumerate(self.filters):
                out[base + t] = f.may_contain_hash(h)
        return bytes(out)
