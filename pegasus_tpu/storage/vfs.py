"""Fault-injectable VFS: the storage layers' single door to the disk.

Every durable-data file open (SSTables, the storage WAL, the private
mutation log, learning file transfers) routes through
`open_data_file()` here, which layers disk-fault injection over the
at-rest-encryption layer (storage/efile.py). With no fail points armed
this module is a pass-through — the hot path pays one boolean check at
OPEN time, nothing per read/write.

Fault model (parity: the reference's disk-fault fail points around
aio/log writes — fail_point.h sites in replication_app_base.cpp and
mutation_log.cpp, exercised by the .act 200-series): four named
injection sites interpreted by this layer, armed through the global
FAIL_POINTS registry with the standard mini-language (so '<N>%' rate
prefixes and seeded replay come for free):

    vfs::open    return(eio)                    open fails
    vfs::read    return(bit_flip | eio)         flip one seeded bit /
                                                fail the read
    vfs::write   return(torn_write | eio |      persist a seeded prefix
                        enospc | bit_flip)      then fail / fail / fail
                                                with ENOSPC / corrupt
                                                one seeded bit in flight
    vfs::fsync   return(eio)                    fsync fails

All randomness (WHICH bit flips, HOW MUCH of a torn write survives)
draws from FAIL_POINTS' seeded RNG, so a chaos run replays exactly from
`FAIL_POINTS.seed(n)`. A torn write persists a strict prefix and then
raises EIO — the on-disk state a crash mid-write leaves behind, which
the framed-log torn-tail recovery must absorb.

Cluster arming: `disk_fault_plan` in cluster.json (the disk twin of the
network `fault_plan`), e.g.

    {"seed": 7, "points": {"vfs::write": "2%return(torn_write)",
                           "vfs::fsync": "1%return(eio)"}}

installed at node boot by `install_disk_faults()`.

NOTE: plaintext SSTables are mmapped by their reader, so `vfs::read`
does not intercept block reads there (it does intercept the framed
logs and encrypted stores). On-disk SST corruption is injected by
flipping file bytes directly (kill_test --mode corrupt) — the mmap
serves the flipped bytes and the per-block crc32 catches them.
"""

from __future__ import annotations

import errno
import os

from pegasus_tpu.storage import efile
from pegasus_tpu.utils.fail_point import FAIL_POINTS

FP_OPEN = "vfs::open"
FP_READ = "vfs::read"
FP_WRITE = "vfs::write"
FP_FSYNC = "vfs::fsync"


def install_disk_faults(plan: dict) -> None:
    """Arm the vfs fail points from a cluster.json `disk_fault_plan`."""
    FAIL_POINTS.setup()
    if "seed" in plan:
        FAIL_POINTS.seed(int(plan["seed"]))
    for name, action in (plan.get("points") or {}).items():
        FAIL_POINTS.cfg(name, action)


def _flip_one_bit(data: bytes) -> bytes:
    """Corrupt one seeded bit — the single-event-upset shape."""
    if not data:
        return data
    pos = int(FAIL_POINTS.rand() * len(data)) % len(data)
    bit = int(FAIL_POINTS.rand() * 8) % 8
    out = bytearray(data)
    out[pos] ^= 1 << bit
    return bytes(out)


def _err(code: int, site: str) -> OSError:
    return OSError(code, f"injected fault ({site})")


class FaultyFile:
    """Wraps a data file with the vfs fault sites. Exposes exactly the
    surface the storage layers use (read/write/seek/tell/truncate/
    flush/fileno/close + context management); fsync is intercepted via
    `fsync_file()` below, which all storage callers route through."""

    def __init__(self, f) -> None:
        self._f = f

    # -- data ------------------------------------------------------------
    def read(self, n: int = -1) -> bytes:
        act = FAIL_POINTS.inject(FP_READ)
        data = self._f.read(n) if act != "eio" else None
        if act == "eio":
            raise _err(errno.EIO, FP_READ)
        if act == "bit_flip":
            return _flip_one_bit(data)
        return data

    def write(self, data) -> int:
        act = FAIL_POINTS.inject(FP_WRITE)
        if act == "eio":
            raise _err(errno.EIO, FP_WRITE)
        if act == "enospc":
            raise _err(errno.ENOSPC, FP_WRITE)
        if act == "torn_write" and len(data) > 0:
            # a strict prefix lands, then the write "crashes": the
            # durable state recovery has to truncate past. Flush so the
            # torn bytes really reach the OS before the error unwinds
            # whatever buffering sits above.
            keep = int(FAIL_POINTS.rand() * len(data)) % len(data)
            self._f.write(bytes(data[:keep]))
            self._f.flush()
            raise _err(errno.EIO, FP_WRITE)
        if act == "bit_flip" and len(data) > 0:
            self._f.write(_flip_one_bit(bytes(data)))
            return len(data)
        self._f.write(data)
        return len(data)

    # -- passthrough ------------------------------------------------------
    def seek(self, off: int, whence: int = os.SEEK_SET) -> int:
        return self._f.seek(off, whence)

    def tell(self) -> int:
        return self._f.tell()

    def truncate(self, size=None):
        return (self._f.truncate() if size is None
                else self._f.truncate(size))

    def flush(self) -> None:
        self._f.flush()

    def fileno(self) -> int:
        return self._f.fileno()

    def close(self) -> None:
        self._f.close()

    @property
    def closed(self) -> bool:
        return self._f.closed

    def __enter__(self) -> "FaultyFile":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _disk_faults_armed() -> bool:
    """True when any vfs::* point is configured. FAIL_POINTS is shared
    with the NETWORK FaultPlan (rpc/fault.py calls setup() too), so
    gating the wrap on the registry's global enabled bit would tax
    every disk IO of a network-only chaos run with a Python proxy."""
    if not FAIL_POINTS.enabled:
        return False
    return any(FAIL_POINTS.configured(site)
               for site in (FP_OPEN, FP_READ, FP_WRITE, FP_FSYNC))


def open_data_file(path: str, mode: str = "rb"):
    """The storage layers' open(): encryption-aware (efile) and, when
    a vfs fault site is armed, fault-wrapped. The no-disk-chaos path
    returns efile's file object untouched — zero per-IO overhead."""
    if not _disk_faults_armed():
        return efile.open_data_file(path, mode)
    if FAIL_POINTS.inject(FP_OPEN) == "eio":
        raise _err(errno.EIO, FP_OPEN)
    return FaultyFile(efile.open_data_file(path, mode))


def fsync_file(f) -> None:
    """fsync through the fault layer: storage durability points
    (SST finish, log gc, frame sync) call this instead of raw
    os.fsync so an injected fsync failure surfaces as the OSError a
    dying disk would produce."""
    if FAIL_POINTS.enabled and FAIL_POINTS.inject(FP_FSYNC) == "eio":
        raise _err(errno.EIO, FP_FSYNC)
    os.fsync(f.fileno())


def fsync_dir(path: str) -> None:
    """Directory-entry durability (post-rename), same fault site."""
    if FAIL_POINTS.enabled and FAIL_POINTS.inject(FP_FSYNC) == "eio":
        raise _err(errno.EIO, FP_FSYNC)
    dir_fd = os.open(path or ".", os.O_RDONLY)
    try:
        os.fsync(dir_fd)
    finally:
        os.close(dir_fd)


# the FaultyFile wrap is decided at OPEN time: a file opened before its
# site was armed keeps the raw handle (chaos plans arm at boot, before
# any store opens — the contract disk_fault_plan relies on)


# efile helpers re-exported so storage modules keep ONE import door
repair_truncate = efile.repair_truncate
logical_size = efile.logical_size
is_encrypted = efile.is_encrypted
copy_data_tree = efile.copy_data_tree
