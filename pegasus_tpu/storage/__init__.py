"""Per-partition storage engine.

The reference embeds RocksDB behind pegasus_server_impl
(src/server/rocksdb_wrapper.h:51, pegasus_server_impl_init.cpp). We build
our own LSM engine designed TPU-first: SST blocks are stored *columnar*
(padded key-byte matrix + expire_ts column + value heap) so the scan and
compaction hot paths hand whole blocks to the device predicate kernels with
zero per-record host work.

Components:
  memtable  — sorted in-memory overlay with tombstones
  wal       — framed, crc-protected write-ahead log (the "private log"
              analogue at the storage layer)
  bloom     — per-SSTable bloom filters (the point-read filter layer)
  sstable   — columnar SST read/write
  lsm       — LSMStore: memtable + L0 runs + L1, flush/compaction, iterators
  engine    — StorageEngine: write batches with decree watermark discipline
              (parity: src/server/rocksdb_wrapper.cpp:205, base/meta_store.h)
"""

from pegasus_tpu.storage.memtable import Memtable, TOMBSTONE
from pegasus_tpu.storage.wal import WriteAheadLog, WalRecord, OP_PUT, OP_DEL
from pegasus_tpu.storage.bloom import BloomFilter
from pegasus_tpu.storage.sstable import SSTable, SSTableWriter, BLOCK_CAPACITY
from pegasus_tpu.storage.lsm import LSMStore
from pegasus_tpu.storage.engine import StorageEngine, WriteBatchItem
