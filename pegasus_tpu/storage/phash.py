"""Perfect-hash two-level SST index: key-hash -> (block, slot) in one probe.

Role parity: CompassDB's perfect-hash point-read index (PAPERS.md) —
the measured case for replacing the bloom + index-bisect pair with ONE
resident structure that answers key -> location in a single hash pass.
PR 4's blooms prune *tables*, but every key that passes a filter still
pays a block-fence bisect plus an in-block bisect (and, on hot blocks,
a materialized Python key list). This index answers both questions at
once: a miss dies with ZERO block touches (definitive absent, exactly
like a bloom negative), and a hit goes straight to its (block, slot)
row — no fence bisect, no searchsorted, no key-list materialization.

Construction (CHD — compress, hash, displace):

    mix(h, seed)  splitmix-style finalizer over the crc64 full-key hash
                  the bloom path already computes (ONE shared hash pass)
    bucket        (x >> 32) % nb         (nb ~ n/4 buckets)
    position(d)   (p0 + d * delta) % ts  (ts ~ n/0.85 slots, odd)
    entry         fp(10 bits) | loc(22 bits)   per occupied slot

Buckets are placed in decreasing-size order; each bucket searches the
smallest displacement d (uint16) under which all of its keys land on
distinct empty slots. The displacement array (one u16 per bucket) plus
the slot array (one u32 per slot) is the WHOLE index: ~5.2 bytes/key
at the default geometry, replacing the bloom bits + the per-key resident
bisect state (key lists / probe tables charge ~64+ bytes/row once a
block turns hot) for point-read working sets.

`loc` packs (block_idx << slot_bits) | slot, where `slot` is the row
index inside the DECODED block — stable across the `none`/`dcz`/`dcz2`
codecs because decode reproduces row order byte-for-byte (including
dcz2's overflow rows), and stable across the verbatim-copy / native
subset compaction paths because every writer builds a fresh index from
its own per-block hash columns in append order.

Probing an absent key lands on an empty slot or a fingerprint mismatch
(definitive absent — if the key were present, the build would have
placed it at exactly this slot). A fingerprint COLLISION (~0.08%:
occupied slot, matching 10-bit fp, different key) surfaces as a located
row whose key does not match; callers must verify the row's key before
serving, which makes a collision one wasted block touch, never a wrong
answer.

Construction can fail (adversarial key sets, crc64 hash collisions,
oversized loc geometry): bounded seed retries, then the run is stamped
"no phash" and serves via bloom + bisect — a perf event
(`phash_build_fail_count`), never a correctness event.

Knobs (`[pegasus.server]`): `phash_index` (build-time), `phash_probe`
(mutable probe-time kill switch), `phash_force_fail` (deterministic
fail point for fallback tests).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from pegasus_tpu.utils.flags import FLAGS, define_flag
from pegasus_tpu.utils.metrics import METRICS

define_flag("pegasus.server", "phash_index", True,
            "build a perfect-hash (block, slot) index into new SST "
            "files at every writer finish site (flush / merge-compact "
            "/ bulk-compact / ingest); files without one keep serving "
            "via bloom + bisect", mutable=True)
define_flag("pegasus.server", "phash_probe", True,
            "consult SST perfect-hash indexes on the point-read path "
            "(misses die with zero block touches; hits skip both "
            "bisects)", mutable=True)
define_flag("pegasus.server", "phash_force_fail", False,
            "fail point: force every perfect-hash build to fail, "
            "exercising the bloom+bisect fallback deterministically",
            mutable=True)


def phash_build_enabled() -> bool:
    return bool(FLAGS.get("pegasus.server", "phash_index"))


def phash_probe_enabled() -> bool:
    return bool(FLAGS.get("pegasus.server", "phash_probe"))


# node-wide observability (the bloom counters' siblings): useful =
# definitive-absent answers that skipped every block touch; hit = keys
# located straight to (block, slot); build_fail = runs stamped
# "no phash" after the bounded seed retries
_STORAGE = METRICS.entity("storage", "node")
PHASH_USEFUL = _STORAGE.relaxed_counter("phash_useful_count")
PHASH_HIT = _STORAGE.relaxed_counter("phash_hit_count")
PHASH_BUILD_FAIL = _STORAGE.relaxed_counter("phash_build_fail_count")

PHASH_VERSION = 1
KNOWN_PHASH_VERSIONS = (1,)

_M64 = (1 << 64) - 1
_GOLDEN = 0x9E3779B97F4A7C15
_MIXK = 0xFF51AFD7ED558CCD
FP_BITS = 10
LOC_BITS = 22
LOC_MASK = (1 << LOC_BITS) - 1
EMPTY = 0xFFFFFFFF  # empty slot sentinel (also the probe's absent code)
ABSENT = 0xFFFFFFFF
_D_MAX = 1 << 16    # displacement is a uint16
_SEED_TRIES = 3


def _mix_arr(hashes: np.ndarray, seed: int) -> np.ndarray:
    """uint64[n] seed-keyed finalizer — bit-identical to the native
    kernel's phash_mix (the mixer is part of the on-disk format; the
    seed is stored in the index header)."""
    smul = np.uint64((_GOLDEN * (seed + 1)) & _M64)
    x = hashes.astype(np.uint64, copy=False) ^ smul
    x = x ^ (x >> np.uint64(33))
    x = x * np.uint64(_MIXK)
    return x ^ (x >> np.uint64(29))


def _mix_int(h: int, seed: int) -> int:
    x = (h ^ ((_GOLDEN * (seed + 1)) & _M64)) & _M64
    x ^= x >> 33
    x = (x * _MIXK) & _M64
    x ^= x >> 29
    return x


# (bucket, base position, step) from a mixed hash — Lemire
# multiply-shift reductions (one multiply where a `%` costs a divide;
# the native kernel's measured bottleneck was exactly these divisions)
# plus the ONE remaining modular step the displacement walk needs.
# These formulas are FORMAT: the native kernel's phash_bpd mirrors
# them bit-for-bit, and the stored seed/ts/nb only mean anything under
# them. With a PRIME ts every delta in [1, ts-1] is coprime, so
# (p0 + d*delta) % ts reaches the whole table.

def _bpd_int(x: int, ts: int, nb: int):
    bucket = ((x >> 32) * nb) >> 32
    p0 = ((x & 0xFFFFFFFF) * ts) >> 32
    delta = 1 + ((((x >> 17) & 0xFFFFFFFF) * (ts - 1)) >> 32)
    return bucket, p0, delta


def _bpd_arr(x: np.ndarray, ts: int, nb: int):
    lo32 = np.uint64(0xFFFFFFFF)
    s32 = np.uint64(32)
    bucket = ((x >> s32) * np.uint64(nb)) >> s32
    p0 = ((x & lo32) * np.uint64(ts)) >> s32
    delta = np.uint64(1) + (
        (((x >> np.uint64(17)) & lo32) * np.uint64(ts - 1)) >> s32)
    return bucket.astype(np.int64), p0.astype(np.int64), \
        delta.astype(np.int64)


def _next_prime(m: int) -> int:
    """Smallest prime >= m (trial division — m is bounded by the L1
    run capacity, so sqrt(m) stays a few hundred)."""
    if m <= 2:
        return 2
    m |= 1
    while True:
        d = 3
        while d * d <= m:
            if m % d == 0:
                break
            d += 2
        else:
            return m
        m += 2


def _geometry(n: int) -> Tuple[int, int]:
    """(table_size, n_buckets): ~0.85 load over a PRIME slot count,
    ~4 keys/bucket. Primality is load-bearing, not cosmetic: with a
    composite ts a key whose delta shares a large factor can only
    reach ts/gcd slots — a size-2 bucket whose key cycles through 5
    occupied positions is unplaceable at ANY displacement (observed at
    ts=825, gcd 165). A prime ts makes every delta coprime, so each
    key's probe sequence covers the whole table."""
    ts = _next_prime(max(3, (20 * n + 16) // 17))  # ceil(n / 0.85)
    nb = max(1, (n + 3) // 4)
    return ts, nb


class PHashIndex:
    """One run's CHD index: `slots` uint32[ts] (fp|loc entries, EMPTY
    for unoccupied), `disp` uint16[nb], plus the geometry the probe
    recomputes positions from."""

    __slots__ = ("slots", "disp", "ts", "nb", "seed", "slot_bits", "n")

    def __init__(self, slots: np.ndarray, disp: np.ndarray, seed: int,
                 slot_bits: int, n: int) -> None:
        self.slots = slots
        self.disp = disp
        self.ts = int(slots.shape[0])
        self.nb = int(disp.shape[0])
        self.seed = seed
        self.slot_bits = slot_bits
        self.n = n

    # ---- build ---------------------------------------------------------

    @staticmethod
    def build(hashes: np.ndarray, block_counts: List[int]
              ) -> Optional["PHashIndex"]:
        """Index over a finished run: `hashes` uint64[n] crc64 full-key
        hashes in FILE ORDER (the bloom's hash columns, concatenated),
        `block_counts` the per-block row counts in the same order.
        Returns None on construction failure (callers stamp "no phash"
        and tick `phash_build_fail_count` — never an error)."""
        n = int(hashes.shape[0])
        if n == 0 or sum(block_counts) != n:
            return None
        if bool(FLAGS.get("pegasus.server", "phash_force_fail")):
            return None
        counts = np.asarray(block_counts, dtype=np.int64)
        slot_bits = max(1, int(counts.max() - 1).bit_length())
        block_bits = max(1, int(len(block_counts) - 1).bit_length())
        if slot_bits + block_bits > LOC_BITS:
            return None  # run too large for the packed loc — fall back
        starts = np.zeros(len(block_counts) + 1, dtype=np.int64)
        np.cumsum(counts, out=starts[1:])
        block_ids = np.repeat(np.arange(len(block_counts),
                                        dtype=np.int64), counts)
        slot_in_block = (np.arange(n, dtype=np.int64)
                         - np.repeat(starts[:-1], counts))
        locs = ((block_ids << slot_bits) | slot_in_block).astype(np.uint32)
        h = np.ascontiguousarray(hashes, dtype=np.uint64)
        ts, nb = _geometry(n)
        from pegasus_tpu import native

        build = native.phash_build_fn()
        for seed in range(_SEED_TRIES):
            if build is not None:
                res = build(h, locs, seed, ts, nb)
            else:
                res = _build_once_py(h, locs, seed, ts, nb)
            if res is not None:
                slots, disp = res
                return PHashIndex(slots, disp, seed, slot_bits, n)
        return None

    # ---- probe ---------------------------------------------------------

    def lookup_hash(self, h: int) -> int:
        """Scalar probe (the solo-get path, sharing the batched
        kernel's crc64 hash): packed loc (block << slot_bits | slot),
        or -1 for a definitive absent. A returned loc may still be a
        fingerprint collision — the caller verifies the row's key."""
        x = _mix_int(int(h), self.seed)
        ts = self.ts
        bucket, p0, delta = _bpd_int(x, ts, self.nb)
        pos = (p0 + int(self.disp[bucket]) * delta) % ts
        e = int(self.slots[pos])
        if e == EMPTY or (e >> LOC_BITS) != (x >> (64 - FP_BITS)):
            return -1
        return e & LOC_MASK

    def probe_hashes(self, hashes: np.ndarray) -> np.ndarray:
        """uint32[n] packed locs (ABSENT = definitive miss) — ONE
        vectorized pass answers a whole read flush against this run."""
        x = _mix_arr(hashes, self.seed)
        b, p0, delta = _bpd_arr(x, self.ts, self.nb)
        d = self.disp[b].astype(np.int64)
        pos = (p0 + d * delta) % self.ts
        e = self.slots[pos]
        fp = (x >> np.uint64(64 - FP_BITS)).astype(np.uint32)
        ok = (e != np.uint32(EMPTY)) & ((e >> np.uint32(LOC_BITS)) == fp)
        return np.where(ok, e & np.uint32(LOC_MASK), np.uint32(ABSENT))

    def unpack(self, loc: int) -> Tuple[int, int]:
        """packed loc -> (block_idx, slot)."""
        return loc >> self.slot_bits, loc & ((1 << self.slot_bits) - 1)

    # ---- persistence ---------------------------------------------------

    def to_bytes(self) -> bytes:
        # u32 slots FIRST, u16 disp after: with the blob start 4-byte
        # aligned (the writer pads to a boundary) every section meets
        # its natural alignment, so the mmap-backed frombuffer views
        # hand the native probe pointers it may legally dereference
        return self.slots.tobytes() + self.disp.tobytes()

    def meta(self) -> dict:
        """The index-JSON header naming geometry + format version
        (version gates open exactly like the block codec: readers
        without this version refuse the file, never misparse)."""
        return {"version": PHASH_VERSION, "n": self.n, "ts": self.ts,
                "nb": self.nb, "seed": self.seed,
                "slot_bits": self.slot_bits}

    def mem_bytes(self) -> int:
        return self.disp.nbytes + self.slots.nbytes

    @staticmethod
    def from_bytes(raw, meta: dict) -> Optional["PHashIndex"]:
        """None on torn/mismatched geometry (degrade to bloom+bisect,
        like a torn bloom). Unknown VERSIONS are the caller's refusal
        (sstable open), not a degrade. A buffer whose base address is
        not 4-byte aligned (the writer pads new files, but encrypted
        reads / foreign buffers make no promise) is copied once —
        the native probe dereferences these as u32/u16 and a
        misaligned pointer is UB (SIGBUS on strict-alignment
        targets)."""
        nb, ts = int(meta["nb"]), int(meta["ts"])
        if len(raw) != 2 * nb + 4 * ts:
            return None
        buf = np.frombuffer(raw, dtype=np.uint8)
        if buf.ctypes.data % 4:
            buf = buf.copy()
        slots = np.frombuffer(buf, dtype=np.uint32, count=ts)
        disp = np.frombuffer(buf, dtype=np.uint16, count=nb,
                             offset=4 * ts)
        return PHashIndex(slots, disp, int(meta["seed"]),
                          int(meta["slot_bits"]), int(meta["n"]))

    @property
    def contiguous_slots(self) -> np.ndarray:
        if not self.slots.flags["C_CONTIGUOUS"]:
            self.slots = np.ascontiguousarray(self.slots)
        return self.slots

    @property
    def contiguous_disp(self) -> np.ndarray:
        if not self.disp.flags["C_CONTIGUOUS"]:
            self.disp = np.ascontiguousarray(self.disp)
        return self.disp


def _build_once_py(hashes: np.ndarray, locs: np.ndarray, seed: int,
                   ts: int, nb: int):
    """Python CHD build, bit-identical to pegasus_phash_build (same
    bucket order, same displacement search) — the no-toolchain
    fallback. The loop is per BUCKET (~n/4 iterations), not per key;
    the native kernel is the production path."""
    x = _mix_arr(hashes, seed)
    fp = (x >> np.uint64(64 - FP_BITS)).astype(np.uint32)
    entries = (fp << np.uint32(LOC_BITS)) | locs
    if bool((entries == np.uint32(EMPTY)).any()):
        return None  # an entry colliding with the sentinel: reseed
    bucket, p0, delta = _bpd_arr(x, ts, nb)
    order = np.argsort(bucket, kind="stable")
    counts = np.bincount(bucket, minlength=nb)
    starts = np.zeros(nb + 1, dtype=np.int64)
    np.cumsum(counts, out=starts[1:])
    # decreasing size, bucket id breaking ties — big buckets place
    # while the table is empty (the CHD ordering that makes high load
    # factors reachable)
    border = np.lexsort((np.arange(nb), -counts))
    slots = np.full(ts, EMPTY, dtype=np.uint32)
    disp = np.zeros(nb, dtype=np.uint16)
    for b in border:
        c = int(counts[b])
        if c == 0:
            continue
        ks = order[starts[b]:starts[b] + c]
        kp0 = p0[ks]
        kd = delta[ks]
        ke = entries[ks]
        for d in range(_D_MAX):
            pos = (kp0 + d * kd) % ts
            if c > 1 and len(set(pos.tolist())) < c:
                continue
            if (slots[pos] == np.uint32(EMPTY)).all():
                slots[pos] = ke
                disp[b] = d
                break
        else:
            return None
    return slots, disp


class PHashMultiProbe:
    """Every perfect-hash index of one partition's run set, probed in
    ONE pass — the sibling of storage.bloom.MultiProbe: the planner's
    flush hashes its disk-bound keys once and `probe` answers the whole
    (keys x indexed runs) LOCATION matrix with one native call
    (`pegasus_phash_probe_multi`). Returns row-major uint32 locs:
    out[key_i * n + table_t] is the packed (block << slot_bits | slot),
    or ABSENT for a definitive miss. Holding `indexes` keeps the slot
    arrays alive for the address columns."""

    __slots__ = ("indexes", "n", "slot_bits", "_native", "_slots_addrs",
                 "_disp_addrs", "_ts", "_nb", "_seeds", "_fixed_ptrs")

    def __init__(self, indexes) -> None:
        self.indexes = list(indexes)
        self.n = len(self.indexes)
        self.slot_bits = [ix.slot_bits for ix in self.indexes]
        try:
            from pegasus_tpu.native import phash_probe_multi_fn

            self._native = phash_probe_multi_fn()
        except Exception:  # noqa: BLE001 - vectorized fallback below
            self._native = None
        if self._native is not None:
            self._slots_addrs = np.array(
                [ix.contiguous_slots.ctypes.data for ix in self.indexes],
                dtype=np.uint64)
            self._disp_addrs = np.array(
                [ix.contiguous_disp.ctypes.data for ix in self.indexes],
                dtype=np.uint64)
            self._ts = np.array([ix.ts for ix in self.indexes],
                                dtype=np.uint64)
            self._nb = np.array([ix.nb for ix in self.indexes],
                                dtype=np.uint64)
            self._seeds = np.array([ix.seed for ix in self.indexes],
                                   dtype=np.uint64)
            # raw pointers of the IMMUTABLE per-probe arrays, resolved
            # once: each `.ctypes.data` access costs ~0.4 us, and the
            # per-generation probe is called once per read flush —
            # five of the eight kernel args never change
            self._fixed_ptrs = (
                self._slots_addrs.ctypes.data,
                self._disp_addrs.ctypes.data, self._ts.ctypes.data,
                self._nb.ctypes.data, self._seeds.ctypes.data)

    def probe(self, hashes: np.ndarray):
        """(loc cells, hit-mask bytes) for the whole matrix. The MASK
        is consumed as python bytes — the candidacy verdict per
        (key, table) cell at the same C-speed index read the bloom
        matrix costs — and the loc cells (a memoryview: plain-int
        reads, no numpy scalar boxing) are touched only for the rare
        located cells. The native kernel emits both in its one pass;
        the fallback derives the mask vectorized."""
        n_keys = len(hashes)
        out = np.empty(n_keys * self.n, dtype=np.uint32)
        if self._native is not None:
            hits = np.empty(n_keys * self.n, dtype=np.uint8)
            self._native(self._fixed_ptrs, self.n,
                         np.ascontiguousarray(hashes, dtype=np.uint64),
                         n_keys, out, hits)
            return memoryview(out), hits.tobytes()
        for t, ix in enumerate(self.indexes):
            out[t::self.n] = ix.probe_hashes(
                np.asarray(hashes, dtype=np.uint64))
        return (memoryview(out),
                (out != np.uint32(ABSENT)).tobytes())
