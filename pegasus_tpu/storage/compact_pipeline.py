"""Staged bulk-compaction pipeline: read → filter → write, overlapped.

LUDA's result (PAPERS.md) is that GPU-offloaded LSM compaction wins by
RESTRUCTURING compaction into overlapped stages, not by faster
per-stage kernels — the same shape Pegasus' bulk path wants: block
reads are disk-bound, filter evaluation is accelerator- or CPU-bound
(device programs for ruleset batches, raw-column numpy for encoded
blocks, the GIL-free native subset kernel downstream), and the
compressed-write stage is CPU+disk-bound. Serially they add; staged
they hide behind the slowest one.

Topology (one compaction = one pipeline; stages are threads, the
inter-stage queues are bounded so memory stays a few windows deep):

    READ thread    walks the L1 block entries in key order, reads the
                   raw/encoded block bytes (paced through the
                   CompactionGovernor token bucket — this is where
                   background IO meets the foreground-pressure
                   feedback), windows them
    FILTER thread  two-phase per window: submit the window's filter
                   programs (device or host XLA, per the placement
                   cost model; encoded blocks with key-free rulesets
                   evaluate host-direct off their raw predicate
                   columns), then drain the PREVIOUS window while this
                   one evaluates — the device lookahead the serial
                   path had, kept inside the stage
    WRITE (caller) the consuming generator feeds
                   LSMStore.bulk_compact_rewrite unchanged: subset
                   kernel, async SST writers, threaded finish, and the
                   manifest-then-unlink publish ordering all stay
                   exactly where they were

Because the queues are FIFO and the stages preserve entry order, the
rewrite consumes the identical (block, drop-mask) stream the serial
path would produce — pipelined output is byte-identical by
construction, and the bench/tests gate on a content digest to prove
it stays that way.

Mesh-filtered mode: when the table's blocks are resident on the
device mesh (parallel/mesh_resident.py), the engine pre-computes the
WHOLE store's drop masks in one SPMD dispatch before the pipeline
starts; every window then arrives at the filter stage pre-served (no
in-flight program, eager-forwarded straight to WRITE), so the
pipeline degrades gracefully to read → write with the governor still
pacing reads. Same (block, mask) stream, same bytes.

Shutdown: any stage exception travels down the queues and re-raises in
the consumer; closing the consumer generator (writer failure) sets the
stop event, unblocks both queues, and joins the threads — no daemon
thread keeps reading a store whose compaction already failed.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterator, List, Optional, Sequence

from pegasus_tpu.utils.flags import FLAGS, define_flag
from pegasus_tpu.utils.metrics import METRICS

define_flag("pegasus.storage", "compact_pipeline", True,
            "overlap bulk compaction's block-read / filter-eval / "
            "write stages on dedicated threads with bounded queues; "
            "off = the serial windowed path (same output bytes either "
            "way)", mutable=True)
define_flag("pegasus.storage", "compact_pipeline_window", 128,
            "blocks per pipeline window (the unit the stages hand "
            "each other); bounds per-window memory and the filter "
            "batch size — smaller windows feed the write-stage "
            "transform pool sooner (measured best 64-128 on the "
            "round-12 box)", mutable=True)
define_flag("pegasus.storage", "compact_pipeline_depth", 2,
            "windows each bounded inter-stage queue may hold — total "
            "in-flight memory is ~(2*depth + 2) windows", mutable=True)


def pipeline_enabled() -> bool:
    return bool(FLAGS.get("pegasus.storage", "compact_pipeline"))


def pipeline_window() -> int:
    return int(FLAGS.get("pegasus.storage", "compact_pipeline_window"))


def pipeline_depth() -> int:
    return int(FLAGS.get("pegasus.storage", "compact_pipeline_depth"))


def window_count(n_entries: int) -> int:
    """Windows a compaction over `n_entries` blocks will submit — the
    host filter stage pays one dispatch per window, which is the unit
    the mesh gate (ops/placement.mesh_compact_pays) weighs one
    whole-table SPMD dispatch against."""
    return max(1, -(-int(n_entries) // max(1, pipeline_window())))


def transform_workers() -> int:
    """Write-stage transform pool size: the subset kernel / gather
    work per block runs GIL-free, so the pipelined rewrite keeps up
    to cpu workers transforming ahead while the consumer thread
    appends in order (the consumer is mostly blocked on futures, so
    it does not need its own core)."""
    import os

    return max(2, min(4, os.cpu_count() or 2))


def stage_threads_enabled() -> bool:
    """Dedicated read/filter stage threads only pay when the box has
    cores for them: on a 2-core host the stage threads fight the
    GIL-free transform workers for the GIL slices they DO need
    (parse, mask numpy) and measurably slow the whole pipeline — the
    write-stage transform pool alone is the winning overlap there.
    4+ cores: full 3-stage topology."""
    import os

    return (os.cpu_count() or 2) >= 4


_ENT = METRICS.entity("storage", "node")
# stall = time a stage spent blocked on its neighbor's queue: the
# read stage stalls when write/filter are the bottleneck, the write
# stage stalls when disk reads are — together with the queue-depth
# gauges these say WHICH stage owns the critical path right now
_READ_STALL_MS = _ENT.relaxed_counter("compact_read_stall_ms")
_FILTER_STALL_MS = _ENT.relaxed_counter("compact_filter_stall_ms")
_WRITE_STALL_MS = _ENT.relaxed_counter("compact_write_stall_ms")
_READQ_DEPTH = _ENT.gauge("compact_readq_depth")
_FILTQ_DEPTH = _ENT.gauge("compact_filtq_depth")

_END = object()


class _StageError:
    __slots__ = ("exc",)

    def __init__(self, exc: BaseException) -> None:
        self.exc = exc


class CompactPipeline:
    """One pipelined bulk compaction.

    `load(entry)` runs on the READ thread per block entry;
    `submit(items)` / `drain(token)` run on the FILTER thread per
    window (submit dispatches without waiting, drain materializes —
    the pipeline keeps one window submitted ahead). The `results()`
    generator yields drained outputs in entry order on the caller's
    (write) thread.
    """

    def __init__(self, entries: Sequence, load: Callable,
                 submit: Callable[[List], object],
                 drain: Callable[[object], List],
                 window: int, depth: int = 2,
                 eager: Optional[Callable[[object], bool]] = None
                 ) -> None:
        self._entries = entries
        self._load = load
        self._submit = submit
        self._drain = drain
        # eager(token) True = this window has no asynchronously-
        # evaluating leg (all masks were computed at submit), so
        # holding it for the one-window device lookahead would only
        # starve the write stage — drain and forward it immediately
        self._eager = eager or (lambda _t: False)
        self._window = max(1, window)
        self._stop = threading.Event()
        self._q_read: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._q_filt: "queue.Queue" = queue.Queue(maxsize=max(1, depth))

    # ---- bounded-queue helpers that honor the stop event ---------------

    def _put(self, q: "queue.Queue", item, stall) -> bool:
        t0 = time.perf_counter()
        while not self._stop.is_set():
            try:
                q.put(item, timeout=0.05)
                waited = time.perf_counter() - t0
                if waited > 0.001:
                    stall.increment(int(waited * 1000))
                return True
            except queue.Full:
                continue
        return False

    def _get(self, q: "queue.Queue", stall):
        t0 = time.perf_counter()
        while not self._stop.is_set():
            try:
                item = q.get(timeout=0.05)
                waited = time.perf_counter() - t0
                if waited > 0.001:
                    stall.increment(int(waited * 1000))
                return item
            except queue.Empty:
                continue
        return _END

    # ---- stages ---------------------------------------------------------

    def _read_stage(self) -> None:
        try:
            w = self._window
            for off in range(0, len(self._entries), w):
                if self._stop.is_set():
                    return
                items = [self._load(e)
                         for e in self._entries[off:off + w]]
                _READQ_DEPTH.set(self._q_read.qsize())
                if not self._put(self._q_read, items, _READ_STALL_MS):
                    return
            self._put(self._q_read, _END, _READ_STALL_MS)
        except BaseException as e:  # noqa: BLE001 - travels to consumer
            self._put(self._q_read, _StageError(e), _READ_STALL_MS)

    def _filter_stage(self) -> None:
        pending = None
        try:
            while not self._stop.is_set():
                items = self._get(self._q_read, _FILTER_STALL_MS)
                if isinstance(items, _StageError):
                    if pending is not None:
                        self._put(self._q_filt, self._drain(pending),
                                  _FILTER_STALL_MS)
                        pending = None
                    self._put(self._q_filt, items, _FILTER_STALL_MS)
                    return
                if items is _END:
                    break
                token = self._submit(items)
                if pending is not None:
                    _FILTQ_DEPTH.set(self._q_filt.qsize())
                    if not self._put(self._q_filt, self._drain(pending),
                                     _FILTER_STALL_MS):
                        return
                    pending = None
                if self._eager(token):
                    if not self._put(self._q_filt, self._drain(token),
                                     _FILTER_STALL_MS):
                        return
                else:
                    pending = token
            if pending is not None and not self._stop.is_set():
                self._put(self._q_filt, self._drain(pending),
                          _FILTER_STALL_MS)
            self._put(self._q_filt, _END, _FILTER_STALL_MS)
        except BaseException as e:  # noqa: BLE001 - travels to consumer
            self._put(self._q_filt, _StageError(e), _FILTER_STALL_MS)

    # ---- consumer --------------------------------------------------------

    def results(self) -> Iterator:
        """Yield (entry-order) filter outputs; re-raises any stage
        failure. Closing the generator stops and joins the stages."""
        t_read = threading.Thread(target=self._read_stage,
                                  name="compact-read", daemon=True)
        t_filt = threading.Thread(target=self._filter_stage,
                                  name="compact-filter", daemon=True)
        t_read.start()
        t_filt.start()
        try:
            while True:
                outs = self._get(self._q_filt, _WRITE_STALL_MS)
                if outs is _END:
                    return
                if isinstance(outs, _StageError):
                    raise outs.exc
                yield from outs
        finally:
            self._stop.set()
            # unblock producers stuck on a full queue, then join —
            # the threads must not outlive the compaction that owns
            # the run handles they read from
            for q in (self._q_read, self._q_filt):
                try:
                    while True:
                        q.get_nowait()
                except queue.Empty:
                    pass
            t_read.join(timeout=5.0)
            t_filt.join(timeout=5.0)
