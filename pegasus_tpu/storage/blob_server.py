"""Standalone remote blob-store daemon.

Parity role: the remote end of the reference's HDFS block service
(src/block_service/hdfs/hdfs_service.h:47) — a NETWORK blob store that
backup, restore, bulk load, and duplication bootstrap write to and read
from across machines. The image has no HDFS, so the daemon is our own:
a threaded HTTP server over a LocalBlockService root (content md5
verified on both ends), speaking a four-verb protocol any backend
could implement:

    PUT    /blob/<path>    body -> stored (md5 sidecar)
    GET    /blob/<path>    -> body (verified), X-Content-MD5 header
    HEAD   /blob/<path>    -> 200/404
    GET    /list/<path>    -> JSON name list
    DELETE /blob/<path>    -> recursive remove

CLI: python -m pegasus_tpu.storage.blob_server --root R --port P
"""

from __future__ import annotations

import hashlib
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from pegasus_tpu.storage.block_service import LocalBlockService


class _Handler(BaseHTTPRequestHandler):
    store: LocalBlockService = None  # type: ignore[assignment]

    def log_message(self, *args) -> None:  # quiet
        pass

    def _reply(self, code: int, body: bytes = b"",
               content_md5: str = "") -> None:
        self.send_response(code)
        self.send_header("Content-Length", str(len(body)))
        if content_md5:
            self.send_header("X-Content-MD5", content_md5)
        self.end_headers()
        if body:
            self.wfile.write(body)

    def _path(self, prefix: str) -> str:
        return self.path[len(prefix):].lstrip("/")

    def do_PUT(self) -> None:
        if not self.path.startswith("/blob/"):
            return self._reply(404)
        n = int(self.headers.get("Content-Length", 0))
        data = self.rfile.read(n)
        try:
            self.store.write_file(self._path("/blob/"), data)
        except ValueError:
            return self._reply(400)
        self._reply(200, content_md5=hashlib.md5(data).hexdigest())

    def do_GET(self) -> None:
        # a path-traversal attempt (LocalBlockService._abs raises
        # ValueError) is a BAD REQUEST on every verb, never an
        # uncaught traceback that kills the connection
        if self.path.startswith("/blob/"):
            p = self._path("/blob/")
            try:
                if not self.store.exists(p):
                    return self._reply(404)
                data, digest = self.store.read_file_with_md5(p)
            except ValueError:
                return self._reply(400)
            except OSError:
                # includes the sidecar md5 mismatch: an INTEGRITY
                # failure, which must not masquerade as absence
                return self._reply(500)
            return self._reply(200, data, content_md5=digest)
        if self.path.startswith("/list/"):
            try:
                names = self.store.list_dir(self._path("/list/"))
            except ValueError:
                return self._reply(400)
            return self._reply(200, json.dumps(names).encode())
        self._reply(404)

    def do_HEAD(self) -> None:
        if not self.path.startswith("/blob/"):
            return self._reply(404)
        try:
            found = self.store.exists(self._path("/blob/"))
        except ValueError:
            return self._reply(400)
        self._reply(200 if found else 404)

    def do_DELETE(self) -> None:
        if not self.path.startswith("/blob/"):
            return self._reply(404)
        try:
            self.store.remove_path(self._path("/blob/"))
        except ValueError:
            return self._reply(400)
        self._reply(200)


class BlobServer:
    """In-process daemon handle (tests / onebox); the CLI below runs it
    as a standalone process."""

    def __init__(self, root: str, host: str = "127.0.0.1",
                 port: int = 0) -> None:
        handler = type("BoundHandler", (_Handler,),
                       {"store": LocalBlockService(root)})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="blob-server", daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"remote://{self.host}:{self.port}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--root", required=True)
    # loopback by default: the daemon is unauthenticated, so exposing
    # backup/bulk-load data on all interfaces must be an explicit
    # operator choice (--host 0.0.0.0)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8950)
    args = ap.parse_args()
    srv = BlobServer(args.root, args.host, args.port)
    print(f"blob server on {srv.host}:{srv.port} root={args.root}",
          flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        srv.close()


if __name__ == "__main__":
    main()
