"""Write-ahead log: framed, crc-protected batch records.

Parity: the reference's per-replica private log (src/replica/mutation_log.h)
at the *storage* layer — each committed write batch is appended as one
frame carrying its decree, and replayed on boot from the last durable
decree. The replication layer will layer its own mutation log on top; this
WAL guards the memtable.

Frame format (little-endian): the shared framed-log codec
(storage/framed_log.py — [u32 payload_len][u32 crc32(payload)][payload]
with torn-tail recovery) around:
payload:
    [u64 decree][u32 record_count] record*
record:
    [u8 op][u32 key_len][key][u32 value_len][value][u32 expire_ts]
"""

from __future__ import annotations

import os
import struct
from dataclasses import dataclass
from typing import Iterator, List, Optional, Tuple

from pegasus_tpu.storage.vfs import (
    fsync_file,
    open_data_file,
    repair_truncate,
)
from pegasus_tpu.storage.framed_log import (
    iter_frames,
    pack_frame,
    scan_valid_end,
)

OP_PUT = 0
OP_DEL = 1

_PAYLOAD_HDR = struct.Struct("<QI")
_REC_HDR = struct.Struct("<BI")


@dataclass
class WalRecord:
    op: int
    key: bytes
    value: bytes
    expire_ts: int


class WriteAheadLog:
    def __init__(self, path: str) -> None:
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # Truncate any torn/corrupt tail before appending: frames written
        # after garbage would be unreachable by replay() forever (replay
        # stops at the first bad frame), losing acknowledged writes on the
        # second restart.
        valid_end = self._scan_valid_end(path)
        if valid_end is not None:
            repair_truncate(path, valid_end)
        self._f = open_data_file(path, "ab")

    @staticmethod
    def _scan_valid_end(path: str) -> Optional[int]:
        """Byte offset just past the last valid frame, or None if the file
        doesn't exist or is fully valid."""
        if not os.path.exists(path):
            return None
        with open_data_file(path, "rb") as f:
            data = f.read()
        return scan_valid_end(data)

    def append_batch(self, decree: int, records: List[WalRecord],
                     sync: bool = False, flush: bool = True) -> None:
        """`flush=False` leaves the frame in the IO buffer (the replica
        apply path under a group-commit window: the ack's durability
        rides the private log, which hardened first, and every decree
        this WAL could recover also replays from the plog — the frame
        reaches the OS when the buffer fills or truncate()/close()
        flush it; a torn tail is recovered like any other)."""
        parts = [_PAYLOAD_HDR.pack(decree, len(records))]
        for r in records:
            parts.append(_REC_HDR.pack(r.op, len(r.key)))
            parts.append(r.key)
            parts.append(struct.pack("<I", len(r.value)))
            parts.append(r.value)
            parts.append(struct.pack("<I", r.expire_ts))
        self._f.write(pack_frame(b"".join(parts)))
        if not flush:
            return
        self._f.flush()
        if sync:
            fsync_file(self._f)

    def close(self) -> None:
        self._f.close()

    def truncate(self) -> None:
        """Drop all frames (called after a flush makes them durable)."""
        self._f.close()
        self._f = open_data_file(self.path, "wb")
        self._f.close()
        self._f = open_data_file(self.path, "ab")

    @staticmethod
    def replay(path: str) -> Iterator[Tuple[int, List[WalRecord]]]:
        """Yield (decree, records) batches; stop at the first torn frame."""
        if not os.path.exists(path):
            return
        with open_data_file(path, "rb") as f:
            data = f.read()
        for payload, _end in iter_frames(data):
            decree, count = _PAYLOAD_HDR.unpack_from(payload, 0)
            off = _PAYLOAD_HDR.size
            records = []
            try:
                for _ in range(count):
                    op, klen = _REC_HDR.unpack_from(payload, off)
                    off += _REC_HDR.size
                    key = payload[off:off + klen]
                    off += klen
                    (vlen,) = struct.unpack_from("<I", payload, off)
                    off += 4
                    value = payload[off:off + vlen]
                    off += vlen
                    (ets,) = struct.unpack_from("<I", payload, off)
                    off += 4
                    records.append(WalRecord(op, key, value, ets))
            except struct.error:
                return  # malformed payload despite crc — treat as torn
            yield decree, records
