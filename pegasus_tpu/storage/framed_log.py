"""Shared framed-log codec: [u32 len][u32 crc32(payload)][payload].

Both durable logs — the storage WAL guarding the memtable
(storage/wal.py) and the per-replica private mutation log
(replica/mutation_log.py) — frame their records identically and share
one torn-tail recovery contract (parity: log_file replay,
src/replica/mutation_log_replay.cpp): replay stops at the first
incomplete or crc-mismatched frame, and boot truncates the file back to
the end of its valid prefix so later appends are never stranded behind
garbage. This module is the single implementation of that contract; the
two logs keep only their payload schemas.
"""

from __future__ import annotations

import struct
from typing import Iterator, Optional, Tuple

from pegasus_tpu.base.crc import crc32

FRAME_HDR = struct.Struct("<II")


def pack_frame(payload: bytes) -> bytes:
    """One wire/log frame for `payload`."""
    return FRAME_HDR.pack(len(payload), crc32(payload)) + payload


def iter_frames(data: bytes, offset: int = 0
                ) -> Iterator[Tuple[bytes, int]]:
    """Yield (payload, end_offset) for each valid frame in `data`
    starting at byte `offset`; stops silently at a torn or corrupt
    tail (the recovery contract — everything before it is served,
    nothing after it is trusted)."""
    pos = offset
    n = len(data)
    size = FRAME_HDR.size
    while pos + size <= n:
        length, want = FRAME_HDR.unpack_from(data, pos)
        end = pos + size + length
        if end > n:
            return  # torn tail
        payload = data[pos + size:end]
        if crc32(payload) != want:
            return  # corrupt tail
        yield payload, end
        pos = end


def scan_valid_end(data: bytes) -> Optional[int]:
    """Byte offset just past the last valid frame, or None when the
    whole buffer is valid frames (nothing to repair)."""
    pos = 0
    for _payload, end in iter_frames(data):
        pos = end
    return pos if pos < len(data) else None
