"""Block service: remote blob storage for backup artifacts.

Parity: src/block_service/block_service.h:273,337 — the abstract remote
file system (create_file / write / read / list_dir / remove_path /
upload / download) used by cold backup, restore, and bulk load. Backends:
LocalFS here (parity: block_service/local/local_service.h:47); an object
store (GCS/HDFS-style) backend slots in behind the same interface.
"""

from __future__ import annotations

import hashlib
import json
import os

from pegasus_tpu.storage.efile import open_data_file
import shutil
from typing import List, Optional


class BlockService:
    """Interface."""

    def write_file(self, path: str, data: bytes) -> None:
        raise NotImplementedError

    def read_file(self, path: str) -> bytes:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def list_dir(self, path: str) -> List[str]:
        raise NotImplementedError

    def remove_path(self, path: str) -> None:
        raise NotImplementedError

    def upload(self, local_path: str, remote_path: str) -> None:
        with open_data_file(local_path, "rb") as f:
            self.write_file(remote_path, f.read())

    def download(self, remote_path: str, local_path: str) -> None:
        os.makedirs(os.path.dirname(local_path) or ".", exist_ok=True)
        with open_data_file(local_path, "wb") as f:
            f.write(self.read_file(remote_path))


class LocalBlockService(BlockService):
    """Filesystem-backed blob store with content md5s in a sidecar index
    (parity: local_service writes .md5 metadata alongside files)."""

    def __init__(self, root: str) -> None:
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _abs(self, path: str) -> str:
        p = os.path.normpath(os.path.join(self.root, path.lstrip("/")))
        root = os.path.normpath(self.root)
        if os.path.commonpath([p, root]) != root:
            raise ValueError(f"path escapes block service root: {path}")
        return p

    def write_file(self, path: str, data: bytes) -> None:
        abs_path = self._abs(path)
        os.makedirs(os.path.dirname(abs_path), exist_ok=True)
        tmp = abs_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        # data first, checksum after: a crash in between leaves old data
        # with the OLD md5 (readable), never new-md5-over-old-data
        os.replace(tmp, abs_path)
        with open(abs_path + ".md5", "w") as f:
            f.write(hashlib.md5(data).hexdigest())

    def read_file(self, path: str) -> bytes:
        abs_path = self._abs(path)
        with open(abs_path, "rb") as f:
            data = f.read()
        md5_path = abs_path + ".md5"
        if os.path.exists(md5_path):
            with open(md5_path) as f:
                want = f.read().strip()
            if hashlib.md5(data).hexdigest() != want:
                raise IOError(f"block service md5 mismatch for {path}")
        return data

    def exists(self, path: str) -> bool:
        return os.path.exists(self._abs(path))

    def list_dir(self, path: str) -> List[str]:
        abs_path = self._abs(path)
        if not os.path.isdir(abs_path):
            return []
        return sorted(n for n in os.listdir(abs_path)
                      if not n.endswith((".md5", ".tmp")))

    def remove_path(self, path: str) -> None:
        abs_path = self._abs(path)
        if os.path.isdir(abs_path):
            shutil.rmtree(abs_path)
        elif os.path.exists(abs_path):
            os.remove(abs_path)
            md5 = abs_path + ".md5"
            if os.path.exists(md5):
                os.remove(md5)
