"""Block service: remote blob storage for backup artifacts.

Parity: src/block_service/block_service.h:273,337 — the abstract remote
file system (create_file / write / read / list_dir / remove_path /
upload / download) used by cold backup, restore, and bulk load.
Backends: LocalFS (parity: block_service/local/local_service.h:47) and
RemoteBlockService, a network blob store speaking the blob daemon's
HTTP protocol (storage/blob_server.py — the HDFS-backend role,
block_service/hdfs/hdfs_service.h:47).

Every subsystem resolves its configured root through
`block_service_for(root)`: a plain path is local, `remote://host:port[/
bucket]` is the network backend — so pointing a backup policy / bulk
load / duplication bootstrap at a remote store is a config change, not
a code change.
"""

from __future__ import annotations

import hashlib
import json
import os

from pegasus_tpu.storage.efile import open_data_file
import shutil
from typing import List, Optional


class BlockService:
    """Interface."""

    def write_file(self, path: str, data: bytes) -> None:
        raise NotImplementedError

    def read_file(self, path: str) -> bytes:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def list_dir(self, path: str) -> List[str]:
        raise NotImplementedError

    def remove_path(self, path: str) -> None:
        raise NotImplementedError

    def upload(self, local_path: str, remote_path: str) -> None:
        with open_data_file(local_path, "rb") as f:
            self.write_file(remote_path, f.read())

    def download(self, remote_path: str, local_path: str) -> None:
        os.makedirs(os.path.dirname(local_path) or ".", exist_ok=True)
        with open_data_file(local_path, "wb") as f:
            f.write(self.read_file(remote_path))


class RemoteBlockService(BlockService):
    """Network blob store over the blob daemon's HTTP protocol
    (storage/blob_server.py). Content md5 is verified on read against
    the server's X-Content-MD5 header — the same end-to-end integrity
    LocalBlockService gets from its sidecar files."""

    def __init__(self, url: str) -> None:
        # url: "remote://host:port[/bucket]"
        rest = url[len("remote://"):]
        hostport, _, bucket = rest.partition("/")
        host, _, port = hostport.partition(":")
        self.host = host
        self.port = int(port or 8950)
        self.bucket = bucket.strip("/")
        self._base = f"http://{self.host}:{self.port}"

    def _url(self, kind: str, path: str) -> str:
        p = "/".join(x for x in (self.bucket, path.lstrip("/")) if x)
        return f"{self._base}/{kind}/{p}"

    def _request(self, method: str, url: str, data: bytes = None):
        import urllib.request

        req = urllib.request.Request(url, data=data, method=method)
        return urllib.request.urlopen(req, timeout=60)

    def write_file(self, path: str, data: bytes) -> None:
        with self._request("PUT", self._url("blob", path), data) as r:
            if r.status != 200:
                raise IOError(f"blob PUT {path}: {r.status}")
            want = hashlib.md5(data).hexdigest()
            got = r.headers.get("X-Content-MD5", "")
            if got and got != want:
                # the server stored bytes that do not match what we
                # sent: surface NOW, not at some future restore
                raise IOError(f"blob PUT {path}: stored md5 {got} != "
                              f"sent {want}")

    def read_file(self, path: str) -> bytes:
        import urllib.error

        try:
            with self._request("GET", self._url("blob", path)) as r:
                data = r.read()
                want = r.headers.get("X-Content-MD5", "")
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise FileNotFoundError(
                    f"blob GET {path}: not found") from e
            # 5xx / integrity failures are SERVER errors, not absence —
            # a corrupt backup must not read as "never taken"
            raise IOError(f"blob GET {path}: HTTP {e.code}") from e
        if want and hashlib.md5(data).hexdigest() != want:
            raise IOError(f"blob md5 mismatch for {path}")
        return data

    def exists(self, path: str) -> bool:
        import urllib.error

        try:
            with self._request("HEAD", self._url("blob", path)) as r:
                return r.status == 200
        except urllib.error.HTTPError:
            return False

    def list_dir(self, path: str) -> List[str]:
        import urllib.error

        try:
            with self._request("GET", self._url("list", path)) as r:
                return json.loads(r.read())
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return []
            # a server fault must not read as "no backups exist"
            raise IOError(f"blob LIST {path}: HTTP {e.code}") from e

    def remove_path(self, path: str) -> None:
        import urllib.error

        try:
            self._request("DELETE", self._url("blob", path)).close()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return  # already absent: removal is idempotent
            # a failed delete silently "succeeding" leaks artifacts
            raise IOError(f"blob DELETE {path}: HTTP {e.code}") from e


def block_service_for(root: str) -> BlockService:
    """Resolve a configured backup/bulk-load/bootstrap root to its
    backend (the block_service_manager role,
    block_service/block_service_manager.h)."""
    if root.startswith("remote://"):
        return RemoteBlockService(root)
    return LocalBlockService(root)


class LocalBlockService(BlockService):
    """Filesystem-backed blob store with content md5s in a sidecar index
    (parity: local_service writes .md5 metadata alongside files)."""

    def __init__(self, root: str) -> None:
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _abs(self, path: str) -> str:
        p = os.path.normpath(os.path.join(self.root, path.lstrip("/")))
        root = os.path.normpath(self.root)
        if os.path.commonpath([p, root]) != root:
            raise ValueError(f"path escapes block service root: {path}")
        return p

    def write_file(self, path: str, data: bytes) -> None:
        abs_path = self._abs(path)
        os.makedirs(os.path.dirname(abs_path), exist_ok=True)
        tmp = abs_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        # data first, checksum after: a crash in between leaves old data
        # with the OLD md5 (readable), never new-md5-over-old-data
        os.replace(tmp, abs_path)
        with open(abs_path + ".md5", "w") as f:
            f.write(hashlib.md5(data).hexdigest())

    def read_file(self, path: str) -> bytes:
        return self.read_file_with_md5(path)[0]

    def read_file_with_md5(self, path: str):
        """(data, md5hex) with the digest computed exactly once —
        verified against the sidecar when present (the blob daemon
        serves the digest in X-Content-MD5 without re-hashing)."""
        abs_path = self._abs(path)
        with open(abs_path, "rb") as f:
            data = f.read()
        digest = hashlib.md5(data).hexdigest()
        md5_path = abs_path + ".md5"
        if os.path.exists(md5_path):
            with open(md5_path) as f:
                want = f.read().strip()
            if digest != want:
                raise IOError(f"block service md5 mismatch for {path}")
        return data, digest

    def exists(self, path: str) -> bool:
        return os.path.exists(self._abs(path))

    def list_dir(self, path: str) -> List[str]:
        abs_path = self._abs(path)
        if not os.path.isdir(abs_path):
            return []
        return sorted(n for n in os.listdir(abs_path)
                      if not n.endswith((".md5", ".tmp")))

    def remove_path(self, path: str) -> None:
        abs_path = self._abs(path)
        if os.path.isdir(abs_path):
            shutil.rmtree(abs_path)
        elif os.path.exists(abs_path):
            os.remove(abs_path)
            md5 = abs_path + ".md5"
            if os.path.exists(md5):
                os.remove(md5)
