"""LSMStore: memtable + L0 runs + L1, flush, merge, compaction.

Role parity: the RocksDB instance behind one replica
(src/server/pegasus_server_impl.cpp:1551 opens the DB; manual compaction
drives CompactRange, src/server/pegasus_manual_compact_service.h:48).

Shape: two levels. Flushes produce L0 SSTs (overlapping, newest wins);
full compaction merges memtable + L0 + L1 into a single L1 run, dropping
tombstones, expired records (device-evaluated TTL predicate), stale
post-split keys, and applying user-specified compaction rules — the
bottommost-level semantics the reference relies on for TTL GC
(src/server/key_ttl_compaction_filter.h:55,91).

Scan merge order: memtable > newest L0 > ... > oldest L0 > L1.
"""

from __future__ import annotations

import heapq
import os
from typing import Callable, Iterator, List, Optional, Tuple

import numpy as np

from pegasus_tpu.storage.memtable import Memtable, TOMBSTONE
from pegasus_tpu.storage.sstable import (
    BLOCK_CAPACITY,
    SSTable,
    SSTableWriter,
)

# (key, value|None, expire_ts) record triple
Record = Tuple[bytes, Optional[bytes], int]


class LSMStore:
    def __init__(self, data_dir: str, block_capacity: int = BLOCK_CAPACITY,
                 l0_compaction_trigger: int = 4) -> None:
        self.data_dir = data_dir
        os.makedirs(data_dir, exist_ok=True)
        self._block_capacity = block_capacity
        self._l0_trigger = l0_compaction_trigger
        self.memtable = Memtable()
        self.l0: List[SSTable] = []   # newest first
        self.l1: Optional[SSTable] = None
        self._file_seq = 0
        self._load_existing()

    # ---- files --------------------------------------------------------

    def _load_existing(self) -> None:
        l0_files = []
        l1_file = None
        l1_file_stale: List[Tuple[int, str]] = []
        for name in os.listdir(self.data_dir):
            if name.endswith(".sst"):
                seq = int(name.split("-")[1].split(".")[0])
                self._file_seq = max(self._file_seq, seq + 1)
                if name.startswith("l0-"):
                    l0_files.append((seq, name))
                elif name.startswith("l1-"):
                    if l1_file is None or seq > l1_file[0]:
                        if l1_file is not None:
                            l1_file_stale.append(l1_file)
                        l1_file = (seq, name)
                    else:
                        l1_file_stale.append((seq, name))
            elif name.endswith(".sst.tmp"):
                # abandoned writer from a crash mid-build
                os.remove(os.path.join(self.data_dir, name))
        # Crash-recovery invariant: compaction merges EVERY live file into
        # the new L1, so any file with seq < newest-L1 seq is an obsolete
        # compaction input whose removal didn't complete — resurrect-proof
        # cleanup happens here instead of via a manifest.
        l1_seq = l1_file[0] if l1_file is not None else -1
        for seq, name in list(l0_files):
            if seq < l1_seq:
                os.remove(os.path.join(self.data_dir, name))
                l0_files.remove((seq, name))
        for seq, name in l1_file_stale:
            os.remove(os.path.join(self.data_dir, name))
        for seq, name in sorted(l0_files, reverse=True):
            self.l0.append(SSTable(os.path.join(self.data_dir, name)))
        if l1_file is not None:
            self.l1 = SSTable(os.path.join(self.data_dir, l1_file[1]))

    def _next_path(self, level: str) -> str:
        path = os.path.join(self.data_dir, f"{level}-{self._file_seq}.sst")
        self._file_seq += 1
        return path

    def close(self) -> None:
        for t in self.l0:
            t.close()
        if self.l1 is not None:
            self.l1.close()

    # ---- writes -------------------------------------------------------

    def put(self, key: bytes, value: bytes, expire_ts: int = 0) -> None:
        self.memtable.put(key, value, expire_ts)

    def delete(self, key: bytes) -> None:
        self.memtable.delete(key)

    def flush(self, meta: Optional[dict] = None) -> Optional[SSTable]:
        """Memtable -> new L0 SST carrying `meta` (decree watermark etc.)."""
        if len(self.memtable) == 0:
            return None
        writer = SSTableWriter(self._next_path("l0"),
                               block_capacity=self._block_capacity, meta=meta)
        for key, value, ets in self.memtable.items_sorted():
            if value is TOMBSTONE:
                writer.add(key, b"", 0, tombstone=True)
            else:
                writer.add(key, value, ets)
        writer.finish()
        table = SSTable(writer.path)
        self.l0.insert(0, table)
        self.memtable = Memtable()
        return table

    def ingest(self, build_sst, meta: Optional[dict] = None):
        """Adopt an externally-built run as the newest L0 SST. `build_sst`
        is a callback (dest_path, meta) -> None writing the file; keeping
        the naming + newest-first invariants inside the store."""
        dest = self._next_path("l0")
        build_sst(dest, meta)
        table = SSTable(dest)
        self.l0.insert(0, table)
        return table

    def should_compact(self) -> bool:
        return len(self.l0) >= self._l0_trigger

    # ---- reads --------------------------------------------------------

    def get(self, key: bytes) -> Optional[Tuple[bytes, int]]:
        """Visible (value, expire_ts) or None. TTL filtering is the caller's
        job (reference checks expiry in the handlers, not the engine)."""
        hit = self.memtable.get(key)
        if hit is not None:
            value, ets = hit
            return None if value is TOMBSTONE else (value, ets)
        for table in self.l0:
            hit = table.get(key)
            if hit is not None:
                value, ets = hit
                return None if value is None else (value, ets)
        if self.l1 is not None:
            hit = self.l1.get(key)
            if hit is not None:
                value, ets = hit
                return None if value is None else (value, ets)
        return None

    def iterate(self, start: bytes = b"", stop: Optional[bytes] = None,
                reverse: bool = False) -> Iterator[Record]:
        """Merged visible records (tombstones resolved, TTL not applied)."""
        sources: List[Iterator[Record]] = [
            self.memtable.iterate(start, stop, reverse)]
        for table in self.l0:
            sources.append(table.iterate(start, stop, reverse))
        if self.l1 is not None:
            sources.append(self.l1.iterate(start, stop, reverse))
        return _merge(sources, reverse)

    def sorted_run(self) -> Optional[SSTable]:
        """The single L1 run when the store is fully compacted and there is
        no overlay — the device fast path qualifier: scans may then stream
        L1 blocks columnar to the predicate kernels."""
        if len(self.memtable) == 0 and not self.l0 and self.l1 is not None:
            return self.l1
        return None

    # ---- compaction ---------------------------------------------------

    def compact(
        self,
        record_filter: Optional[Callable[..., np.ndarray]] = None,
        meta: Optional[dict] = None,
    ) -> None:
        """Full merge into one L1 run.

        `record_filter(keys: List[bytes], expire_ts: List[int]) ->
        (drop_mask, new_expire)` is evaluated over columnar batches of
        merged records — the seam where the device TTL/compaction-rule
        kernels plug in (engine.StorageEngine wires it). Tombstones always
        drop (bottommost).
        """
        merged = self.iterate()
        writer = SSTableWriter(self._next_path("l1"),
                               block_capacity=self._block_capacity, meta=meta)
        batch_keys: List[bytes] = []
        batch_vals: List[bytes] = []
        batch_ets: List[int] = []

        def flush_batch() -> None:
            if not batch_keys:
                return
            if record_filter is not None:
                drop, new_ets = record_filter(batch_keys, batch_ets)
                for i, k in enumerate(batch_keys):
                    if not drop[i]:
                        writer.add(k, batch_vals[i], int(new_ets[i]))
            else:
                for k, v, e in zip(batch_keys, batch_vals, batch_ets):
                    writer.add(k, v, e)
            batch_keys.clear()
            batch_vals.clear()
            batch_ets.clear()

        for key, value, ets in merged:
            if value is None:  # tombstone: bottommost level -> drop
                continue
            batch_keys.append(key)
            batch_vals.append(value)
            batch_ets.append(ets)
            if len(batch_keys) >= self._block_capacity:
                flush_batch()
        flush_batch()
        writer.finish()

        old_l0, old_l1 = self.l0, self.l1
        self.l1 = SSTable(writer.path)
        self.l0 = []
        self.memtable = Memtable()
        for t in old_l0:
            t.close()
            os.remove(t.path)
        if old_l1 is not None:
            old_l1.close()
            os.remove(old_l1.path)


class _HeapEntry:
    """Heap ordering: key asc (or desc when reverse), then source index asc —
    so for equal keys the newest source (lowest index) pops first."""

    __slots__ = ("key", "src_idx", "record", "it", "reverse")

    def __init__(self, key, src_idx, record, it, reverse):
        self.key = key
        self.src_idx = src_idx
        self.record = record
        self.it = it
        self.reverse = reverse

    def __lt__(self, other: "_HeapEntry") -> bool:
        if self.key != other.key:
            return self.key > other.key if self.reverse else self.key < other.key
        return self.src_idx < other.src_idx


def _merge(sources: List[Iterator[Record]], reverse: bool = False
           ) -> Iterator[Record]:
    """K-way merge; on duplicate keys the lowest source index (newest) wins;
    shadowed duplicates are skipped and tombstone winners are dropped."""
    heap: List[_HeapEntry] = []
    for src_idx, it in enumerate(sources):
        first = next(it, None)
        if first is not None:
            heap.append(_HeapEntry(first[0], src_idx, first, it, reverse))
    heapq.heapify(heap)
    prev_key: Optional[bytes] = None
    while heap:
        entry = heapq.heappop(heap)
        key, value, ets = entry.record
        if key != prev_key:
            prev_key = key
            if value is not None:  # tombstone winners are invisible
                yield key, value, ets
        nxt = next(entry.it, None)
        if nxt is not None:
            heapq.heappush(heap,
                           _HeapEntry(nxt[0], entry.src_idx, nxt, entry.it,
                                      reverse))
