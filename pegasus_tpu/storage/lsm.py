"""LSMStore: memtable + L0 runs + ranged L1 runs, flush, merge, compaction.

Role parity: the RocksDB instance behind one replica
(src/server/pegasus_server_impl.cpp:1551 opens the DB; manual compaction
drives CompactRange, src/server/pegasus_manual_compact_service.h:48).

Shape: two levels. Flushes produce L0 SSTs (overlapping, newest wins).
L1 is a sequence of NON-OVERLAPPING, size-capped runs ordered by key —
compaction processes one output range at a time (merge memtable + L0
sub-range + that L1 run) and caps each output run, so a big table is
never rewritten as one monolithic file and each step's memory/latency
stays bounded (the leveled-compaction property manual CompactRange
relies on). The filter seam drops tombstones, expired records
(device-evaluated TTL predicate), stale post-split keys, and applies
user-specified rules — the bottommost-level semantics of
src/server/key_ttl_compaction_filter.h:55,91.

Device pipelining: while the device evaluates one batch's filter, the
host builds the next (jax dispatch is async; materialization is delayed
one batch).

Durability: a manifest (temp+rename) names the live L1 runs; boot
removes obsolete compaction inputs/outputs from crash windows.

Scan merge order: memtable > newest L0 > ... > oldest L0 > L1 runs.
"""

from __future__ import annotations

import heapq
import itertools
import os
from typing import Callable, Iterator, List, Optional, Tuple

import numpy as np

from pegasus_tpu.base.crc import crc64
from pegasus_tpu.storage.block_codec import (
    CODEC_NONE,
    EncodedBlock,
    codec_accepts,
)
from pegasus_tpu.storage.bloom import bloom_probe_enabled
from pegasus_tpu.storage.memtable import Memtable, TOMBSTONE
from pegasus_tpu.storage.sstable import (
    BLOCK_CAPACITY,
    SSTable,
    SSTableWriter,
)

# (key, value|None, expire_ts) record triple
Record = Tuple[bytes, Optional[bytes], int]


# records per L1 output run before the compactor starts a new one:
# bounds every future range-compaction step (and its device batches)
L1_RUN_CAPACITY = 262_144

# process-unique store ids: cache owners (the node row cache) key
# entries by store identity + generation, and an int token can never
# alias a recycled object id after an engine swap
_STORE_UIDS = itertools.count(1)


def survivor_mask(drop: np.ndarray, flags) -> np.ndarray:
    """Rows a compaction keeps: the filter's drop mask plus the
    tombstone flags — THE survivor definition. bulk_compact_rewrite's
    transform applies it to build output blocks, and the mesh residency
    refresh (parallel/mesh_resident._survivor_slab) replays it to
    gather the post-compaction slab without re-reading those blocks;
    both sides calling one function is what keeps them in lockstep."""
    keep = ~np.asarray(drop, bool)
    if flags is not None:
        keep &= np.asarray(flags) == 0  # tombstones never stay
    return keep


class LSMStore:
    def __init__(self, data_dir: str, block_capacity: int = BLOCK_CAPACITY,
                 l0_compaction_trigger: int = 4,
                 l1_run_capacity: int = L1_RUN_CAPACITY) -> None:
        self.data_dir = data_dir
        os.makedirs(data_dir, exist_ok=True)
        self._block_capacity = block_capacity
        self._l0_trigger = l0_compaction_trigger
        self._l1_run_capacity = l1_run_capacity
        self.memtable = Memtable()
        self.l0: List[SSTable] = []   # newest first
        self.l1_runs: List[SSTable] = []  # key-ordered, non-overlapping
        self._file_seq = 0
        # bumped whenever the visible run set changes (flush / ingest /
        # compaction publish): callers key derived caches (scan plans)
        # on it so they invalidate exactly when the block set does
        self.generation = 0
        self.store_uid = next(_STORE_UIDS)
        # last manual-compaction finish time (pegasus-epoch seconds),
        # persisted in the manifest INDEPENDENTLY of the run set so an
        # all-tombstone compaction (zero surviving runs) still records
        # completion — env-trigger staleness checks depend on it.
        # Recorded AT PUBLISH (with the manifest write), never at merge
        # start: a failed mid-run compaction must not make a
        # re-delivered env trigger look satisfied.
        self.compact_finish_time = 0
        # publish hook: called with the live L1 path set after every
        # compaction publish, so cache owners (PartitionServer) evict
        # entries keyed by runs that just left the manifest instead of
        # pinning dead fds/mmaps/HBM until GC
        self.on_publish: Optional[Callable[[set], None]] = None
        self._load_existing()

    # ---- files --------------------------------------------------------

    def _manifest_path(self) -> str:
        return os.path.join(self.data_dir, "MANIFEST.json")

    def _write_manifest(self, l1_names: List[str]) -> None:
        """Atomically record the live L1 run set + the seq horizon. Any
        l1-* file not listed, and any l0-* file older than the horizon,
        is a crash leftover boot removes."""
        import json as _json
        import tempfile as _tempfile

        fd, tmp = _tempfile.mkstemp(dir=self.data_dir)
        with os.fdopen(fd, "w") as f:
            _json.dump({"seq": self._file_seq, "l1": l1_names,
                        "mcft": self.compact_finish_time}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._manifest_path())

    def _load_existing(self) -> None:
        import json as _json

        manifest = None
        if os.path.exists(self._manifest_path()):
            with open(self._manifest_path()) as f:
                manifest = _json.load(f)
            # the seq horizon must survive even when every .sst is gone
            # (an all-tombstone compaction): fresh flushes below the
            # horizon would be deleted as consumed inputs at next boot
            self._file_seq = max(self._file_seq, manifest["seq"])
            self.compact_finish_time = manifest.get("mcft", 0)
        l0_files = []
        l1_files = []
        for name in os.listdir(self.data_dir):
            if name.endswith(".sst"):
                seq = int(name.split("-")[1].split(".")[0])
                self._file_seq = max(self._file_seq, seq + 1)
                if name.startswith("l0-"):
                    l0_files.append((seq, name))
                elif name.startswith("l1-"):
                    l1_files.append((seq, name))
            elif name.endswith(".sst.tmp"):
                # abandoned writer from a crash mid-build
                os.remove(os.path.join(self.data_dir, name))
        if manifest is None:
            # legacy layout (pre-manifest): newest l1 file wins, older
            # files are obsolete compaction inputs
            l1_live = []
            if l1_files:
                newest = max(l1_files)
                l1_live = [newest[1]]
                horizon = newest[0]
            else:
                horizon = -1
            stale_l1 = [n for _s, n in l1_files if n not in l1_live]
        else:
            l1_live = [n for n in manifest["l1"]
                       if os.path.exists(os.path.join(self.data_dir, n))]
            horizon = manifest["seq"]
            # unlisted l1 files: incomplete outputs from a crashed
            # compaction (or inputs whose removal did not finish)
            stale_l1 = [n for _s, n in l1_files if n not in l1_live]
        for name in stale_l1:
            os.remove(os.path.join(self.data_dir, name))
        # l0 files older than the horizon are consumed compaction inputs
        for seq, name in list(l0_files):
            if seq < horizon:
                os.remove(os.path.join(self.data_dir, name))
                l0_files.remove((seq, name))
        for seq, name in sorted(l0_files, reverse=True):
            self.l0.append(SSTable(os.path.join(self.data_dir, name)))
        runs = [SSTable(os.path.join(self.data_dir, name))
                for name in l1_live]
        runs.sort(key=lambda t: t.first_key or b"")
        self.l1_runs = runs

    def _next_path(self, level: str) -> str:
        path = os.path.join(self.data_dir, f"{level}-{self._file_seq}.sst")
        self._file_seq += 1
        return path

    def close(self) -> None:
        for t in self.l0:
            t.close()
        for t in self.l1_runs:
            t.close()

    # ---- writes -------------------------------------------------------

    def put(self, key: bytes, value: bytes, expire_ts: int = 0) -> None:
        self.memtable.put(key, value, expire_ts)

    def delete(self, key: bytes) -> None:
        self.memtable.delete(key)

    def flush(self, meta: Optional[dict] = None) -> Optional[SSTable]:
        """Memtable -> new L0 SST carrying `meta` (decree watermark etc.)."""
        if len(self.memtable) == 0:
            return None
        writer = SSTableWriter(self._next_path("l0"),
                               block_capacity=self._block_capacity, meta=meta)
        for key, value, ets in self.memtable.items_sorted():
            if value is TOMBSTONE:
                writer.add(key, b"", 0, tombstone=True)
            else:
                writer.add(key, value, ets)
        writer.finish()
        table = SSTable(writer.path)
        self.l0.insert(0, table)
        self.memtable = Memtable()
        self.generation += 1
        return table

    def ingest(self, build_sst, meta: Optional[dict] = None):
        """Adopt an externally-built run as the newest L0 SST. `build_sst`
        is a callback (dest_path, meta) -> None writing the file; keeping
        the naming + newest-first invariants inside the store."""
        dest = self._next_path("l0")
        build_sst(dest, meta)
        table = SSTable(dest)
        self.l0.insert(0, table)
        self.generation += 1
        return table

    def should_compact(self) -> bool:
        return len(self.l0) >= self._l0_trigger

    # ---- reads --------------------------------------------------------

    def get(self, key: bytes) -> Optional[Tuple[bytes, int]]:
        """Visible (value, expire_ts) or None. TTL filtering is the caller's
        job (reference checks expiry in the handlers, not the engine).

        L0 tables short-circuit on their first/last-key fences (an
        out-of-range table costs two compares, not a block lookup) and
        then on their sidecar structures — the key is hashed ONCE (the
        crc64 every sidecar shares) when any candidate table carries a
        bloom or a perfect-hash index, and the same hash feeds every
        structure this get consults. Indexed runs answer through
        SSTable.get's scalar phash probe (the batched kernel's hash,
        solo form): a miss costs one slot gather with zero block
        touches, a hit goes straight to its (block, slot) row — the
        non-batched client path never silently regresses to the
        bisect. Steady-state stores (empty L0, filterless runs) skip
        the hash entirely."""
        from pegasus_tpu.utils.perf_context import current as _perf_current

        pc = _perf_current()  # solo-path cost vector (None = untracked)
        hit = self.memtable.get(key)
        if hit is not None:
            if pc is not None:
                pc.overlay_hits += 1
            value, ets = hit
            return None if value is TOMBSTONE else (value, ets)
        from pegasus_tpu.storage.phash import phash_probe_enabled

        bloom_on = bloom_probe_enabled()
        phash_on = phash_probe_enabled()
        if pc is not None:
            # same meaning as the batched planner's field: the sidecar
            # candidacy matrix width this key was answered against
            pc.runs_considered += len(self.l0) + len(self.l1_runs)
        key_hash: Optional[int] = None  # computed at most once

        def lookup(table):
            """One table's sidecar-gated probe, matching the batched
            planner's structure selection exactly: an indexed table
            (phash probing on) answers through the perfect hash ALONE
            — consulting its bloom too would double the per-pair work
            — and each kill switch disables ONLY its own structure
            (a bloom_probe=False escape hatch must not keep pruning
            through a suspect filter just because phash hashing ran)."""
            nonlocal key_hash
            use_phash = phash_on and table.phash is not None
            use_bloom = bloom_on and not use_phash \
                and table.bloom is not None
            if (use_phash or use_bloom) and key_hash is None:
                key_hash = crc64(key)
            if use_bloom and not table.may_contain(key, key_hash):
                return None  # definitively absent from this table
            return table.get(key, key_hash=key_hash
                             if use_phash else None)

        for table in self.l0:
            fk = table.first_key
            if fk is None or key < fk or key > table.last_key:
                continue
            hit = lookup(table)
            if hit is not None:
                value, ets = hit
                return None if value is None else (value, ets)
        run = self._run_for(key)
        if run is not None:
            hit = lookup(run)
            if hit is not None:
                value, ets = hit
                return None if value is None else (value, ets)
        return None

    def _run_for(self, key: bytes) -> Optional[SSTable]:
        """The (single) L1 run whose range may hold `key` — runs are
        non-overlapping and key-ordered. Operates on ONE snapshot of
        the run list: a concurrent compaction publish swaps
        `self.l1_runs` wholesale (env-triggered manual compaction runs
        off the node lock), and re-reading the attribute mid-search
        could index a shorter list."""
        runs = self.l1_runs
        lo, hi = 0, len(runs)
        while lo < hi:
            mid = (lo + hi) // 2
            if (runs[mid].last_key or b"") < key:
                lo = mid + 1
            else:
                hi = mid
        if lo < len(runs) and ((runs[lo].first_key or b"") <= key):
            return runs[lo]
        return None

    def iterate(self, start: bytes = b"", stop: Optional[bytes] = None,
                reverse: bool = False) -> Iterator[Record]:
        """Merged visible records (tombstones resolved, TTL not applied)."""
        sources: List[Iterator[Record]] = [
            self.memtable.iterate(start, stop, reverse)]
        for table in self.l0:
            sources.append(table.iterate(start, stop, reverse))
        if self.l1_runs:
            # non-overlapping ordered runs chain into ONE merged source,
            # keeping the merge heap as small as the old single-L1 shape
            runs = (self.l1_runs if not reverse
                    else list(reversed(self.l1_runs)))
            sources.append(_chain_runs(runs, start, stop, reverse))
        return _merge(sources, reverse)

    def sorted_runs(self) -> Optional[List[SSTable]]:
        """The ordered L1 runs when the store is fully compacted and there
        is no overlay — the device fast path qualifier: scans stream each
        run's blocks columnar to the predicate kernels, in key order."""
        if len(self.memtable) == 0 and not self.l0 and self.l1_runs:
            return self.l1_runs
        return None

    # ---- compaction ---------------------------------------------------

    def compact(
        self,
        record_filter: Optional[Callable[..., np.ndarray]] = None,
        meta: Optional[dict] = None,
        patch_headers: bool = False,
        publish_lock=None,
    ) -> None:
        """Full compaction as a sequence of BOUNDED range steps.

        One merged pass over the overlay + L1 runs; output runs are
        size-capped (`l1_run_capacity`), so no monolithic rewrite and a
        predictable working set per step — the manual CompactRange shape.

        `publish_lock=None` (legacy mode): the caller holds the writer
        lock for the whole merge; memtable + live L0 + L1 merge and the
        overlay resets at publish. `publish_lock` set (snapshot mode —
        the narrow critical section): the caller froze the memtable
        with a flush, the merge runs over the IMMUTABLE L0/L1 snapshot
        with writes flowing, and the lock is taken only for the publish
        cut-over — post-snapshot writes (fresh memtable, newer L0
        flushes) survive untouched and keep shadowing the merged base.

        `record_filter(keys: List[bytes], expire_ts: List[int]) ->
        (drop_mask, new_expire)` is the device TTL/compaction-rule seam
        (engine.StorageEngine wires it); evaluation is DOUBLE-BUFFERED:
        while the device filters batch N, the host gathers batch N+1
        (jax dispatch is asynchronous — only materialization blocks).
        Tombstones always drop (bottommost).
        """
        runs_snap = list(self.l1_runs)
        if publish_lock is not None:
            l0_snap = list(self.l0)
            sources: List[Iterator[Record]] = [
                t.iterate() for t in l0_snap]
            if runs_snap:
                sources.append(_chain_runs(runs_snap, b"", None, False))
            merged = _merge(sources)
        else:
            l0_snap = None
            merged = self.iterate()
        new_runs: List[SSTable] = []
        writer: Optional[SSTableWriter] = None
        written_in_run = 0
        # write-stage overlap, same shape as the bulk path: block
        # writes stream on the writer's async-IO thread while the
        # merge/filter keeps producing, and filled runs finish on the
        # shared _FinishPool (joined before publish)
        finish_pool = _FinishPool()

        def open_writer() -> SSTableWriter:
            return SSTableWriter(self._next_path("l1"),
                                 block_capacity=self._block_capacity,
                                 meta=meta, async_io=True)

        def write_records(keys, vals, ets_orig, drop, new_ets) -> None:
            nonlocal writer, written_in_run
            from pegasus_tpu.base.value_schema import update_expire_ts

            for i, k in enumerate(keys):
                if drop is not None and drop[i]:
                    continue
                if writer is None:
                    writer = open_writer()
                ne = int(new_ets[i])
                v = vals[i]
                if patch_headers and ne != ets_orig[i]:
                    # a TTL rewrite must reach the encoded value header
                    # too, or readers of the raw header see the old TTL
                    # (the bulk path patches it the same way)
                    v = update_expire_ts(1, v, ne)
                writer.add(k, v, ne)
                written_in_run += 1
                if written_in_run >= self._l1_run_capacity:
                    finish_pool.submit(writer)
                    writer = None
                    written_in_run = 0

        # pipeline state: the batch whose filter is in flight on device
        pending: Optional[tuple] = None

        def submit(keys, vals, ets):
            if record_filter is None:
                return (keys, vals, ets, None, ets)
            drop, new_ets = record_filter(keys, ets)
            # jax returns asynchronously-evaluated arrays; conversion to
            # numpy in drain() is the synchronization point
            return (keys, vals, ets, drop, new_ets)

        def drain(entry) -> None:
            keys, vals, ets_orig, drop, new_ets = entry
            if drop is not None:
                # materialize = the device synchronization point
                drop = np.asarray(drop)
                new_ets = np.asarray(new_ets)
            write_records(keys, vals, ets_orig, drop, new_ets)

        from pegasus_tpu.storage.compact_governor import GOVERNOR

        batch_keys: List[bytes] = []
        batch_vals: List[bytes] = []
        batch_ets: List[int] = []
        batch_bytes = 0
        # the FILTER batch is much larger than the write-block size: a
        # high-RTT device pays per dispatch, so the compactor amortizes
        # 16 blocks of records into each filter evaluation
        filter_batch = self._block_capacity * 16
        ok = False
        try:
            for key, value, ets in merged:
                if value is None:  # tombstone: bottommost level -> drop
                    continue
                batch_keys.append(key)
                batch_vals.append(value)
                batch_ets.append(ets)
                batch_bytes += len(key) + len(value)
                if len(batch_keys) >= filter_batch:
                    # the merge path's input pacing: one governor
                    # charge per filter batch (the bulk path pays per
                    # block) — background bandwidth answers foreground
                    # pressure on BOTH compaction shapes
                    GOVERNOR.acquire(batch_bytes)
                    entry = submit(batch_keys, batch_vals, batch_ets)
                    if pending is not None:
                        drain(pending)
                    pending = entry
                    batch_keys, batch_vals, batch_ets = [], [], []
                    batch_bytes = 0
            if batch_keys:
                GOVERNOR.acquire(batch_bytes)
                entry = submit(batch_keys, batch_vals, batch_ets)
                if pending is not None:
                    drain(pending)
                pending = entry
            if pending is not None:
                drain(pending)
            if writer is not None:
                finish_pool.submit(writer)
                writer = None
            new_runs = finish_pool.results()
            ok = True
        finally:
            finish_pool.shutdown(ok, open_writer=writer)

        self._publish_l1(new_runs, consumed_l0=l0_snap,
                         old_runs=runs_snap, publish_lock=publish_lock,
                         mcft=(meta or {}).get(
                             "manual_compact_finish_time", 0))

    def _publish_l1(self, new_runs: List[SSTable],
                    consumed_l0: Optional[List[SSTable]] = None,
                    old_runs: Optional[List[SSTable]] = None,
                    publish_lock=None, mcft: int = 0) -> None:
        """Swap in a freshly-compacted L1 under `publish_lock` (None =
        the caller already excludes writers): manifest first (atomic),
        then remove inputs — boot cleans up either crash window. Both
        compaction paths share this so the crash-safety ordering lives
        in exactly one place.

        consumed_l0=None: the merge consumed the LIVE overlay (caller
        held the writer lock throughout) — memtable and L0 reset
        wholesale. consumed_l0=[...]: snapshot mode — exactly those L0
        tables leave; the memtable and any newer L0 flushes
        (post-snapshot writes) survive and keep shadowing the new base.
        old_runs: the L1 snapshot the merge consumed, revalidated
        against the live list under the lock — compactions are
        serialized (engine.compact_lock), so a mismatch means a torn
        merge whose output must not publish.
        mcft: manual-compaction finish time, recorded HERE (with the
        manifest) so a failed mid-run compaction never satisfies a
        re-delivered env trigger."""
        import contextlib

        lock = publish_lock if publish_lock is not None \
            else contextlib.nullcontext()
        old_l0: List[SSTable] = []
        with lock:
            if old_runs is not None and \
                    [id(t) for t in self.l1_runs] != \
                    [id(t) for t in old_runs]:
                for t in new_runs:
                    try:
                        t.close()
                        os.remove(t.path)
                    except OSError:
                        pass
                raise RuntimeError(
                    "concurrent L1 publish detected; compaction output "
                    "discarded")
            if mcft:
                self.compact_finish_time = mcft
            self._write_manifest([os.path.basename(t.path)
                                  for t in new_runs])
            superseded = self.l1_runs
            self.l1_runs = new_runs
            self.generation += 1
            if consumed_l0 is None:
                old_l0, self.l0 = self.l0, []
                self.memtable = Memtable()
            elif consumed_l0:
                consumed = {id(t) for t in consumed_l0}
                self.l0 = [t for t in self.l0
                           if id(t) not in consumed]
                old_l0 = list(consumed_l0)
            # Input files are unlinked now (crash-safe: the manifest no
            # longer names them) but their HANDLES are released by GC,
            # not closed here: a reader admitted before the swap may
            # still be serving from these runs (the env-triggered
            # compaction thread publishes concurrently with serving),
            # and on encrypted stores a hard close() would yank the
            # CipherFile out from under its next read_block. POSIX
            # keeps unlinked-but-open files readable; the refcount
            # drops to zero as soon as the last in-flight scan state /
            # superseded plan cache lets go. Unlinking INSIDE the lock
            # keeps checkpoint's file-copy walk (which takes the same
            # lock) from racing the removals.
            for t in old_l0 + superseded:
                os.remove(t.path)
        hook = self.on_publish
        if hook is not None:
            # cache owners evict entries keyed by the dead runs
            hook({t.path for t in new_runs})

    # ---- bulk block-level compaction (the GB/s path) -------------------

    def bulk_compact_eligible(self) -> bool:
        """The store is pure non-overlapping L1 (manual-compact steady
        state): no merge is needed, so compaction can rewrite block-wise
        with vectorized gathers instead of streaming per-record Python.
        v1 files (no hash_lo column) fall back to the merge path."""
        return (len(self.memtable) == 0 and not self.l0
                and bool(self.l1_runs)
                and all(getattr(r, "_has_hash_lo", False)
                        for r in self.l1_runs))

    def bulk_compact_entries(self):
        """Every L1 block in global key order: [(run, idx, BlockMeta)]."""
        out = []
        for run in self.l1_runs:
            for i, bm in enumerate(run.blocks):
                out.append((run, i, bm))
        return out

    def bulk_compact_rewrite(self, per_block, meta,
                             ttl_may_change: bool,
                             patch_headers: bool = False,
                             publish_lock=None,
                             transform_workers: int = 0) -> None:
        """Rewrite the L1 level from precomputed per-block filter results.

        `per_block`: [(run, idx, blk, drop, new_ets)] in key order (drop
        / new_ets sized to the block's real count). Untouched blocks are
        re-serialized straight from their already-decoded columns (no
        gather, no crc recompute, no second disk read); touched blocks
        are rebuilt with numpy gathers — the value heap survivor bytes
        via one boolean-repeat mask, expire_ts headers patched with
        scatter stores — so no per-record Python runs at any drop
        rate. The rewrite never touches the memtable/L0 (eligibility
        requires them empty at snapshot), so with `publish_lock` the
        whole disk pass runs with writes flowing and the lock is taken
        only for the publish cut-over.

        `transform_workers` > 0 (the pipelined compactor's write
        stage): the per-block transform — subset kernel, heap
        inflate/re-deflate, numpy gathers — runs on an ordered worker
        pool while this thread only appends results, so the GIL-free
        kernel work of block N+1..N+k overlaps block N's writer append.
        The transform is ONE function executed identically inline or
        pooled, so output bytes cannot depend on the mode."""
        import concurrent.futures as _cf

        from pegasus_tpu.storage.bloom import bloom_build_bits
        from pegasus_tpu.storage.sstable import (
            SSTable,
            SSTableWriter,
            block_codec,
        )

        runs_snap = list(self.l1_runs)
        # filled runs finish on the shared _FinishPool (fsync releases
        # the GIL) while this thread keeps appending; joined before
        # the manifest publish
        finish_pool = _FinishPool()

        from pegasus_tpu import native

        cblock_subset = native.cblock_subset_fn()
        writer: Optional[SSTableWriter] = None
        written_in_run = 0
        ok = False

        def roll_writer() -> SSTableWriter:
            nonlocal writer, written_in_run
            if writer is not None and written_in_run >= self._l1_run_capacity:
                finish_pool.submit(writer)
                writer = None
                written_in_run = 0
            if writer is None:
                writer = SSTableWriter(self._next_path("l1"),
                                       block_capacity=self._block_capacity,
                                       meta=meta, async_io=True)
            return writer

        def copy_block(blk) -> None:
            nonlocal written_in_run
            w = roll_writer()
            w.add_block_columnar(blk.keys, blk.key_len, blk.expire_ts,
                                 blk.hash_lo, blk.flags, blk.value_offs,
                                 blk.value_heap)
            written_in_run += blk.count

        # writer-independent state the TRANSFORM latches once, so the
        # same decisions compute on any thread: every writer this
        # rewrite rolls latches the identical flag values at creation.
        # `sidecar_now` (bloom OR phash) decides whether the subset
        # kernel must emit per-row hashes — either sidecar needs them
        codec_now = block_codec()
        from pegasus_tpu.storage.phash import phash_build_enabled

        sidecar_now = bloom_build_bits() > 0 or phash_build_enabled()

        def transform(item):
            """Stateless per-block transform -> (kind, payload). The
            expensive work lives here — subset kernel (GIL-free), heap
            inflate, numpy gathers — and runs identically inline
            (serial) or on the ordered worker pool (pipelined)."""
            _run, _idx, blk, drop, new_ets = item
            dropped = bool(drop.any())
            encoded = isinstance(blk, EncodedBlock)
            ets_changed = ttl_may_change and \
                not np.array_equal(new_ets, blk.expire_ts)
            if not dropped and not ets_changed:
                if encoded:
                    if codec_now != CODEC_NONE:
                        # untouched compressed block: the on-disk
                        # bytes copy VERBATIM — no heap inflate, no
                        # re-encode, no re-deflate
                        return "verbatim", blk
                    blk = blk.decode()  # codec turned off mid-store
                return "copy", blk
            n = blk.count
            if encoded:
                # survivor check first: a fully-dropped block must
                # never roll a writer (an empty L1 run would publish
                # when every block drops every row)
                keep = survivor_mask(drop, blk.flags)
                if not keep.any():
                    return "skip", None
                if codec_now != CODEC_NONE and cblock_subset is not None \
                        and codec_accepts(codec_now, blk.version):
                    # rows drop (or TTLs rewrite): subset the block
                    # in the ENCODED domain — one GIL-free native
                    # pass (dict remap + ragged gathers + heap
                    # inflate/re-deflate) instead of the Python
                    # decode -> gather -> re-encode round trip that
                    # serialized the compaction thread pool
                    res = cblock_subset(
                        blk.raw, blk.raw_heap_len, blk.key_width,
                        keep, new_ets if ets_changed else None,
                        ets_changed and patch_headers,
                        want_hashes=sidecar_now)
                    if res is not None:
                        return "raw", (res, blk.key_width)
                # native kernel unavailable (or codec flipped off
                # mid-store): materialize once and take the
                # vectorized gather path below
                blk = blk.decode()
            keep = survivor_mask(drop, blk.flags)
            kept = np.flatnonzero(keep)
            if kept.size == 0:
                return "skip", None
            vo = blk.value_offs.astype(np.int64)
            lens = vo[1:] - vo[:-1]
            heap_arr = blk.value_heap
            if not isinstance(heap_arr, np.ndarray):
                heap_arr = np.frombuffer(heap_arr, dtype=np.uint8)
            ets_col = new_ets if ets_changed else blk.expire_ts
            if ets_changed and patch_headers:
                # patch the big-endian u32 expire_ts value header in
                # place (vectorized scatter, value_schema.h: header
                # starts every encoded value)
                heap_arr = heap_arr.copy()
                chg = np.flatnonzero((new_ets != blk.expire_ts)
                                     & keep)
                if chg.size:
                    pos = vo[chg]
                    vals = new_ets[chg].astype(np.uint32)
                    heap_arr[pos] = (vals >> 24).astype(np.uint8)
                    heap_arr[pos + 1] = \
                        ((vals >> 16) & 0xFF).astype(np.uint8)
                    heap_arr[pos + 2] = \
                        ((vals >> 8) & 0xFF).astype(np.uint8)
                    heap_arr[pos + 3] = (vals & 0xFF).astype(np.uint8)
            if kept.size == n:
                new_heap = heap_arr
                new_offs = blk.value_offs
                keys2d, klen = blk.keys, blk.key_len
                hlo, flg = blk.hash_lo, blk.flags
                ets_out = ets_col
            else:
                keep_bytes = np.repeat(keep, lens)
                new_heap = heap_arr[keep_bytes]
                kept_lens = lens[kept]
                new_offs = np.zeros(kept.size + 1, dtype=np.uint32)
                new_offs[1:] = np.cumsum(kept_lens)
                keys2d = blk.keys[kept]
                klen = blk.key_len[kept]
                ets_out = np.asarray(ets_col)[kept]
                hlo = blk.hash_lo[kept]
                flg = blk.flags[kept]
            return "columnar", (keys2d, klen, ets_out, hlo, flg,
                                new_offs, new_heap, int(kept.size))

        def consume(kind, payload) -> None:
            """Writer appends, strictly in block order on THIS thread
            (the writers are single-threaded; ordering is the format
            contract)."""
            nonlocal written_in_run
            if kind == "skip":
                return
            if kind == "verbatim":
                w = roll_writer()
                # add_block_encoded transcodes a version the writer's
                # codec cannot contain (flag moved mid-store)
                w.add_block_encoded(payload)
                written_in_run += payload.count
            elif kind == "copy":
                copy_block(payload)
            elif kind == "raw":
                (buf, hashes, m, vsub, fk, lk), kw = payload
                w = roll_writer()
                w.add_block_encoded_raw(buf, m, kw, vsub, fk, lk,
                                        hashes)
                written_in_run += m
            else:
                w = roll_writer()
                w.add_block_columnar(*payload[:7])
                written_in_run += payload[7]

        try:
            if transform_workers > 0:
                # ordered lookahead: transforms run CHUNKED on the
                # pool (one future per ~16 blocks — a future round
                # trip costs a condition-variable wait, which at one
                # per block ate the whole overlap win) while results
                # append in order — the write stage's own intra-stage
                # parallelism
                from collections import deque

                CHUNK = 16
                depth = 2 * transform_workers + 2

                def transform_chunk(chunk):
                    return [transform(x) for x in chunk]

                tpool = _cf.ThreadPoolExecutor(
                    max_workers=transform_workers)
                try:
                    pend: deque = deque()
                    chunk: list = []
                    for item in per_block:
                        chunk.append(item)
                        if len(chunk) >= CHUNK:
                            pend.append(tpool.submit(transform_chunk,
                                                     chunk))
                            chunk = []
                            if len(pend) >= depth:
                                for r in pend.popleft().result():
                                    consume(*r)
                    if chunk:
                        pend.append(tpool.submit(transform_chunk,
                                                 chunk))
                    while pend:
                        for r in pend.popleft().result():
                            consume(*r)
                finally:
                    tpool.shutdown(wait=True)
            else:
                for item in per_block:
                    consume(*transform(item))
            if writer is not None:
                finish_pool.submit(writer)
                writer = None
            new_runs = finish_pool.results()
            ok = True
        finally:
            finish_pool.shutdown(ok, open_writer=writer)
        # memtable/L0 are untouched by construction
        # (bulk_compact_eligible requires them empty at snapshot time;
        # writes that arrived since stay in the live overlay)
        self._publish_l1(new_runs, consumed_l0=[], old_runs=runs_snap,
                         publish_lock=publish_lock,
                         mcft=(meta or {}).get(
                             "manual_compact_finish_time", 0))


class _FinishPool:
    """Shared write-stage finisher for both compaction paths: filled
    runs finish() (flush + fsync + rename + dir-fsync — ~half the wall
    of a disk-bound compaction) on helper threads while the producer
    keeps writing the next run; `results()` joins every future BEFORE
    the manifest publish, so the durability ordering (all runs
    durable, then manifest) is unchanged. `shutdown(ok=False,
    open_writer=...)` is the crash cleanup: nothing may leak the pool,
    in-flight finishes, a half-written handle, or — critically —
    already-renamed partial l1-*.sst outputs (a legacy pre-manifest
    boot would adopt the highest-seq orphan as the whole L1)."""

    def __init__(self) -> None:
        import concurrent.futures as _cf

        self._pool = _cf.ThreadPoolExecutor(max_workers=2)
        self._futures: list = []
        self._writers: list = []

    @staticmethod
    def _finish_one(w) -> "SSTable":
        w.finish()
        return SSTable(w.path)

    def submit(self, w) -> None:
        self._writers.append(w)
        self._futures.append(self._pool.submit(self._finish_one, w))

    def results(self) -> List["SSTable"]:
        return [f.result() for f in self._futures]

    def shutdown(self, ok: bool, open_writer=None) -> None:
        self._pool.shutdown(wait=True)
        if ok:
            return
        for f, w in zip(self._futures, self._writers):
            try:
                t = f.result()
            except Exception:  # noqa: BLE001 - finish() died
                try:
                    w.abandon()
                except Exception:  # noqa: BLE001 - best-effort
                    pass
                continue
            try:
                t.close()
                os.remove(t.path)
            except OSError:
                pass
        if open_writer is not None:
            try:
                open_writer.abandon()
            except Exception:  # noqa: BLE001 - best-effort
                pass


class _HeapEntry:
    """Heap ordering: key asc (or desc when reverse), then source index asc —
    so for equal keys the newest source (lowest index) pops first."""

    __slots__ = ("key", "src_idx", "record", "it", "reverse")

    def __init__(self, key, src_idx, record, it, reverse):
        self.key = key
        self.src_idx = src_idx
        self.record = record
        self.it = it
        self.reverse = reverse

    def __lt__(self, other: "_HeapEntry") -> bool:
        if self.key != other.key:
            return self.key > other.key if self.reverse else self.key < other.key
        return self.src_idx < other.src_idx


def _merge(sources: List[Iterator[Record]], reverse: bool = False
           ) -> Iterator[Record]:
    """K-way merge; on duplicate keys the lowest source index (newest) wins;
    shadowed duplicates are skipped and tombstone winners are dropped."""
    heap: List[_HeapEntry] = []
    for src_idx, it in enumerate(sources):
        first = next(it, None)
        if first is not None:
            heap.append(_HeapEntry(first[0], src_idx, first, it, reverse))
    heapq.heapify(heap)
    prev_key: Optional[bytes] = None
    while heap:
        entry = heapq.heappop(heap)
        key, value, ets = entry.record
        if key != prev_key:
            prev_key = key
            if value is not None:  # tombstone winners are invisible
                yield key, value, ets
        nxt = next(entry.it, None)
        if nxt is not None:
            heapq.heappush(heap,
                           _HeapEntry(nxt[0], entry.src_idx, nxt, entry.it,
                                      reverse))


def _chain_runs(runs: List[SSTable], start: bytes, stop: Optional[bytes],
                reverse: bool) -> Iterator[Record]:
    """Iterate non-overlapping key-ordered runs as one ordered stream,
    skipping runs outside [start, stop)."""
    for run in runs:
        first = run.first_key or b""
        last = run.last_key or b""
        if stop is not None and first >= stop:
            continue
        if start and last < start:
            continue
        yield from run.iterate(start, stop, reverse)
