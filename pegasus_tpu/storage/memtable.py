"""Memtable: sorted in-memory write buffer with tombstones.

Parity: RocksDB's memtable role in the reference stack. Point lookups are
O(1) dict hits; ordered iteration sorts lazily (writes are batched by the
replication layer, scans amortize the sort). Deletes are tombstones so they
shadow older SST data until compaction drops them.
"""

from __future__ import annotations

import bisect
from typing import Iterator, Optional, Tuple

TOMBSTONE = None


class Memtable:
    def __init__(self) -> None:
        # key -> (value_bytes | TOMBSTONE, expire_ts)
        self._data: dict[bytes, Tuple[Optional[bytes], int]] = {}
        self._sorted_keys: list[bytes] = []
        self._dirty = False
        self._bytes = 0

    def __len__(self) -> int:
        return len(self._data)

    @property
    def approximate_bytes(self) -> int:
        return self._bytes

    def put(self, key: bytes, value: bytes, expire_ts: int = 0) -> None:
        old = self._data.get(key)
        if old is None:
            self._dirty = True
            self._bytes += len(key)
        else:
            self._bytes -= len(old[0] or b"")
        self._data[key] = (value, expire_ts)
        self._bytes += len(value)

    def delete(self, key: bytes) -> None:
        old = self._data.get(key)
        if old is None:
            self._dirty = True
            self._bytes += len(key)
        else:
            self._bytes -= len(old[0] or b"")
        self._data[key] = (TOMBSTONE, 0)

    def get(self, key: bytes) -> Optional[Tuple[Optional[bytes], int]]:
        """Returns (value|TOMBSTONE, expire_ts) or None when absent."""
        return self._data.get(key)

    def _ensure_sorted(self) -> None:
        if self._dirty:
            self._sorted_keys = sorted(self._data.keys())
            self._dirty = False

    def iterate(self, start: bytes = b"", stop: Optional[bytes] = None,
                reverse: bool = False
                ) -> Iterator[Tuple[bytes, Optional[bytes], int]]:
        """Yield (key, value|TOMBSTONE, expire_ts) for start <= key < stop."""
        self._ensure_sorted()
        keys = self._sorted_keys
        lo = bisect.bisect_left(keys, start) if start else 0
        hi = bisect.bisect_left(keys, stop) if stop is not None else len(keys)
        rng = range(hi - 1, lo - 1, -1) if reverse else range(lo, hi)
        for i in rng:
            k = keys[i]
            v, ets = self._data[k]
            yield k, v, ets

    def items_sorted(self) -> Iterator[Tuple[bytes, Optional[bytes], int]]:
        return self.iterate()
