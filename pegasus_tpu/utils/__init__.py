"""Utility layer (reference: src/utils/ — L0 of the layer map, SURVEY §1)."""

from pegasus_tpu.utils.errors import ErrorCode, PegasusError, rocksdb_status
from pegasus_tpu.utils.flags import FLAGS, define_flag, load_config
