"""Per-task-code profiler toollet.

Parity: the rDSN profiler toollet (src/runtime/profiler.cpp:90-198) —
per-task-code counters installed on the task engine's join points:
queue delay (enqueue -> dispatch), execute latency, throughput. Here
the task codes are the cluster's message types and the join points are
the transports' dispatch seams (rpc/transport.py dispatcher thread,
runtime/sim.py delivery), which every RPC/timer-driven task crosses.

Like the reference's toollet it is a cross-cutting OPT-IN pack: off by
default (zero overhead beyond one branch per dispatch), switched on per
node via the `task-profiler` remote command (shell: remote_command
<node> task-profiler enable|disable|clear|dump).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List

from pegasus_tpu.utils.metrics import Percentile


class _CodeStats:
    __slots__ = ("count", "queue_ms", "exec_ms", "started")

    def __init__(self) -> None:
        self.count = 0
        self.queue_ms = Percentile(window=1024)
        self.exec_ms = Percentile(window=1024)
        self.started = time.monotonic()


class TaskProfiler:
    """Process-wide per-code stats; one instance per process (the
    reference's profiler state is likewise per-node)."""

    def __init__(self) -> None:
        self.enabled = False
        self._stats: Dict[str, _CodeStats] = {}
        self._lock = threading.Lock()

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        with self._lock:
            self._stats.clear()

    def observe(self, code: str, queue_ms: float, exec_ms: float) -> None:
        with self._lock:
            st = self._stats.get(code)
            if st is None:
                st = self._stats[code] = _CodeStats()
            st.count += 1  # non-atomic RMW: many dispatchers, one lock
        st.queue_ms.set(queue_ms)
        st.exec_ms.set(exec_ms)

    def dump(self) -> List[dict]:
        """Per-code profile rows, busiest first (the reference's
        profiler data surface: THROUGHPUT + QUEUE + EXEC latencies per
        task code)."""
        now = time.monotonic()
        out = []
        with self._lock:
            items = list(self._stats.items())
        for code, st in items:
            window = max(now - st.started, 1e-9)
            q50, q99 = st.queue_ms.quantiles((50, 99))
            e50, e99 = st.exec_ms.quantiles((50, 99))
            out.append({
                "code": code,
                "count": st.count,
                "qps": round(st.count / window, 1),
                "queue_ms_p50": round(q50, 3),
                "queue_ms_p99": round(q99, 3),
                "exec_ms_p50": round(e50, 3),
                "exec_ms_p99": round(e99, 3),
            })
        return sorted(out, key=lambda d: -d["count"])

    def publish(self, registry=None) -> int:
        """Mirror the per-code profile onto the metrics spine: one
        "task" entity per code with count / qps / queue-p99 / exec-p99,
        so enabled-profiler stats appear in Prometheus exposition and
        the flight recorder's rings instead of living only behind the
        text `remote_command ... dump`. Idempotent per call; returns
        the number of codes published."""
        if registry is None:
            from pegasus_tpu.utils.metrics import METRICS as registry
        rows = self.dump()
        for row in rows:
            ent = registry.entity("task", row["code"],
                                  {"code": row["code"]})
            c = ent.counter("task_dispatch_count")
            delta = row["count"] - c.value()
            if delta > 0:
                c.increment(delta)
            ent.gauge("task_qps").set(row["qps"])
            ent.gauge("task_queue_ms_p50").set(row["queue_ms_p50"])
            ent.gauge("task_queue_ms_p99").set(row["queue_ms_p99"])
            ent.gauge("task_exec_ms_p50").set(row["exec_ms_p50"])
            ent.gauge("task_exec_ms_p99").set(row["exec_ms_p99"])
        return len(rows)

    def control(self, args: List[str]):
        """The `task-profiler` command verb body."""
        verb = args[0] if args else "dump"
        if verb == "enable":
            self.enable()
            return "task profiler enabled"
        if verb == "disable":
            self.disable()
            return "task profiler disabled"
        if verb == "clear":
            self.clear()
            return "task profiler cleared"
        self.publish()  # a dump is also a publish: scrapes see it too
        return self.dump()


PROFILER = TaskProfiler()
