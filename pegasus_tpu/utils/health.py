"""Health-rules watchdog over the flight recorder's rings.

Declarative rules — threshold, burn-rate over a window, z-score spike —
evaluate every recorder tick against any recorded series and emit typed
``HealthEvent``s (entity, rule, severity, firing/cleared, evidence =
the offending ring slice) into a bounded per-node journal. Nothing in
the cluster previously *decided* it was unhealthy; this is the layer
that turns raw counters into a decision an on-call human (or the
elasticity controller, later) can act on.

A firing event also auto-pins deeper capture: the PR 9 trace sample
ratio is temporarily raised (so the forensic spans exist for exactly
the windows that matter — tail keep then pins the slow ones) and the
TaskProfiler is enabled for the incident window, its dump snapshotted
onto the cleared event. Pins are refcounted process-wide so overlapping
incidents restore the operator's settings exactly once.

Flap damping is built into the state machine: a rule must hold its
violation `hold` consecutive evaluations to fire and stay clean
`clear_hold` evaluations to clear; burn-rate additionally requires the
LATEST sample over threshold, so a single blip can never hold the
windowed mean up on its own.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from pegasus_tpu.utils.flags import FLAGS, define_flag
from pegasus_tpu.utils.timeseries import FlightRecorder

define_flag("pegasus.health", "journal_cap", 256,
            "health events retained per node (drop-oldest)",
            mutable=True)
define_flag("pegasus.health", "report_max_events", 32,
            "max events shipped per config-sync report (overflow is "
            "counted, never silently lost)", mutable=True)
define_flag("pegasus.health", "pin_sample_ratio", 0.1,
            "trace sample ratio while any health rule fires (auto-pin "
            "deeper capture; restored on clear). Deliberately modest: "
            "an incident is exactly when the node can least afford a "
            "heavy observer", mutable=True)

SEV_DEGRADED = "degraded"
SEV_CRITICAL = "critical"
_SEV_RANK = {"ok": 0, SEV_DEGRADED: 1, SEV_CRITICAL: 2}


def worse(a: str, b: str) -> str:
    return a if _SEV_RANK.get(a, 0) >= _SEV_RANK.get(b, 0) else b


@dataclass
class HealthRule:
    """One declarative rule over recorded series.

    kind:
      - ``threshold``: latest sample > threshold;
      - ``burn_rate``: mean over the trailing `window_s` > threshold AND
        the latest sample > threshold (>= `min_points` samples);
      - ``zscore``: latest sample deviates > `threshold` standard
        deviations from the mean of the PRIOR samples in the window
        (>= `min_points` history samples).
    """

    name: str
    entity_type: str
    metric: str
    kind: str = "threshold"
    threshold: float = 0.0
    window_s: float = 30.0
    min_points: int = 2
    hold: int = 1
    clear_hold: int = 2
    severity: str = SEV_DEGRADED
    entity_id: Optional[str] = None
    description: str = ""


@dataclass
class HealthEvent:
    """Typed watchdog verdict: one rule transition on one entity."""

    node: str
    rule: str
    severity: str
    firing: bool  # True = fired, False = cleared
    entity: Tuple[str, str]
    metric: str
    ts: float
    value: float
    reason: str
    evidence: List[List[float]] = field(default_factory=list)
    profile: Optional[List[dict]] = None

    def to_dict(self) -> dict:
        d = {"node": self.node, "rule": self.rule,
             "severity": self.severity, "firing": self.firing,
             "entity": list(self.entity), "metric": self.metric,
             "ts": round(self.ts, 3), "value": round(self.value, 4),
             "reason": self.reason, "evidence": self.evidence}
        if self.profile is not None:
            d["profile"] = self.profile
        return d


def default_rules() -> List[HealthRule]:
    """The shipped watchdog pack, matched to the counters the previous
    PRs already maintain. Rates are per-second (counter series are
    recorded as rates); thresholds are deliberately loose — a rule that
    cries wolf on a healthy soak is worse than none."""
    return [
        HealthRule("read_shed_growth", "rpc", "read_shed_count",
                   kind="burn_rate", threshold=1.0, window_s=30.0,
                   min_points=2, severity=SEV_DEGRADED,
                   description="sustained read shedding (> 1/s): the "
                   "node is refusing read load to protect itself"),
        HealthRule("deadline_growth", "rpc", "deadline_expired_count",
                   kind="burn_rate", threshold=1.0, window_s=30.0,
                   min_points=2, severity=SEV_DEGRADED,
                   description="sustained deadline expiry (> 1/s): "
                   "clients give up before the node answers"),
        HealthRule("scrub_corruption", "storage", "scrub_corrupt_blocks",
                   kind="threshold", threshold=0.0,
                   severity=SEV_CRITICAL,
                   description="background scrub found at-rest "
                   "corruption"),
        HealthRule("replica_quarantine", "storage",
                   "replica_quarantine_count", kind="threshold",
                   threshold=0.0, severity=SEV_CRITICAL,
                   description="a replica failed integrity checks and "
                   "was quarantined for re-learn"),
        HealthRule("dup_lag", "duplication", "dup_lag_decrees",
                   kind="burn_rate", threshold=500.0, window_s=60.0,
                   min_points=2, severity=SEV_DEGRADED,
                   description="geo-replication falling behind "
                   "(> 500 decrees sustained)"),
        HealthRule("stale_bounce_rate", "storage", "stale_bounce_count",
                   kind="burn_rate", threshold=1.0, window_s=30.0,
                   min_points=2, severity=SEV_DEGRADED,
                   description="sustained follower-read bounces (> 1/s "
                   "ERR_STALE_REPLICA): secondaries keep declining "
                   "consistency-levelled reads — lease lapses (check "
                   "fd_beacon_miss) or replication lag beyond the "
                   "bound, and every bounce is a wasted round-trip "
                   "re-flown at the primary"),
        HealthRule("fd_beacon_miss", "rpc", "beacon_ack_age_s",
                   kind="threshold", threshold=9.0, hold=2,
                   severity=SEV_DEGRADED,
                   description="no failure-detector beacon ack for 3+ "
                   "intervals on 2 consecutive ticks: meta link (or "
                   "lease) is in trouble (hold=2: a backoff-stretched "
                   "schedule step alone must not fire it)"),
        HealthRule("compaction_stall", "storage",
                   "compact_write_stall_ms", kind="burn_rate",
                   threshold=500.0, window_s=60.0, min_points=2,
                   severity=SEV_DEGRADED,
                   description="compaction write stage stalled > 0.5s "
                   "per wall second: background IO is wedged"),
        HealthRule("cost_model_drift", "workload",
                   "cost_model_drift_ratio", kind="threshold",
                   threshold=16.0, hold=2, severity=SEV_DEGRADED,
                   description="placement cost model mis-calibrated: "
                   "measured kernel time sustained > 16x the model's "
                   "prediction (rolling median, compile-warmup "
                   "discarded, stale classes age out) — device-vs-host "
                   "routing is deciding on bad estimates "
                   "(server/workload.DRIFT audits every stacked "
                   "mask-eval wave)"),
        HealthRule("tunnel_wedged", "storage", "tunnel_wedged",
                   kind="threshold", threshold=0.5, hold=2,
                   severity=SEV_DEGRADED,
                   description="the mesh dispatch watchdog tripped "
                   "(consecutive bounded-deadline overruns): serving "
                   "fell back to the CPU-device mesh or to host "
                   "kernels — results stay correct but the accelerator "
                   "leg is out (hold=2: one spurious deadline alone "
                   "must not fire it)"),
        HealthRule("tenant_brownout", "tenant", "tenant_cu_ratio",
                   kind="burn_rate", threshold=2.0, window_s=30.0,
                   min_points=2, hold=2, clear_hold=2,
                   severity=SEV_DEGRADED,
                   description="one tenant's CU consumption sustained "
                   "> 2x its budget: the aggressor outlier. The stubs "
                   "react by shedding ONLY this tenant's reads "
                   "(server/tenancy.py brownout state) — the series is "
                   "per-tenant, so a compliant tenant can never trip "
                   "it; clear_hold releases the gate once shedding "
                   "pulls the ratio back under budget"),
    ]


# ---- auto-pin deeper capture (process-wide, refcounted) ------------------


class _CapturePin:
    """While ANY rule fires anywhere in the process, raise the tracing
    sample ratio and enable the task profiler; restore both when the
    last incident clears. Refcounted: overlapping incidents restore
    the operator's settings exactly once."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._count = 0
        self._saved_ratio: Optional[float] = None
        self._set_ratio: Optional[float] = None
        self._saved_profiler: Optional[bool] = None

    def pin(self) -> None:
        from pegasus_tpu.utils.profiler import PROFILER

        with self._lock:
            self._count += 1
            if self._count > 1:
                return
            ratio = FLAGS.get("pegasus.tracing", "sample_ratio")
            self._saved_ratio = ratio
            self._set_ratio = None
            boost = FLAGS.get("pegasus.health", "pin_sample_ratio")
            if boost > ratio:
                FLAGS.set("pegasus.tracing", "sample_ratio", boost)
                self._set_ratio = boost
            self._saved_profiler = PROFILER.enabled
            PROFILER.enable()

    def unpin(self) -> None:
        from pegasus_tpu.utils.profiler import PROFILER

        with self._lock:
            if self._count == 0:
                return
            self._count -= 1
            if self._count > 0:
                return
            if self._set_ratio is not None and FLAGS.get(
                    "pegasus.tracing", "sample_ratio") == self._set_ratio:
                # restore ONLY if the ratio is still the one we set: an
                # operator who re-tuned it mid-incident keeps their value
                FLAGS.set("pegasus.tracing", "sample_ratio",
                          self._saved_ratio)
            if self._saved_profiler is False:
                PROFILER.disable()
            self._saved_ratio = None
            self._set_ratio = None
            self._saved_profiler = None

    def force_release(self, n: int) -> None:
        """Drop `n` outstanding pins (an engine closing mid-incident
        must not leave the process's capture settings raised)."""
        for _ in range(n):
            self.unpin()

    @property
    def count(self) -> int:
        return self._count


CAPTURE = _CapturePin()


def reset_capture() -> None:
    """Test isolation: release every outstanding pin."""
    CAPTURE.force_release(CAPTURE.count)


# ---- the engine ----------------------------------------------------------


class HealthEngine:
    """Per-node watchdog: evaluates rules over the node's recorder each
    tick, maintains per-(rule, series) firing state with flap damping,
    journals typed events, and drives the capture pin."""

    def __init__(self, node: str, recorder: FlightRecorder,
                 rules: Optional[List[HealthRule]] = None,
                 clock: Optional[Callable[[], float]] = None) -> None:
        self.node = node
        self.recorder = recorder
        self.rules = list(rules) if rules is not None else default_rules()
        self.clock = clock or recorder.clock
        # (rule.name, series key) -> {"viol": n, "clean": n,
        #                             "firing": bool, "since": ts}
        self._state: Dict[Tuple[str, Tuple[str, str, str]], dict] = {}
        self.journal: "deque[dict]" = deque()
        self._unreported: List[dict] = []
        # events shipped but not yet acked by a config_sync_reply: a
        # report sent INTO a broken meta link (exactly the incident the
        # watchdog exists for) must not lose its events — they re-ship
        # until the reply's health_ack covers their seq
        self._pending_ack: List[dict] = []
        self._event_seq = 0
        self.dropped_reports = 0
        self.events_total = 0

    # -- evaluation -------------------------------------------------------

    def evaluate(self) -> List[HealthEvent]:
        """One watchdog pass; returns the TRANSITIONS (fired/cleared)."""
        now = self.clock()
        out: List[HealthEvent] = []
        live_keys = set()
        for rule in self.rules:
            for key, ring in self.recorder.match(rule.entity_type,
                                                 rule.entity_id,
                                                 rule.metric):
                live_keys.add((rule.name, key))
                ev = self._eval_series(rule, key, ring, now)
                if ev is not None:
                    out.append(ev)
        # series that fell out of the window while firing: clear them
        # (the signal died; holding the alert open pins capture forever)
        for skey, st in list(self._state.items()):
            if skey in live_keys or not st["firing"]:
                if skey not in live_keys and not st["firing"]:
                    del self._state[skey]
                continue
            st["clean"] += 1
            if st["clean"] >= self._rule(skey[0]).clear_hold:
                out.append(self._transition(
                    self._rule(skey[0]), skey[1], now, 0.0,
                    "series expired from ring", firing=False))
        for ev in out:
            self._journal(ev)
        return out

    def _rule(self, name: str) -> HealthRule:
        for r in self.rules:
            if r.name == name:
                return r
        raise KeyError(name)

    def _eval_series(self, rule: HealthRule, key, ring,
                     now: float) -> Optional[HealthEvent]:
        violated, value, reason = self._check(rule, ring, now)
        skey = (rule.name, key)
        st = self._state.get(skey)
        if st is None:
            st = self._state[skey] = {"viol": 0, "clean": 0,
                                      "firing": False, "since": None}
        if violated:
            st["viol"] += 1
            st["clean"] = 0
            if not st["firing"] and st["viol"] >= rule.hold:
                return self._transition(rule, key, now, value, reason,
                                        firing=True)
        else:
            st["clean"] += 1
            st["viol"] = 0
            if st["firing"] and st["clean"] >= rule.clear_hold:
                return self._transition(rule, key, now, value,
                                        "recovered", firing=False)
        return None

    def _check(self, rule: HealthRule, ring,
               now: float) -> Tuple[bool, float, str]:
        latest = ring.latest()
        if latest is None:
            return False, 0.0, ""
        ts, x = latest
        unit = "/s" if ring.kind == "rate" else ""
        if rule.kind == "threshold":
            return (x > rule.threshold, x,
                    f"{rule.metric}={x:.4g}{unit} > {rule.threshold:g}")
        window = ring.slice(now - rule.window_s)
        if rule.kind == "burn_rate":
            if len(window) < rule.min_points:
                return False, x, ""
            mean = sum(v for _t, v in window) / len(window)
            # the LAST TWO samples must also be hot: "burn" means
            # consecutive ticks over threshold, so neither a single
            # blip after a quiet stretch (the idle run-length slide
            # leaves only one trailing zero to dilute the mean) nor a
            # spike propping the mean up after it passed can fire
            hit = (mean > rule.threshold and x > rule.threshold
                   and window[-2][1] > rule.threshold)
            return (hit, mean,
                    f"{rule.metric} mean {mean:.4g}{unit} over "
                    f"{rule.window_s:g}s > {rule.threshold:g}")
        if rule.kind == "zscore":
            history = [v for _t, v in window[:-1]]
            if len(history) < rule.min_points:
                return False, x, ""
            mean = sum(history) / len(history)
            var = sum((v - mean) ** 2 for v in history) / len(history)
            std = max(var ** 0.5, 1e-9)
            z = (x - mean) / std
            return (z > rule.threshold, z,
                    f"{rule.metric}={x:.4g}{unit} is {z:.1f}σ above "
                    f"its {rule.window_s:g}s mean {mean:.4g}")
        raise ValueError(f"unknown rule kind {rule.kind!r}")

    def _transition(self, rule: HealthRule, key, now: float,
                    value: float, reason: str,
                    firing: bool) -> HealthEvent:
        from pegasus_tpu.utils.profiler import PROFILER

        skey = (rule.name, key)
        st = self._state[skey]
        st["firing"] = firing
        st["since"] = now if firing else None
        st["viol"] = 0
        st["clean"] = 0
        ring = self.recorder._series.get(key)
        evidence = [[round(t, 3), round(v, 4)]
                    for t, v in (ring.slice(now - rule.window_s)
                                 if ring is not None else [])]
        ev = HealthEvent(
            node=self.node, rule=rule.name, severity=rule.severity,
            firing=firing, entity=(key[0], key[1]), metric=key[2],
            ts=now, value=value, reason=reason, evidence=evidence)
        if firing:
            # auto-pin deeper capture: raise the trace sample ratio and
            # start profiling — the forensic detail exists for exactly
            # the window that matters (no dump here: pre-incident
            # profiler state is stale by definition, and a flapping
            # rule must not pay a dump per transition)
            CAPTURE.pin()
        else:
            # the incident-window profile rides the CLEARED event, then
            # capture settings restore
            ev.profile = PROFILER.dump() or None
            CAPTURE.unpin()
        if not firing:
            del self._state[skey]
        return ev

    def _journal(self, ev: HealthEvent) -> None:
        d = ev.to_dict()
        self.events_total += 1
        self.journal.append(d)
        cap = FLAGS.get("pegasus.health", "journal_cap")
        while len(self.journal) > cap:
            self.journal.popleft()
        if len(self._unreported) < FLAGS.get("pegasus.health",
                                             "report_max_events"):
            # strip the bulky fields from the config-sync copy: meta
            # needs the verdicts; the evidence stays fetchable on the
            # node via health.events / timeseries-dump
            slim = dict(d)
            slim.pop("profile", None)
            slim["evidence"] = slim["evidence"][-8:]
            self._event_seq += 1
            slim["seq"] = self._event_seq
            self._unreported.append(slim)
        else:
            self.dropped_reports += 1

    # -- read surfaces ----------------------------------------------------

    def firing(self) -> List[dict]:
        return [{"rule": name, "entity": list(key[:2]),
                 "metric": key[2],
                 "severity": self._rule(name).severity,
                 "since": st["since"]}
                for (name, key), st in sorted(self._state.items())
                if st["firing"]]

    def status(self) -> dict:
        firing = self.firing()
        sev = "ok"
        for f in firing:
            sev = worse(sev, f["severity"])
        return {"node": self.node, "status": sev, "firing": firing,
                "events_total": self.events_total,
                "ring_bytes": self.recorder.nbytes(),
                "ring_series": len(self.recorder._series)}

    def events(self, limit: int = 64,
               entity_id: Optional[str] = None) -> List[dict]:
        out = [d for d in self.journal
               if entity_id is None or d["entity"][1] == entity_id]
        return out[-limit:]

    def drain_report(self) -> dict:
        """The compact health block riding config-sync: digest + the
        events since the last report (bounded; overflow counted).
        Events stay in the unacked buffer and RE-SHIP every report
        until ack_report covers their seq — a report lost on a broken
        meta link (the incident itself) loses nothing; meta dedupes by
        seq."""
        cap = FLAGS.get("pegasus.health", "report_max_events")
        take = max(0, cap - len(self._pending_ack))
        self._pending_ack.extend(self._unreported[:take])
        overflow = len(self._unreported) - take
        if overflow > 0:
            self.dropped_reports += overflow
        self._unreported = []
        dropped, self.dropped_reports = self.dropped_reports, 0
        st = self.status()
        return {"status": st["status"], "firing": st["firing"],
                "events": list(self._pending_ack), "dropped": dropped,
                # seq high-water: meta detects a node restart (fresh
                # engine, seq reset) when this moves BACKWARD and
                # resets its dedupe cursor — otherwise every event from
                # the restarted node would be deduped away and falsely
                # acked until seq caught up
                "seq_hw": self._event_seq,
                "events_total": self.events_total,
                "ring_bytes": st["ring_bytes"]}

    def ack_report(self, seq: int) -> None:
        """config_sync_reply carried meta's high-water event seq: every
        shipped event at or below it is safely journaled meta-side."""
        self._pending_ack = [e for e in self._pending_ack
                             if e["seq"] > seq]

    def close(self) -> None:
        """Release this engine's outstanding capture pins (a node going
        away mid-incident must not leave process capture raised)."""
        n = sum(1 for st in self._state.values() if st["firing"])
        CAPTURE.force_release(n)
        self._state.clear()


# ---- incident-timeline rendering -----------------------------------------

_SPARK = " .:-=+*#%@"


def _sparkline(points: List[List[float]], width: int = 48) -> str:
    if not points:
        return ""
    t0, t1 = points[0][0], points[-1][0]
    span = max(t1 - t0, 1e-9)
    vmax = max(v for _t, v in points)
    vmin = min(0.0, min(v for _t, v in points))
    vspan = max(vmax - vmin, 1e-9)
    cells = [0.0] * width
    for ts, v in points:
        i = min(width - 1, int((ts - t0) / span * width))
        cells[i] = max(cells[i], (v - vmin) / vspan)
    return "".join(_SPARK[min(len(_SPARK) - 1,
                              int(c * (len(_SPARK) - 1) + 0.5))]
                   for c in cells)


def render_timeline(bundle: dict, width: int = 48) -> str:
    """ONE incident report from a timeline bundle:

    ``{"target", "window": [t0, t1], "status", "events": [...],
       "series": [recorder dump rows], "traces": [slow roots]}``

    Ring slices render as sparklines, health events as a chronological
    ledger, kept slow traces as a summary list — the operator reads the
    whole incident top to bottom without another command.
    """
    t0, t1 = bundle.get("window", (None, None))
    lines = [f"== timeline {bundle.get('target', '?')} — "
             f"status {bundle.get('status', '?')}"
             + (f", window {t1 - t0:.0f}s" if t0 is not None else "")
             + " =="]
    events = bundle.get("events") or []
    lines.append(f"-- health events ({len(events)}) --")
    for d in events:
        mark = "FIRING " if d.get("firing") else "CLEARED"
        rel = f"t+{d['ts'] - t0:8.1f}s" if t0 is not None \
            else f"@{d['ts']:.1f}"
        lines.append(
            f"  {rel}  {mark} {d['severity']:<8} {d['rule']} "
            f"[{d['entity'][0]}/{d['entity'][1]}] {d['reason']}")
    series = bundle.get("series") or []
    if series:
        lines.append(f"-- ring slices ({len(series)}) --")
    for row in series:
        pts = row.get("points") or []
        if not pts:
            continue
        vmax = max(v for _t, v in pts)
        unit = "/s" if row.get("kind") == "rate" else ""
        lines.append(
            f"  {row['entity']}/{row['id']} {row['metric']} "
            f"(peak {vmax:.4g}{unit}, {len(pts)} pts)")
        lines.append(f"  |{_sparkline(pts, width)}|")
    traces = bundle.get("traces") or []
    lines.append(f"-- kept slow traces ({len(traces)}) --")
    for t in traces:
        lines.append(
            f"  trace {t.get('trace')}  {t.get('name')} "
            f"@{t.get('node')}  {t.get('total_ms', 0.0):.3f} ms")
    return "\n".join(lines)


def parse_window(text: str) -> float:
    """'5m' / '90s' / '2h' / bare seconds -> seconds."""
    text = str(text).strip()
    mult = {"s": 1.0, "m": 60.0, "h": 3600.0}.get(text[-1:].lower())
    if mult is not None:
        return float(text[:-1]) * mult
    return float(text)
