"""Latency tracer: per-request stage-timestamp chains + slow-query log.

Parity: src/utils/latency_tracer.h:94 (ADD_POINT :37 — every mutation
carries a tracer whose stage chain is dumped when the request is slow,
dump_trace_points :170) and the slow-query surfaces the shell reads.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

from pegasus_tpu.utils.tracing import current_span as _current_span


class LatencyTracer:
    """One request's stage chain. Cheap: a list of (stage, t) tuples.

    When a distributed-tracing span is active at creation (or passed
    explicitly), every stage point ALSO lands on that span as an
    annotation — the per-process stage chain and the cross-process span
    tree share one instrumentation layer (utils/tracing.py)."""

    __slots__ = ("name", "points", "_clock", "span", "perf")

    def __init__(self, name: str, clock=time.perf_counter,
                 span=None) -> None:
        self.name = name
        self._clock = clock
        self.span = span if span is not None else _current_span()
        # the op's PerfContext cost vector (utils/perf_context.py),
        # bound by the paths that collect one: the slow log attaches it
        # to the entry so a slow dump shows counts, not just durations
        self.perf = None
        self.points: List[Tuple[str, float]] = [("start", clock())]

    def add_point(self, stage: str) -> None:
        self.points.append((stage, self._clock()))
        sp = self.span
        if sp is not None:
            sp.annotate(stage)

    def total_ms(self) -> float:
        return (self.points[-1][1] - self.points[0][1]) * 1000.0

    def report(self) -> Dict[str, Any]:
        """The dump shape (parity: dump_trace_points): cumulative and
        per-stage deltas in ms."""
        t0 = self.points[0][1]
        stages = []
        prev = t0
        for stage, t in self.points[1:]:
            stages.append({"stage": stage,
                           "delta_ms": round((t - prev) * 1000.0, 3),
                           "at_ms": round((t - t0) * 1000.0, 3)})
            prev = t
        return {"name": self.name,
                "total_ms": round(self.total_ms(), 3),
                "stages": stages}


class SlowQueryLog:
    """Bounded ring of slow-request dumps (newest last), one per node or
    per partition server. Thread-safe: the TCP transport observes from
    the dispatcher while remote commands read from HTTP threads."""

    def __init__(self, threshold_ms: float = 20.0,
                 capacity: int = 64) -> None:
        self.threshold_ms = threshold_ms
        self._ring: Deque[Dict[str, Any]] = deque(maxlen=capacity)
        self._lock = threading.Lock()

    def observe(self, tracer: LatencyTracer,
                extra: Optional[Dict[str, Any]] = None) -> bool:
        ms = tracer.total_ms()
        if ms < self.threshold_ms:
            return False
        report = tracer.report()
        if extra:
            report.update(extra)
        if tracer.perf is not None:
            # the op's cost vector rides the slow entry: WHY it cost
            # what it cost, next to the stage chain's WHERE
            report["perf"] = tracer.perf.to_dict()
        with self._lock:
            self._ring.append(report)
        return True

    def observe_simple(self, name: str, elapsed_ms: float,
                       extra: Optional[Dict[str, Any]] = None) -> bool:
        """For paths that only time start->end (the solo-read
        fallback). The AMBIENT PerfContext (when the solo path
        collected one) attaches here so solo and batched slow entries
        stay field-comparable."""
        if elapsed_ms < self.threshold_ms:
            return False
        report = {"name": name, "total_ms": round(elapsed_ms, 3)}
        if extra:
            report.update(extra)
        if "perf" not in report:
            from pegasus_tpu.utils.perf_context import current as _pc

            pc = _pc()
            if pc is not None:
                report["perf"] = pc.to_dict()
        with self._lock:
            self._ring.append(report)
        return True

    def dump(self, clear: bool = False) -> List[Dict[str, Any]]:
        with self._lock:
            out = list(self._ring)
            if clear:
                self._ring.clear()
        return out
