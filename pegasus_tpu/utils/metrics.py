"""Metrics: entities × {gauge, counter, volatile counter, percentile}.

Parity: the reference's Kudu-inspired metric library (src/utils/metrics.h:71-135)
— metric entities (server/table/replica/...) each hold attributed metrics;
percentiles are computed by nth-element over a bounded sample window
(p50..p999); snapshots are served as JSON over HTTP /metrics
(src/http/builtin_http_calls.cpp:280-288). We reproduce the same model
in-process; the HTTP surface arrives with the server layer.
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

_PERCENTILES = (50.0, 90.0, 95.0, 99.0, 99.9)


class Counter:
    __slots__ = ("_value", "_lock")

    def __init__(self) -> None:
        self._value = 0
        self._lock = threading.Lock()

    def increment(self, by: int = 1) -> None:
        with self._lock:
            self._value += by

    def value(self) -> int:
        return self._value

    def snapshot(self) -> Dict[str, Any]:
        return {"type": "counter", "value": self._value}


class RelaxedCounter(Counter):
    """Lock-free counter for per-block hot paths (block-cache hits run
    once per SST block read). `+=` on a Python int is not atomic across
    threads, so concurrent increments may occasionally be lost — the
    relaxed-memory-order trade every stats counter makes in the
    reference; values are for observability, never for accounting."""

    __slots__ = ()

    def __init__(self) -> None:
        self._value = 0
        self._lock = None

    def increment(self, by: int = 1) -> None:
        self._value += by

    def snapshot(self) -> Dict[str, Any]:
        return {"type": "counter", "value": self._value}


class VolatileCounter(Counter):
    """Delta-readable counter (reference: metrics.h volatile counter).

    The reference resets on read — safe there because exactly one
    scraper owns each counter. Here the flight recorder, the info
    collector, and `/metrics` scrapes all read concurrently, and
    reset-on-read made them silently steal each other's deltas: a
    delta consumed by one reader was a delta the others never saw.
    The counter is now CUMULATIVE with a per-reader cursor:
    `delta_since(reader_id)` returns the increments since that
    reader's previous call, so every reader observes the full sum.
    """

    __slots__ = ("_cursors",)

    def __init__(self) -> None:
        super().__init__()
        self._cursors: Dict[str, int] = {}

    def delta_since(self, reader_id: str) -> int:
        """Increments since this reader's last call (first call: since
        creation). Each reader's cursor is independent."""
        with self._lock:
            v = self._value
            delta = v - self._cursors.get(reader_id, 0)
            self._cursors[reader_id] = v
            return delta

    def fetch_and_reset(self) -> int:
        """Deprecated shim for the old reset-on-read surface: one
        implicit shared reader. `value()` keeps reporting the
        cumulative sum (it no longer resets underneath anyone)."""
        return self.delta_since("__legacy_reset__")

    def snapshot(self) -> Dict[str, Any]:
        # cumulative, like a plain counter: a snapshot (JSON /metrics or
        # Prometheus scrape) must never consume another reader's delta —
        # and Prometheus counters are cumulative by contract anyway
        return {"type": "volatile_counter", "value": self._value}


class Gauge:
    __slots__ = ("_value",)

    def __init__(self, initial: float = 0) -> None:
        self._value = initial

    def set(self, value: float) -> None:
        self._value = value

    def value(self) -> float:
        return self._value

    def snapshot(self) -> Dict[str, Any]:
        return {"type": "gauge", "value": self._value}


class Percentile:
    """Bounded-window percentile metric (reference: metrics.h:104 percentile
    via nth-element over a 4096-sample window).

    The sorted view is version-cached: readers that poll faster than
    writers feed (the flight recorder each tick, the profiler publish,
    repeated snapshots) sort once per window CHANGE, not once per read
    — without it a sim schedule that compresses hours of virtual time
    re-sorted every window thousands of times."""

    def __init__(self, window: int = 4096) -> None:
        self._window = window
        self._samples: List[float] = []
        self._idx = 0
        self._version = 0
        self._sorted: Optional[Tuple[int, List[float]]] = None
        self._lock = threading.Lock()

    def set(self, sample: float) -> None:
        with self._lock:
            if len(self._samples) < self._window:
                self._samples.append(sample)
            else:
                self._samples[self._idx] = sample
                self._idx = (self._idx + 1) % self._window
            self._version += 1

    @property
    def version(self) -> int:
        """Bumps on every sample: lets pollers skip unchanged windows."""
        return self._version

    def _sorted_view(self) -> List[float]:
        # caller holds self._lock
        if self._sorted is None or self._sorted[0] != self._version:
            self._sorted = (self._version, sorted(self._samples))
        return self._sorted[1]

    def percentile(self, p: float) -> float:
        return self.quantiles((p,))[0]

    def quantiles(self, ps) -> List[float]:
        """Several percentile levels off ONE (cached) sort."""
        with self._lock:
            if not self._samples:
                return [0.0] * len(ps)
            s = self._sorted_view()
            return [s[min(len(s) - 1, int(len(s) * p / 100.0))]
                    for p in ps]

    def snapshot(self) -> Dict[str, Any]:
        vals = self.quantiles(_PERCENTILES)
        return {
            "type": "percentile",
            **{f"p{str(p).rstrip('0').rstrip('.')}": v
               for p, v in zip(_PERCENTILES, vals)},
        }


class MetricEntity:
    """A named entity (server/table/replica/partition) owning metrics.

    Parity: src/utils/metrics.h metric_entity with attributes.
    """

    def __init__(self, entity_type: str, entity_id: str,
                 attrs: Optional[Dict[str, str]] = None) -> None:
        self.entity_type = entity_type
        self.entity_id = entity_id
        self.attrs = dict(attrs or {})
        self._metrics: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, name: str, factory):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            return m

    def counter(self, name: str) -> Counter:
        return self._get_or_create(name, Counter)

    def relaxed_counter(self, name: str) -> RelaxedCounter:
        return self._get_or_create(name, RelaxedCounter)

    def volatile_counter(self, name: str) -> VolatileCounter:
        return self._get_or_create(name, VolatileCounter)

    def gauge(self, name: str) -> Gauge:
        return self._get_or_create(name, Gauge)

    def percentile(self, name: str) -> Percentile:
        return self._get_or_create(name, Percentile)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "type": self.entity_type,
                "id": self.entity_id,
                "attributes": dict(self.attrs),
                "metrics": {n: m.snapshot() for n, m in self._metrics.items()},
            }


class MetricRegistry:
    """Process-global registry of entities (reference: metrics.h:385 registry,
    JSON snapshot with entity-type/metric filters metrics.h:522-551)."""

    def __init__(self) -> None:
        self._entities: Dict[Tuple[str, str], MetricEntity] = {}
        self._lock = threading.Lock()

    def entity(self, entity_type: str, entity_id: str,
               attrs: Optional[Dict[str, str]] = None) -> MetricEntity:
        key = (entity_type, entity_id)
        with self._lock:
            ent = self._entities.get(key)
            if ent is None:
                ent = MetricEntity(entity_type, entity_id, attrs)
                self._entities[key] = ent
            return ent

    def entities(self) -> List[MetricEntity]:
        """Live entity objects (the flight recorder walks these directly
        each tick: cheaper than snapshot(), which computes every
        percentile level, and it needs the metric OBJECTS to take
        per-reader cursors on volatile counters)."""
        with self._lock:
            return list(self._entities.values())

    def snapshot(self, entity_type: Optional[str] = None,
                 metric_names: Optional[List[str]] = None) -> List[Dict[str, Any]]:
        with self._lock:
            entities = list(self._entities.values())
        out = []
        for ent in entities:
            if entity_type is not None and ent.entity_type != entity_type:
                continue
            snap = ent.snapshot()
            if metric_names is not None:
                snap["metrics"] = {
                    n: v for n, v in snap["metrics"].items() if n in metric_names
                }
            out.append(snap)
        return out


METRICS = MetricRegistry()


# ---- Prometheus text exposition -----------------------------------------

_PROM_NAME_BAD = None  # lazy-compiled regex


def _prom_name(name: str) -> str:
    global _PROM_NAME_BAD
    if _PROM_NAME_BAD is None:
        import re

        _PROM_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")
    out = _PROM_NAME_BAD.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _prom_label_value(v: Any) -> str:
    return str(v).replace("\\", "\\\\").replace("\n", "\\n") \
        .replace('"', '\\"')


def to_prometheus(snapshot: List[Dict[str, Any]],
                  prefix: str = "pegasus_") -> str:
    """Render a MetricRegistry snapshot in the Prometheus text format
    (version 0.0.4): counters/gauges as-is, percentile windows as
    summaries with quantile labels; entity type/id and entity
    attributes become labels. The SURVEY collector->Prometheus sink
    path works against this with any standard scraper."""
    # group series by metric name: the exposition format requires all
    # samples of one metric to be contiguous under one TYPE header
    series: "OrderedDict[str, Tuple[str, List[str]]]" = OrderedDict()

    def add(name: str, prom_type: str, labels: Dict[str, Any],
            value: Any, extra_label: Optional[Tuple[str, str]] = None
            ) -> None:
        mname = prefix + _prom_name(name)
        pairs = [(_prom_name(k), _prom_label_value(v))
                 for k, v in labels.items()]
        if extra_label is not None:
            pairs.append(extra_label)
        lbl = ",".join(f'{k}="{v}"' for k, v in pairs)
        line = f"{mname}{{{lbl}}} {value}" if lbl else f"{mname} {value}"
        ent = series.get(mname)
        if ent is None:
            series[mname] = (prom_type, [line])
        else:
            ent[1].append(line)

    for ent_snap in snapshot:
        labels = {"entity": ent_snap["type"], "id": ent_snap["id"]}
        labels.update(ent_snap.get("attributes") or {})
        for name, m in (ent_snap.get("metrics") or {}).items():
            t = m.get("type")
            if t in ("counter", "volatile_counter"):
                add(name, "counter", labels, m["value"])
            elif t == "gauge":
                add(name, "gauge", labels, m["value"])
            elif t == "percentile":
                for k, v in m.items():
                    if k == "type" or not k.startswith("p"):
                        continue
                    q = float(k[1:]) / 100.0
                    add(name, "summary", labels, v,
                        ("quantile", f"{q:g}"))
    lines: List[str] = []
    for mname, (prom_type, samples) in series.items():
        lines.append(f"# TYPE {mname} {prom_type}")
        lines.extend(samples)
    return "\n".join(lines) + ("\n" if lines else "")


class LatencyTimer:
    """Context manager feeding a Percentile with elapsed ns.

    Parity: METRIC_VAR_AUTO_LATENCY in hot paths
    (src/server/pegasus_server_impl.cpp:422).
    """

    def __init__(self, percentile: Percentile) -> None:
        self._p = percentile

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        self._p.set(time.perf_counter_ns() - self._t0)
        return False
