"""Fail points: named code-site fault-injection hooks.

Parity: src/utils/fail_point.h:47,87 — FAIL_POINT_INJECT_F sites that tests
configure to return a value, raise, or delay; off by default with zero
overhead on the hot path. Used pervasively in the reference's replica and
server code (e.g. src/replica/replication_app_base.cpp:289).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

_SENTINEL = object()


class _FailPointRegistry:
    def __init__(self) -> None:
        self._actions: Dict[str, Callable[[str], Any]] = {}
        self._enabled = False
        self._lock = threading.Lock()

    def setup(self) -> None:
        self._enabled = True

    def teardown(self) -> None:
        with self._lock:
            self._actions.clear()
        self._enabled = False

    def cfg(self, name: str, action: str) -> None:
        """Configure an action string, mirroring the reference's mini-language:
        'off', 'return(<value>)', 'delay(<ms>)', 'raise(<msg>)',
        '<N>%return(<value>)' is not supported (keep deterministic for tests).
        """
        with self._lock:
            if action == "off":
                self._actions.pop(name, None)
                return
            if action.startswith("return(") and action.endswith(")"):
                value = action[len("return("):-1]
                self._actions[name] = lambda _n, v=value: v
            elif action.startswith("delay(") and action.endswith(")"):
                ms = float(action[len("delay("):-1])
                def _delay(_n, ms=ms):
                    time.sleep(ms / 1000.0)
                    return _SENTINEL
                self._actions[name] = _delay
            elif action.startswith("raise(") and action.endswith(")"):
                msg = action[len("raise("):-1]
                def _raise(_n, msg=msg):
                    raise RuntimeError(f"fail_point({_n}): {msg}")
                self._actions[name] = _raise
            else:
                raise ValueError(f"unknown fail_point action: {action!r}")

    def cfg_callable(self, name: str, fn: Callable[[str], Any]) -> None:
        with self._lock:
            self._actions[name] = fn

    def inject(self, name: str) -> Optional[Any]:
        """Returns None when the point is inactive; otherwise the configured
        return value (which callers interpret), or raises/delays."""
        if not self._enabled:
            return None
        fn = self._actions.get(name)
        if fn is None:
            return None
        result = fn(name)
        return None if result is _SENTINEL else result


FAIL_POINTS = _FailPointRegistry()


def fail_point(name: str) -> Optional[Any]:
    return FAIL_POINTS.inject(name)
