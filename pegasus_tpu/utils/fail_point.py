"""Fail points: named code-site fault-injection hooks.

Parity: src/utils/fail_point.h:47,87 — FAIL_POINT_INJECT_F sites that tests
configure to return a value, raise, or delay; off by default with zero
overhead on the hot path. Used pervasively in the reference's replica and
server code (e.g. src/replica/replication_app_base.cpp:289).
"""

from __future__ import annotations

import random
import re
import threading
import time
from typing import Any, Callable, Dict, Optional

_SENTINEL = object()

# '<N>%action(arg)' — the reference's probabilistic frequency prefix
# (fail_point.h parses "25%return(ok)"; N may be fractional)
_FREQ_RE = re.compile(r"^(\d+(?:\.\d+)?)%(.+)$")


class _FailPointRegistry:
    def __init__(self) -> None:
        self._actions: Dict[str, Callable[[str], Any]] = {}
        self._enabled = False
        self._lock = threading.Lock()
        # seedable RNG for the probabilistic '<N>%...' actions: chaos
        # runs replay from their seed (parity: the reference threads one
        # seeded env through the simulator's fault decisions)
        self._rng = random.Random(0)

    def setup(self) -> None:
        self._enabled = True

    @property
    def enabled(self) -> bool:
        return self._enabled

    def teardown(self) -> None:
        with self._lock:
            self._actions.clear()
        self._enabled = False
        self._rng = random.Random(0)

    def seed(self, seed: int) -> None:
        """Re-seed the probabilistic-action RNG (reproducible chaos)."""
        with self._lock:
            self._rng = random.Random(seed)

    def rand(self) -> float:
        """One draw from the seeded chaos stream (under the registry
        lock — concurrent consumers must not tear or de-determinize
        it). Fault actions that need PARAMETERS beyond fire/don't-fire
        — which bit a vfs bit-flip corrupts, how much of a torn write
        survives — draw here so a whole chaos run replays from
        FAIL_POINTS.seed alone."""
        with self._lock:
            return self._rng.random()

    def cfg(self, name: str, action: str) -> None:
        """Configure an action string, mirroring the reference's mini-language:
        'off', 'return(<value>)', 'delay(<ms>)', 'raise(<msg>)', each
        optionally prefixed '<N>%' to fire with probability N/100 per
        inject (fail_point.h's frequency syntax), e.g. '25%raise(io)'.
        """
        with self._lock:
            if action == "off":
                self._actions.pop(name, None)
                return
            prob = 1.0
            m = _FREQ_RE.match(action)
            if m:
                prob = float(m.group(1)) / 100.0
                action = m.group(2)
            if action.startswith("return(") and action.endswith(")"):
                value = action[len("return("):-1]
                base = lambda _n, v=value: v  # noqa: E731
            elif action.startswith("delay(") and action.endswith(")"):
                ms = float(action[len("delay("):-1])
                def base(_n, ms=ms):
                    time.sleep(ms / 1000.0)
                    return _SENTINEL
            elif action.startswith("raise(") and action.endswith(")"):
                msg = action[len("raise("):-1]
                def base(_n, msg=msg):
                    raise RuntimeError(f"fail_point({_n}): {msg}")
            else:
                raise ValueError(f"unknown fail_point action: {action!r}")
            if prob >= 1.0:
                self._actions[name] = base
            else:
                def probabilistic(n, base=base, prob=prob):
                    # RNG draw under the registry lock: concurrent
                    # injects from many dispatcher threads must not
                    # corrupt (or de-determinize) the shared stream
                    with self._lock:
                        hit = self._rng.random() < prob
                    return base(n) if hit else _SENTINEL
                self._actions[name] = probabilistic

    def cfg_callable(self, name: str, fn: Callable[[str], Any]) -> None:
        with self._lock:
            self._actions[name] = fn

    def configured(self, name: str) -> bool:
        """Whether an action is configured for `name` — lets layers
        that wrap whole objects per fault domain (storage/vfs.py) skip
        the wrap when THEIR sites are idle even while the registry is
        enabled for someone else's (the network FaultPlan's)."""
        return name in self._actions

    def inject(self, name: str) -> Optional[Any]:
        """Returns None when the point is inactive; otherwise the configured
        return value (which callers interpret), or raises/delays."""
        if not self._enabled:
            return None
        fn = self._actions.get(name)
        if fn is None:
            return None
        result = fn(name)
        return None if result is _SENTINEL else result


FAIL_POINTS = _FailPointRegistry()


def fail_point(name: str) -> Optional[Any]:
    return FAIL_POINTS.inject(name)
