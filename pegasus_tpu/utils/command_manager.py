"""Remote command registry: runtime control verbs.

Parity: src/utils/command_manager.h:52,137 — components register named
verbs with handlers; operators invoke them remotely (the reference rides
RPC_CLI_CLI_CALL, src/remote_cmd/remote_command.cpp:41-68; here the
verbs are reachable as a "remote_command" cluster message and through
the HTTP /command endpoint), and the shell's remote_command verb
(commands.h:111) fronts them.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List


class CommandManager:
    def __init__(self) -> None:
        self._verbs: Dict[str, Dict[str, Any]] = {}

    def register(self, verb: str,
                 handler: Callable[[List[str]], Any],
                 help_text: str = "") -> None:
        if verb in self._verbs:
            raise ValueError(f"command {verb!r} already registered")
        self._verbs[verb] = {"handler": handler, "help": help_text}

    def deregister(self, verb: str) -> None:
        self._verbs.pop(verb, None)

    def call(self, verb: str, args: List[str]) -> Any:
        if verb == "help":
            return {v: info["help"] for v, info in sorted(
                self._verbs.items())}
        info = self._verbs.get(verb)
        if info is None:
            raise KeyError(f"unknown command {verb!r} "
                           f"(try 'help')")
        return info["handler"](list(args))

    def verbs(self) -> List[str]:
        return sorted(self._verbs)
