"""Capped exponential retry backoff with seeded jitter.

Parity: the reference client's retry pacing (pegasus_client_impl
resolves-and-retries with the rDSN task delay growing per attempt;
partition_resolver.cpp:42 get_retry_interval caps the backoff) plus the
"full jitter" scheme — sleep a uniform fraction of the exponential
ceiling so a thundering herd of clients retrying into a failover
de-synchronizes instead of re-storming the meta in lockstep.

One `Backoff` instance belongs to one retry context (a client); the RNG
is seeded so a chaos schedule replays identically from its seed.
"""

from __future__ import annotations

import random
import time
from typing import Callable, List, Optional

from pegasus_tpu.utils.flags import FLAGS, define_flag

define_flag("pegasus.client", "retry_backoff_base_ms", 20,
            "first-retry backoff ceiling (doubles per attempt)",
            mutable=True)
define_flag("pegasus.client", "retry_backoff_max_ms", 1000,
            "cap on the per-attempt backoff ceiling", mutable=True)


class Backoff:
    """delay(attempt) in [ceiling/2, ceiling], ceiling = min(max, base·2^a).

    The lower bound keeps a measurable sleep on every retry (no
    zero-jitter busy spin) while the upper half of the window provides
    the de-synchronization. `sleep` is injectable: the sim cluster pumps
    virtual time instead of blocking the wall clock, and tests record
    the slept amounts to assert pacing without real waiting.
    """

    def __init__(self, base_ms: Optional[float] = None,
                 max_ms: Optional[float] = None,
                 seed: Optional[int] = None,
                 sleep: Callable[[float], None] = time.sleep) -> None:
        """`seed` None (the default) derives per-process entropy — N
        clients hitting the same failover must NOT draw identical
        jitter streams, or the herd stays in lockstep and the jitter
        buys nothing. Pass an explicit seed only for replayable
        schedules (the sim cluster, timing-bound tests)."""
        import os

        self._base_ms = base_ms
        self._max_ms = max_ms
        if seed is None:
            seed = (os.getpid() << 20) ^ time.time_ns()
        self._rng = random.Random(seed)
        self._sleep = sleep
        self.slept: List[float] = []  # measured backoff, for harnesses

    def delay(self, attempt: int) -> float:
        """Seconds to wait before retry number `attempt` (1-based)."""
        base = self._base_ms if self._base_ms is not None else \
            FLAGS.get("pegasus.client", "retry_backoff_base_ms")
        cap = self._max_ms if self._max_ms is not None else \
            FLAGS.get("pegasus.client", "retry_backoff_max_ms")
        # exponent clamped: long-lived retry contexts (the transport's
        # reconnect streak) pass unbounded attempt counts, and
        # 2.0**large raises OverflowError long after the cap would win
        ceiling = min(float(cap),
                      float(base) * (2.0 ** min(max(0, attempt - 1), 32)))
        return (ceiling * (0.5 + 0.5 * self._rng.random())) / 1000.0

    def sleep(self, attempt: int) -> float:
        d = self.delay(attempt)
        self._sleep(d)
        self.slept.append(d)
        return d

    def reset(self) -> None:
        self.slept.clear()
