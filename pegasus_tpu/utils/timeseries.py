"""Flight recorder: bounded per-metric time-series rings.

The metrics spine (utils/metrics.py) is point-in-time: by the time an
operator asks why a node degraded, the counters that would explain it
have been overwritten. The flight recorder closes that gap the way
RESYSTANCE (PAPERS.md) treats continuous low-overhead introspection of
the storage engine as a first-class feature: a fixed-cadence tick
drains the MetricRegistry into bounded per-series rings of
``(ts, value)`` points —

- counters (incl. relaxed) become RATES via a per-series cursor kept by
  this recorder alone;
- volatile counters are drained through their per-reader cursor
  (``delta_since``), so the recorder, the collector and `/metrics`
  scrapes never steal each other's deltas;
- gauges are sampled as-is;
- percentile windows are sampled at p50/p99 (two ``<name>.p50/.p99``
  series).

Retention is a sliding time window (drop-oldest) under a HARD byte cap:
the recorder can never become the memory incident it is documenting.
The health-rules engine (utils/health.py) evaluates over these rings,
and the ``timeseries-dump`` node verb / `shell timeline` render them.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from pegasus_tpu.utils.flags import FLAGS, define_flag
from pegasus_tpu.utils.metrics import (
    METRICS,
    Counter,
    Gauge,
    MetricEntity,
    Percentile,
    VolatileCounter,
)

define_flag("pegasus.health", "recorder_enabled", True,
            "master switch for the per-node flight recorder tick "
            "(rings + health rules); the bench's off-baseline",
            mutable=True)
define_flag("pegasus.health", "recorder_interval_s", 10.0,
            "minimum seconds between flight-recorder ticks (a caller "
            "timer firing faster is coalesced; sim schedules compress "
            "hours of virtual time, so the per-tick walk is paid "
            "often — keep the cadence coarse enough that recording "
            "stays invisible)", mutable=True)
define_flag("pegasus.health", "recorder_window_s", 600.0,
            "sliding retention window per series (drop-oldest)",
            mutable=True)
define_flag("pegasus.health", "recorder_byte_cap", 262144,
            "hard cap on one recorder's ring memory; overflow evicts "
            "oldest points first", mutable=True)

# accounting model for the byte cap: one (ts, value) tuple and its ring
# slot, plus a fixed per-series overhead (key, deque, cursor)
POINT_BYTES = 16
SERIES_OVERHEAD = 96

SeriesKey = Tuple[str, str, str]  # (entity_type, entity_id, metric)


class SeriesRing:
    """One metric's bounded (ts, value) history."""

    __slots__ = ("kind", "points")

    def __init__(self, kind: str) -> None:
        self.kind = kind  # "rate" (per-second) | "value"
        self.points: "deque[Tuple[float, float]]" = deque()

    def append(self, ts: float, value: float) -> None:
        self.points.append((ts, value))

    def trim(self, horizon: float) -> int:
        """Drop points older than `horizon`; returns how many."""
        n = 0
        pts = self.points
        while pts and pts[0][0] < horizon:
            pts.popleft()
            n += 1
        return n

    def slice(self, t0: Optional[float] = None,
              t1: Optional[float] = None) -> List[Tuple[float, float]]:
        return [(ts, v) for ts, v in self.points
                if (t0 is None or ts >= t0) and (t1 is None or ts <= t1)]

    def latest(self) -> Optional[Tuple[float, float]]:
        return self.points[-1] if self.points else None


class FlightRecorder:
    """One node's recorder over the (process-global) MetricRegistry.

    `owns(entity) -> bool` scopes recording: in a real deployment the
    process IS the node, but in-process sim clusters share one registry,
    so each stub passes a predicate selecting its own entities (plus
    the per-process singletons that are node-local when deployed).
    """

    def __init__(self, node: str, clock: Callable[[], float] = time.time,
                 registry=METRICS,
                 owns: Optional[Callable[[MetricEntity], bool]] = None
                 ) -> None:
        self.node = node
        self.clock = clock
        self.registry = registry
        self.owns = owns
        self.reader_id = f"recorder:{node}"
        self._series: Dict[SeriesKey, SeriesRing] = {}
        # counter cursors live here (not on the counter): the recorder
        # is one reader among many and must never perturb the others
        self._cursors: Dict[SeriesKey, float] = {}
        self._last_tick: Optional[float] = None
        self._total_points = 0
        self.evicted_points = 0

    # ---- recording -----------------------------------------------------

    def due(self) -> bool:
        """Whether a tick() now would actually record (side-effect
        free): callers hang their own per-cadence work — profiler
        publish, watchdog evaluation — off the same coalescing."""
        if not FLAGS.get("pegasus.health", "recorder_enabled"):
            return False
        return (self._last_tick is None
                or self.clock() - self._last_tick
                >= FLAGS.get("pegasus.health", "recorder_interval_s"))

    def tick(self, force: bool = False) -> Optional[int]:
        """One recording pass; returns points appended, or None when
        the call was coalesced/disabled (callers gate rule evaluation
        on an actual pass — an idle pass still appends zero-rates to
        live series, which is what lets alerts CLEAR). Calls faster
        than `recorder_interval_s` coalesce so timers can fire faster
        than the cadence and cluster step loops stay simple."""
        if not FLAGS.get("pegasus.health", "recorder_enabled"):
            return None
        now = self.clock()
        if (not force and self._last_tick is not None
                and now - self._last_tick
                < FLAGS.get("pegasus.health", "recorder_interval_s")):
            return None
        dt = now - self._last_tick if self._last_tick is not None else 0.0
        self._last_tick = now
        added = 0
        for ent in self.registry.entities():
            if self.owns is not None and not self.owns(ent):
                continue
            # snapshot the metric dict under the entity's lock
            with ent._lock:
                metrics = list(ent._metrics.items())
            for name, m in metrics:
                added += self._record_metric(ent, name, m, now, dt)
        self._trim(now)
        return added

    def _record_metric(self, ent: MetricEntity, name: str, m: Any,
                       now: float, dt: float) -> int:
        key = (ent.entity_type, ent.entity_id, name)
        if isinstance(m, VolatileCounter):
            delta = m.delta_since(self.reader_id)
            if dt <= 0.0:
                return 0
            return self._append(key, "rate", now, delta / dt)
        if isinstance(m, Counter):
            v = float(m.value())
            last = self._cursors.get(key)
            self._cursors[key] = v
            if last is None or dt <= 0.0:
                return 0  # first sight: cursor only, rates need a dt
            return self._append(key, "rate", now, (v - last) / dt)
        if isinstance(m, Gauge):
            return self._append(key, "value", now, float(m.value()))
        if isinstance(m, Percentile):
            if not m._samples:  # idle window: don't record zeros
                return 0
            p50, p99 = m.quantiles((50.0, 99.0))
            n = self._append((key[0], key[1], name + ".p50"), "value",
                             now, p50)
            n += self._append((key[0], key[1], name + ".p99"), "value",
                              now, p99)
            return n
        return 0

    def _append(self, key: SeriesKey, kind: str, now: float,
                value: float) -> int:
        ring = self._series.get(key)
        if ring is None:
            if value == 0.0:
                # a series is born at its first signal: thousands of
                # never-moving counters must not each pin a ring
                return 0
            ring = self._series[key] = SeriesRing(kind)
        pts = ring.points
        if (kind == "rate" and value == 0.0 and len(pts) >= 2
                and pts[-1][1] == 0.0 and pts[-2][1] == 0.0):
            # run-length-compress idle stretches: a counter that is not
            # moving slides the last zero forward instead of appending
            # one zero per tick — an hours-long sim lull stays O(1)
            # points. Hot (nonzero) samples are NEVER compressed: burn
            # windows need their real cardinality.
            pts[-1] = (now, 0.0)
            return 0
        ring.append(now, value)
        self._total_points += 1
        return 1

    def _trim(self, now: float) -> None:
        horizon = now - FLAGS.get("pegasus.health", "recorder_window_s")
        dead = []
        for key, ring in self._series.items():
            self._total_points -= ring.trim(horizon)
            if not ring.points:
                dead.append(key)
        for key in dead:
            del self._series[key]
        # hard byte cap: evict oldest points from the fattest series
        # first — retention degrades, memory never does
        cap = FLAGS.get("pegasus.health", "recorder_byte_cap")
        while self.nbytes() > cap and self._total_points > 0:
            ring = max(self._series.values(), key=lambda r: len(r.points))
            drop = max(1, len(ring.points) // 2)
            for _ in range(drop):
                ring.points.popleft()
            self._total_points -= drop
            self.evicted_points += drop

    # ---- read surfaces -------------------------------------------------

    def nbytes(self) -> int:
        """Ring-memory estimate (the cost the bench records and the cap
        enforces)."""
        return (len(self._series) * SERIES_OVERHEAD
                + self._total_points * POINT_BYTES)

    def series(self, entity_type: str, entity_id: str,
               metric: str) -> Optional[SeriesRing]:
        return self._series.get((entity_type, entity_id, metric))

    def match(self, entity_type: Optional[str] = None,
              entity_id: Optional[str] = None,
              metric: Optional[str] = None
              ) -> List[Tuple[SeriesKey, SeriesRing]]:
        out = []
        for key, ring in self._series.items():
            if entity_type is not None and key[0] != entity_type:
                continue
            if entity_id is not None and key[1] != entity_id:
                continue
            if metric is not None and key[2] != metric:
                continue
            out.append((key, ring))
        return out

    def dump(self, entity_type: Optional[str] = None,
             entity_id: Optional[str] = None,
             metric: Optional[str] = None,
             window_s: Optional[float] = None) -> List[dict]:
        """Ring slices as JSON-able rows (the `timeseries-dump` node
        verb and `shell timeline`'s fan-out target)."""
        t0 = None
        if window_s is not None:
            t0 = self.clock() - window_s
        out = []
        for (et, ei, name), ring in sorted(
                self.match(entity_type, entity_id, metric)):
            pts = ring.slice(t0)
            if not pts:
                continue
            out.append({"entity": et, "id": ei, "metric": name,
                        "kind": ring.kind,
                        "points": [[round(ts, 3), round(v, 4)]
                                   for ts, v in pts]})
        return out

    def stats(self) -> dict:
        return {"node": self.node, "series": len(self._series),
                "points": self._total_points, "bytes": self.nbytes(),
                "evicted_points": self.evicted_points}
