"""Token-bucket throttling controller.

Parity: src/utils/token_bucket_throttling_controller.h:32 and
src/utils/throttling_controller.* — per-table QPS/size throttles used by
replica read/write/backup throttling (src/replica/replica_throttle.cpp),
configured from app-envs like "2000*delay*100" or "100K".
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Tuple


class TokenBucket:
    """Classic token bucket: `rate` units/sec with `burst` capacity."""

    def __init__(self, rate: float, burst: Optional[float] = None,
                 clock=None) -> None:
        """`clock`: monotonic-seconds source (default wall
        time.monotonic). Sim-hosted buckets pass the virtual clock so
        refill tracks virtual seconds — a compressed sim schedule burns
        thousands of virtual seconds in milliseconds of wall, and a
        wall-clocked bucket would never refill under it."""
        self.rate = float(rate)
        self.burst = float(burst if burst is not None else rate)
        self._clock = clock if clock is not None else time.monotonic
        self._tokens = self.burst
        self._last = self._clock()
        self._lock = threading.Lock()

    def _refill(self, now: float) -> None:
        elapsed = max(0.0, now - self._last)
        self._last = now
        self._tokens = min(self.burst, self._tokens + elapsed * self.rate)

    def try_consume(self, tokens: float = 1.0) -> bool:
        with self._lock:
            now = self._clock()
            self._refill(now)
            if self._tokens >= tokens:
                self._tokens -= tokens
                return True
            return False

    def consume_or_delay(self, tokens: float = 1.0) -> float:
        """Consume unconditionally; return suggested delay (seconds) before
        serving, 0 if within budget. Mirrors the reference's delay-mode
        throttling (delay instead of reject)."""
        with self._lock:
            now = self._clock()
            self._refill(now)
            self._tokens -= tokens
            if self._tokens >= 0:
                return 0.0
            return -self._tokens / self.rate

    def debit(self, tokens: float) -> None:
        """Post-debit charge: subtract unconditionally, allowing the
        level to go negative. The CU-budget admission model charges the
        ACTUAL capacity units after serving (they are only known then)
        and gates the NEXT op on the sign of the level — an op that
        overshoots pushes the bucket into debt the refill must pay off
        before the tenant is admitted again."""
        with self._lock:
            self._refill(self._clock())
            self._tokens -= tokens

    def level(self) -> float:
        """Current token level after refill (may be negative under
        debit()); admission peeks this without consuming."""
        with self._lock:
            self._refill(self._clock())
            return self._tokens


def parse_throttle_env(value: str) -> Tuple[Optional[TokenBucket], Optional[TokenBucket]]:
    """Parse a throttle app-env of the reference's form
    "<qps>*delay*<ms>[,<qps>*reject*<ms>]" or a bare size like "100K"/"2M".

    Returns (delay_bucket, reject_bucket). Parity:
    src/utils/throttling_controller.cpp parse_from_env.
    """
    delay_b: Optional[TokenBucket] = None
    reject_b: Optional[TokenBucket] = None
    value = value.strip()
    if not value:
        return None, None
    for part in value.split(","):
        part = part.strip()
        if "*" in part:
            fields = part.split("*")
            qps = _parse_units(fields[0])
            kind = fields[1] if len(fields) > 1 else "delay"
            bucket = TokenBucket(qps)
            if kind == "reject":
                reject_b = bucket
            else:
                delay_b = bucket
        else:
            delay_b = TokenBucket(_parse_units(part))
    return delay_b, reject_b


def _parse_units(s: str) -> float:
    s = s.strip().upper()
    mult = 1.0
    if s.endswith("K"):
        mult, s = 1e3, s[:-1]
    elif s.endswith("M"):
        mult, s = 1e6, s[:-1]
    elif s.endswith("G"):
        mult, s = 1e9, s[:-1]
    return float(s) * mult
