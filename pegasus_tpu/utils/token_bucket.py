"""Token-bucket throttling controller.

Parity: src/utils/token_bucket_throttling_controller.h:32 and
src/utils/throttling_controller.* — per-table QPS/size throttles used by
replica read/write/backup throttling (src/replica/replica_throttle.cpp),
configured from app-envs like "2000*delay*100" or "100K".
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Tuple


class TokenBucket:
    """Classic token bucket: `rate` units/sec with `burst` capacity."""

    def __init__(self, rate: float, burst: Optional[float] = None) -> None:
        self.rate = float(rate)
        self.burst = float(burst if burst is not None else rate)
        self._tokens = self.burst
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def _refill(self, now: float) -> None:
        elapsed = now - self._last
        self._last = now
        self._tokens = min(self.burst, self._tokens + elapsed * self.rate)

    def try_consume(self, tokens: float = 1.0) -> bool:
        with self._lock:
            now = time.monotonic()
            self._refill(now)
            if self._tokens >= tokens:
                self._tokens -= tokens
                return True
            return False

    def consume_or_delay(self, tokens: float = 1.0) -> float:
        """Consume unconditionally; return suggested delay (seconds) before
        serving, 0 if within budget. Mirrors the reference's delay-mode
        throttling (delay instead of reject)."""
        with self._lock:
            now = time.monotonic()
            self._refill(now)
            self._tokens -= tokens
            if self._tokens >= 0:
                return 0.0
            return -self._tokens / self.rate


def parse_throttle_env(value: str) -> Tuple[Optional[TokenBucket], Optional[TokenBucket]]:
    """Parse a throttle app-env of the reference's form
    "<qps>*delay*<ms>[,<qps>*reject*<ms>]" or a bare size like "100K"/"2M".

    Returns (delay_bucket, reject_bucket). Parity:
    src/utils/throttling_controller.cpp parse_from_env.
    """
    delay_b: Optional[TokenBucket] = None
    reject_b: Optional[TokenBucket] = None
    value = value.strip()
    if not value:
        return None, None
    for part in value.split(","):
        part = part.strip()
        if "*" in part:
            fields = part.split("*")
            qps = _parse_units(fields[0])
            kind = fields[1] if len(fields) > 1 else "delay"
            bucket = TokenBucket(qps)
            if kind == "reject":
                reject_b = bucket
            else:
                delay_b = bucket
        else:
            delay_b = TokenBucket(_parse_units(part))
    return delay_b, reject_b


def _parse_units(s: str) -> float:
    s = s.strip().upper()
    mult = 1.0
    if s.endswith("K"):
        mult, s = 1e3, s[:-1]
    elif s.endswith("M"):
        mult, s = 1e6, s[:-1]
    elif s.endswith("G"):
        mult, s = 1e9, s[:-1]
    return float(s) * mult
