"""CPU isolation from the TPU-tunnel PJRT plugin.

This image's sitecustomize installs the `axon` tunnel plugin, whose
backend factory dials the SINGLE-TENANT TPU pool even when
JAX_PLATFORMS=cpu — a wedged tunnel then hangs the dialing process for
minutes (observed >600 s). Every code path that must stay CPU-only
(tests, the driver's virtual multichip dryrun, bench's CPU fallback,
server processes) needs the same three steps BEFORE first backend
init: force the env/config to cpu, pop the axon backend factory (and
ONLY axon — popping "tpu" would break importing pallas' TPU
lowerings), and optionally prove the isolation held.

Shared here so a jax private-API move breaks ONE site loudly instead
of leaving a forgotten copy silently re-dialing the tunnel. Callers
that must run before this package can import (tests/conftest.py, the
exec'd prologue in bench.py) keep self-contained copies by necessity —
they cite this module.
"""

from __future__ import annotations


def force_cpu(verify: bool = False) -> None:
    """Pin jax to the cpu backend and de-register the axon tunnel
    plugin. Call before the first jax backend initialization.

    verify=True proves the isolation actually held by initializing the
    backend and checking every visible device is cpu — this FAILS
    LOUDLY if the private factory registry moved, instead of silently
    dialing the tunnel on first dispatch. (It also freezes the backend
    config, so set XLA_FLAGS device-count overrides first.)
    """
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax
    import jax._src.xla_bridge as _xb

    jax.config.update("jax_platforms", "cpu")
    _xb._backend_factories.pop("axon", None)
    if verify:
        devs = {d.platform for d in jax.devices()}
        if devs != {"cpu"}:
            raise RuntimeError(
                f"CPU isolation failed — visible platforms {devs}; "
                "the axon plugin registry has likely moved")
