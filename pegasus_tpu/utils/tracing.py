"""Cluster-wide distributed tracing: trace context on every RPC,
tail-kept slow traces, cross-node stitching.

Parity/inspiration: the reference treats observability as a first-class
layer — every mutation carries an rDSN latency tracer whose stage chain
dumps when slow (src/utils/latency_tracer.h:94, replica_2pc.cpp:338-359).
This module extends that *per-process* stage chain into a *cross-process*
span tree:

- every sampled client op mints a ``(trace_id, span_id, flags)`` context
  that rides the RPC payload dict (key ``"trace"``) through BOTH
  transports (rpc/transport.py TCP and runtime/sim.py delivery);
- server-side, the transport dispatch opens a span per inbound request
  parented to the carried context; finer join points (per-op spans at
  the batching seams, 2PC per-peer prepare hops) parent to it; the
  already-present ``LatencyTracer`` stage points feed the bound span as
  annotations — one instrumentation layer, not two;
- spans land in a per-node bounded ring (drop-oldest). Sampling is
  head-based (``[pegasus.tracing] sample_ratio``, default 0 — zero spans,
  zero allocation) plus TAIL KEEP: a request that crosses
  ``slow_trace_ms`` pins its local spans out of the ring's churn and the
  keep decision rides the reply context upstream so every upstream hop
  pins too — slow traces are always whole;
- ``stitch()`` assembles dumps from many nodes into one rooted tree and
  aligns clocks per hop from the parent/child span endpoints (the
  send/recv pair observable at the transport), reporting a skew bound.

The span stack is thread-local: on the TCP transport the single
dispatcher thread owns it; in the sim everything nests on one thread and
push/pop order preserves correctness through recursive delivery.
"""

from __future__ import annotations

import itertools
import random
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Tuple

from pegasus_tpu.utils.flags import FLAGS, define_flag

define_flag("pegasus.tracing", "sample_ratio", 0.0,
            "head-based sampling probability for new client ops "
            "(0 disables tracing entirely: no spans, no allocation)",
            mutable=True)
define_flag("pegasus.tracing", "slow_trace_ms", 20.0,
            "a sampled request slower than this is tail-kept: its spans "
            "pin out of the ring and the keep decision propagates "
            "upstream on the reply so slow traces are always whole",
            mutable=True)
define_flag("pegasus.tracing", "ring_capacity", 2048,
            "per-node span ring size (drop-oldest)", mutable=True)
define_flag("pegasus.tracing", "kept_traces", 64,
            "tail-kept slow traces retained per node (drop-oldest)",
            mutable=True)

# context flag bits
SAMPLED = 1
KEEP = 2

# spans per kept trace (a runaway trace must not pin unbounded memory)
KEPT_SPAN_CAP = 1024

# message types that are replies/acks: their carried context pins
# tail-keep but never opens a dispatch span (a reply is the END of a
# hop, not a new one)
_REPLY_SUFFIXES = ("_reply", "_ack")


def is_reply_type(name: str) -> bool:
    return name.endswith(_REPLY_SUFFIXES)


# ---- ids -----------------------------------------------------------------

_lock = threading.Lock()
_rng = random.Random()
_prefix = _rng.getrandbits(32)
_trace_ids = itertools.count(1)
_span_ids = itertools.count(1)
_hard_off = False  # bench baseline switch: bypass even the flag read


def seed(n: int) -> None:
    """Deterministic ids + sampling draws (tests / sim replays)."""
    global _rng, _prefix, _trace_ids, _span_ids
    with _lock:
        _rng = random.Random(n)
        _prefix = _rng.getrandbits(32)
        _trace_ids = itertools.count(1)
        _span_ids = itertools.count(1)


def hard_disable(off: bool) -> None:
    """Kill switch for the bench's no-tracing baseline: skips even the
    sample_ratio flag read on the client hot path."""
    global _hard_off
    _hard_off = off


def _new_trace_id() -> str:
    return f"{_prefix:08x}{next(_trace_ids):08x}"


def _new_span_id() -> int:
    return (_prefix << 24) | (next(_span_ids) & 0xFFFFFF)


def maybe_sample() -> bool:
    """One head-based sampling draw (client op mint)."""
    if _hard_off:
        return False
    ratio = FLAGS.get("pegasus.tracing", "sample_ratio")
    if ratio <= 0.0:
        return False
    return ratio >= 1.0 or _rng.random() < ratio


# ---- spans ---------------------------------------------------------------


class Span:
    __slots__ = ("ring", "trace_id", "span_id", "parent_id", "name",
                 "node", "start", "end", "annotations", "tags")

    def __init__(self, ring: "SpanRing", trace_id: str, span_id: int,
                 parent_id: Optional[int], name: str) -> None:
        self.ring = ring
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.node = ring.node
        self.start = ring.clock()
        self.end: Optional[float] = None
        self.annotations: List[Tuple[str, float]] = []
        self.tags: Dict[str, Any] = {}

    def annotate(self, stage: str, at: Optional[float] = None) -> None:
        self.annotations.append(
            (stage, self.ring.clock() if at is None else at))

    def elapsed_ms(self) -> float:
        return (self.ring.clock() - self.start) * 1000.0

    def ctx(self) -> Tuple[str, int, int]:
        """The wire context. The KEEP bit is computed HERE, at send
        time: a reply stamped while the local request already crossed
        the slow threshold (or its trace was already pinned) carries the
        tail-keep decision upstream."""
        flags = SAMPLED
        if (self.ring.is_kept(self.trace_id)
                or self.elapsed_ms()
                >= FLAGS.get("pegasus.tracing", "slow_trace_ms")):
            flags |= KEEP
        return (self.trace_id, self.span_id, flags)

    def finish(self) -> None:
        if self.end is not None:
            return  # idempotent (error paths may double-finish)
        self.end = self.ring.clock()
        self.ring.record(self)

    def to_dict(self) -> Dict[str, Any]:
        return {"trace": self.trace_id, "span": self.span_id,
                "parent": self.parent_id, "name": self.name,
                "node": self.node, "start": self.start,
                "end": self.end if self.end is not None else self.start,
                "ann": list(self.annotations),
                "tags": dict(self.tags)}


class SpanRing:
    """One node's span store: a drop-oldest ring of finished spans plus
    the pinned (tail-kept) slow traces, which survive ring churn."""

    def __init__(self, node: str, clock=time.time) -> None:
        from pegasus_tpu.utils.metrics import METRICS

        self.node = node
        self.clock = clock
        self._ring: "deque[dict]" = deque()
        self._kept: "OrderedDict[str, List[dict]]" = OrderedDict()
        self._lock = threading.RLock()
        ent = METRICS.entity("tracing", node)
        self.kept_count = ent.counter("kept_trace_count")
        self.drop_count = ent.counter("span_drop_count")
        self.span_count = ent.counter("span_count")

    # -- recording --------------------------------------------------------

    def start(self, name: str, parent: Optional[Span] = None,
              parent_ctx: Optional[tuple] = None,
              trace_id: Optional[str] = None) -> Span:
        """A new span; the caller already decided it is sampled."""
        if parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        elif parent_ctx is not None:
            trace_id, parent_id = parent_ctx[0], parent_ctx[1]
        else:
            trace_id, parent_id = trace_id or _new_trace_id(), None
        return Span(self, trace_id, _new_span_id(), parent_id, name)

    def record(self, span: Span) -> None:
        d = span.to_dict()
        pin_after = False
        with self._lock:
            self.span_count.increment()
            if span.trace_id in self._kept:
                kept = self._kept[span.trace_id]
                if len(kept) < KEPT_SPAN_CAP:
                    kept.append(d)
            else:
                self._ring.append(d)
                cap = FLAGS.get("pegasus.tracing", "ring_capacity")
                while len(self._ring) > cap:
                    self._ring.popleft()
                    self.drop_count.increment()
                # local tail-keep: this span alone crossed the slow
                # threshold -> pin its whole trace
                if (d["end"] - d["start"]) * 1000.0 >= FLAGS.get(
                        "pegasus.tracing", "slow_trace_ms"):
                    pin_after = True
        if pin_after:
            self.pin(span.trace_id)

    def pin(self, trace_id: str) -> None:
        """Tail keep: pull this trace's spans out of the churn ring into
        the kept store; spans recorded later join them directly."""
        with self._lock:
            if trace_id in self._kept:
                return
            mine = [d for d in self._ring if d["trace"] == trace_id]
            if mine:
                self._ring = deque(d for d in self._ring
                                   if d["trace"] != trace_id)
            self._kept[trace_id] = mine[:KEPT_SPAN_CAP]
            self.kept_count.increment()
            cap = FLAGS.get("pegasus.tracing", "kept_traces")
            while len(self._kept) > cap:
                self._kept.popitem(last=False)

    def is_kept(self, trace_id: str) -> bool:
        return trace_id in self._kept

    # -- read surfaces ----------------------------------------------------

    def dump(self, trace_id: Optional[str] = None) -> List[dict]:
        with self._lock:
            out = []
            for spans in self._kept.values():
                out.extend(spans)
            out.extend(self._ring)
        if trace_id is not None:
            out = [d for d in out if d["trace"] == trace_id]
        return out

    def slow_roots(self, limit: int = 16) -> List[dict]:
        """Summaries of the tail-kept traces, newest last: the root (or
        earliest) span per trace — what `shell traces --slow` lists."""
        with self._lock:
            items = list(self._kept.items())[-limit:]
        out = []
        for tid, spans in items:
            if not spans:
                out.append({"trace": tid, "name": "?", "node": self.node,
                            "start": 0.0, "total_ms": 0.0})
                continue
            roots = [s for s in spans if s["parent"] is None]
            root = min(roots or spans, key=lambda s: s["start"])
            out.append({"trace": tid, "name": root["name"],
                        "node": root["node"], "start": root["start"],
                        "total_ms": round(
                            (root["end"] - root["start"]) * 1000.0, 3)})
        return out

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._kept.clear()


# ---- registry ------------------------------------------------------------

_rings: Dict[str, SpanRing] = {}
_rings_lock = threading.Lock()


def ring_for(node: str, clock=None) -> SpanRing:
    """The node's ring (created on first use). Passing `clock` (re)binds
    the ring's timebase — the sim cluster points every node at its
    virtual clock so span timelines live in sim time."""
    with _rings_lock:
        ring = _rings.get(node)
        if ring is None:
            ring = _rings[node] = SpanRing(node, clock or time.time)
        elif clock is not None:
            ring.clock = clock
        return ring


def dump_all(trace_id: Optional[str] = None) -> List[dict]:
    """Every local ring's spans (the shell process's own client ring
    joins the fan-out dumps this way)."""
    with _rings_lock:
        rings = list(_rings.values())
    out: List[dict] = []
    for r in rings:
        out.extend(r.dump(trace_id))
    return out


def slow_roots_all(limit: int = 16) -> List[dict]:
    with _rings_lock:
        rings = list(_rings.values())
    out: List[dict] = []
    for r in rings:
        out.extend(r.slow_roots(limit))
    return sorted(out, key=lambda d: d["start"])[-limit:]


def drop_ring(node: str) -> None:
    """Remove one node's ring (a closed sim cluster drops the rings it
    registered so its clock closures — and through them the whole dead
    cluster — are not pinned in the process-global registry)."""
    with _rings_lock:
        _rings.pop(node, None)


def reset() -> None:
    """Drop every ring (test isolation; sim clusters re-register)."""
    with _rings_lock:
        _rings.clear()


# ---- ambient span stack (server-side dispatch) ---------------------------

_tls = threading.local()


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def push(span: Span) -> None:
    _stack().append(span)


def pop(span: Span) -> None:
    st = _stack()
    if st and st[-1] is span:
        st.pop()
    elif span in st:  # defensive: unwind past a mispaired frame
        st.remove(span)


def current_span() -> Optional[Span]:
    st = getattr(_tls, "stack", None)
    return st[-1] if st else None


def current_ctx() -> Optional[tuple]:
    """The wire context of the ambient span (None when untraced) — what
    the transports stamp onto outbound payload dicts."""
    st = getattr(_tls, "stack", None)
    return st[-1].ctx() if st else None


def annotate(stage: str) -> None:
    """Annotate the ambient span; a single attr check when untraced."""
    st = getattr(_tls, "stack", None)
    if st:
        st[-1].annotate(stage)


class activate:
    """Context manager: make `span` ambient (no-op for None)."""

    __slots__ = ("_span",)

    def __init__(self, span: Optional[Span]) -> None:
        self._span = span

    def __enter__(self):
        if self._span is not None:
            push(self._span)
        return self._span

    def __exit__(self, *exc) -> None:
        if self._span is not None:
            pop(self._span)


def child_of(parent: Optional[Span], name: str) -> Optional[Span]:
    """A child span on the parent's ring (None-propagating)."""
    if parent is None:
        return None
    return parent.ring.start(name, parent=parent)


# ---- transport hooks -----------------------------------------------------


def on_inbound_ctx(node: str, ctx) -> None:
    """Process a carried context on ANY inbound message: a KEEP bit pins
    the trace locally (upstream hops of a slow request pin theirs when
    the decision rides back on the reply)."""
    if ctx and (ctx[2] & KEEP):
        ring_for(node).pin(ctx[0])


def start_server_span(node: str, name: str, ctx) -> Optional[Span]:
    """Dispatch join point: open a span for an inbound request carrying
    a sampled context (replies/acks only pin, never span)."""
    if not ctx or not (ctx[2] & SAMPLED):
        return None
    ring = ring_for(node)
    if ctx[2] & KEEP:
        ring.pin(ctx[0])
    return ring.start(name, parent_ctx=ctx)


# ---- stitching -----------------------------------------------------------


def stitch(spans: List[dict]) -> Optional[dict]:
    """Assemble span dumps (from any number of nodes) into ONE rooted
    tree with per-hop clock alignment.

    Each tree node is the span dict plus:
      - ``offset``: seconds added to this span's local clock to land it
        on the ROOT's timebase (cumulative down the tree);
      - ``skew_ms``: half-width of the per-hop offset interval — the
        alignment uncertainty from transport asymmetry;
      - ``rel_ms`` / ``dur_ms`` / ``self_ms``: aligned start relative to
        the root, duration, and self time (duration minus children);
      - ``children``: sorted by aligned start.

    Alignment derives from the send/recv pair the transports already
    observe: a child hop's span must START after its parent span started
    and END before the parent ended (request left after the parent span
    opened; reply arrived before it closed), so the child->parent clock
    offset lies in ``[p.start - c.start, p.end - c.end]``; the midpoint
    aligns, the half-width bounds the skew. Async children that outlive
    their parent clamp to start-alignment and report the overrun as
    skew.
    """
    if not spans:
        return None
    by_id: Dict[int, dict] = {}
    for s in spans:
        prev = by_id.get(s["span"])
        # dedupe (duplicated deliveries / overlapping dumps): keep the
        # longer record — it saw more of the span's life
        if prev is None or (s["end"] - s["start"]) > (
                prev["end"] - prev["start"]):
            by_id[s["span"]] = s
    nodes = {sid: dict(s, children=[]) for sid, s in by_id.items()}
    roots = []
    for sid, n in nodes.items():
        p = n.get("parent")
        if p is not None and p in nodes:
            nodes[p]["children"].append(n)
        else:
            roots.append(n)
    if len(roots) > 1:
        # orphans (ring-dropped parents): synthesize a root so the
        # result is still ONE tree
        t0 = min(r["start"] for r in roots)
        t1 = max(r["end"] for r in roots)
        root = {"trace": roots[0]["trace"], "span": 0, "parent": None,
                "name": "(stitched)", "node": "?", "start": t0,
                "end": t1, "ann": [], "tags": {},
                "children": sorted(roots, key=lambda r: r["start"])}
    else:
        root = roots[0]

    def local_extent(n: dict) -> Tuple[float, float]:
        """Interval covered by this span plus its SAME-NODE descendants
        (one shared clock, so no alignment needed): the true window of
        this hop's local work, even when an async child outlives the
        span that spawned it."""
        ext = n.get("_lex")
        if ext is None:
            s, e = n["start"], n["end"]
            for c in n["children"]:
                if c["node"] == n["node"]:
                    cs, ce = local_extent(c)
                    s, e = min(s, cs), max(e, ce)
            ext = n["_lex"] = (s, e)
        return ext

    def align(n: dict, offset: float) -> None:
        n["offset"] = offset
        n["skew_ms"] = n.get("skew_ms", 0.0)
        n["dur_ms"] = round((n["end"] - n["start"]) * 1000.0, 3)
        _ps, pe = local_extent(n)
        for c in n["children"]:
            if c["node"] == n["node"]:
                d, skew = 0.0, 0.0  # same clock: no per-hop estimation
            else:
                # the hop bound: the child's local work started after
                # the parent span opened (request sent) and ended
                # before the parent's local work closed (reply seen)
                cs, ce = local_extent(c)
                lo = n["start"] - cs
                hi = pe - ce
                if hi >= lo:
                    d, skew = (lo + hi) / 2.0, (hi - lo) / 2.0
                else:  # one-way hop (no reply observed): align starts
                    d, skew = lo, (lo - hi) / 2.0
            c["skew_ms"] = round(skew * 1000.0, 3)
            align(c, offset + d)
        n["children"].sort(key=lambda c: c["start"] + c["offset"])

    def extent(n: dict) -> Tuple[float, float]:
        """Aligned interval covered by this span's whole subtree (an
        async child may outlive its parent span)."""
        s = n["start"] + n["offset"]
        e = n["end"] + n["offset"]
        for c in n["children"]:
            cs, ce = extent(c)
            s, e = min(s, cs), max(e, ce)
        n["_ext"] = (s, e)
        return s, e

    def self_time(n: dict) -> None:
        """Self time = own interval minus the union of child SUBTREE
        intervals — parallel children overlap and async children spill
        past their own span, so a plain duration sum misattributes."""
        for c in n["children"]:
            self_time(c)
        extent_ = [c["_ext"] for c in n["children"]] if n["children"] \
            else []
        s0 = n["start"] + n["offset"]
        e0 = n["end"] + n["offset"]
        covered = 0.0
        last = s0
        for cs, ce in sorted(extent_):
            cs, ce = max(cs, last), min(ce, e0)
            if ce > cs:
                covered += ce - cs
                last = ce
        n["self_ms"] = round(max(0.0, (e0 - s0) - covered) * 1000.0, 3)

    align(root, 0.0)
    extent(root)
    self_time(root)
    for n in list(walk_dict(root)):
        n.pop("_ext", None)
        n.pop("_lex", None)
    t_root = root["start"]

    def rel(n: dict) -> None:
        n["rel_ms"] = round(
            (n["start"] + n["offset"] - t_root) * 1000.0, 3)
        for c in n["children"]:
            rel(c)

    rel(root)
    return root


def walk_dict(tree: dict):
    """Yield every node of a stitched tree (pre-order)."""
    yield tree
    for c in tree["children"]:
        yield from walk_dict(c)


walk = walk_dict


def render(tree: Optional[dict], width: int = 48) -> str:
    """Text timeline of a stitched tree: one line per span with an
    aligned bar, duration, self time, and per-hop skew bound."""
    if tree is None:
        return "(no spans)"
    total = max(tree["dur_ms"], 1e-9)
    lines = [f"trace {tree['trace']}  total {tree['dur_ms']:.3f} ms"]

    def emit(n: dict, depth: int) -> None:
        left = int(n["rel_ms"] / total * width)
        bar_w = max(1, int(n["dur_ms"] / total * width))
        bar = " " * min(left, width - 1) + "#" * min(bar_w,
                                                     width - left)
        skew = (f" ±{n['skew_ms']:.3f}ms" if n.get("skew_ms") else "")
        ann = ""
        if n["ann"]:
            stages = ",".join(a[0] for a in n["ann"][:8])
            ann = f"  [{stages}]"
        lines.append(
            f"{'  ' * depth}{n['name']} @{n['node']}  "
            f"{n['dur_ms']:.3f}ms (self {n['self_ms']:.3f}ms){skew}"
            f"{ann}")
        pc = (n.get("tags") or {}).get("perf")
        if pc:
            # the op's PerfContext rode the span: counts, not just
            # durations (only the fields that moved; an all-zero
            # vector — a gate-rejected flush — prints nothing)
            moved = " ".join(
                f"{k}={v}" for k, v in pc.items()
                if k not in ("op", "placement")
                and v not in (0, 0.0, None))
            place = (f" [{pc['placement']}]"
                     if pc.get("placement") else "")
            if moved or place:
                lines.append(f"{'  ' * depth}  perf{place}: {moved}")
        lines.append(f"{'  ' * depth}|{bar:<{width}}|")
        for c in n["children"]:
            emit(c, depth + 1)

    emit(tree, 0)
    return "\n".join(lines)
