"""Typed flag / configuration system.

Parity: the reference declares typed flags at point of use with
DSN_DEFINE_{int32,bool,string,...} (src/utils/flags.h:66-89), loads values
from ini config sections (src/utils/configuration.*), supports validators
and runtime mutation of FT_MUTABLE-tagged flags. We keep the same shape:
`define_flag(section, name, default, ...)` registers, `load_config` fills
from an ini file, `FLAGS.get/set` read and mutate.
"""

from __future__ import annotations

import configparser
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple


@dataclass
class _Flag:
    section: str
    name: str
    value: Any
    default: Any
    type: type
    description: str = ""
    mutable: bool = False
    validator: Optional[Callable[[Any], bool]] = None


class FlagRegistry:
    def __init__(self) -> None:
        self._flags: Dict[Tuple[str, str], _Flag] = {}
        self._lock = threading.Lock()

    def define(
        self,
        section: str,
        name: str,
        default: Any,
        description: str = "",
        mutable: bool = False,
        validator: Optional[Callable[[Any], bool]] = None,
    ) -> None:
        key = (section, name)
        with self._lock:
            if key in self._flags:
                return
            self._flags[key] = _Flag(
                section=section,
                name=name,
                value=default,
                default=default,
                type=type(default),
                description=description,
                mutable=mutable,
                validator=validator,
            )

    def get(self, section: str, name: str) -> Any:
        return self._flags[(section, name)].value

    def set(self, section: str, name: str, value: Any, force: bool = False) -> None:
        flag = self._flags[(section, name)]
        if not flag.mutable and not force:
            raise ValueError(f"flag [{section}]{name} is not mutable")
        value = _coerce(value, flag.type)
        if flag.validator is not None and not flag.validator(value):
            raise ValueError(f"invalid value for [{section}]{name}: {value!r}")
        flag.value = value

    def load_ini(self, path: str) -> None:
        parser = configparser.ConfigParser()
        parser.read(path)
        with self._lock:
            for (section, name), flag in self._flags.items():
                if parser.has_option(section, name):
                    raw = parser.get(section, name)
                    value = _coerce(raw, flag.type)
                    if flag.validator is not None and not flag.validator(value):
                        raise ValueError(
                            f"invalid config value for [{section}]{name}: {raw!r}"
                        )
                    flag.value = value

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        out: Dict[str, Dict[str, Any]] = {}
        for (section, name), flag in sorted(self._flags.items()):
            out.setdefault(section, {})[name] = flag.value
        return out


def _coerce(value: Any, typ: type) -> Any:
    if isinstance(value, typ):
        return value
    if typ is bool:
        if isinstance(value, str):
            return value.strip().lower() in ("1", "true", "yes", "on")
        return bool(value)
    return typ(value)


FLAGS = FlagRegistry()


def define_flag(section: str, name: str, default: Any, description: str = "",
                mutable: bool = False,
                validator: Optional[Callable[[Any], bool]] = None) -> None:
    FLAGS.define(section, name, default, description, mutable, validator)


def load_config(path: str) -> None:
    FLAGS.load_ini(path)
