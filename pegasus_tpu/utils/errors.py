"""Error codes.

The reference uses registered named error codes (dsn::error_code,
src/utils/error_code.h) plus rocksdb status codes surfaced through the rrdb
API as int32 `error` fields (src/server/pegasus_server_impl.cpp uses
rocksdb::Status::code()). We keep one enum for framework errors and a small
mapping for the storage-status integers the client-visible rrdb responses
carry (0 = OK, 1 = NotFound, ...).
"""

from __future__ import annotations

import enum


class ErrorCode(enum.IntEnum):
    """Framework-level error codes (parity: src/utils/error_code.h registry)."""

    ERR_OK = 0
    ERR_UNKNOWN = 1
    ERR_SERVICE_NOT_FOUND = 2
    ERR_SERVICE_ALREADY_RUNNING = 3
    ERR_INVALID_PARAMETERS = 4
    ERR_OBJECT_NOT_FOUND = 5
    ERR_TIMEOUT = 6
    ERR_BUSY = 7
    ERR_NETWORK_FAILURE = 8
    ERR_HANDLER_NOT_FOUND = 9
    ERR_OPERATION_DISABLED = 10
    ERR_NOT_ENOUGH_MEMBER = 11
    ERR_FILE_OPERATION_FAILED = 12
    ERR_INVALID_STATE = 13
    ERR_INACTIVE_STATE = 14
    ERR_NOT_IMPLEMENTED = 15
    ERR_CHECKPOINT_FAILED = 16
    ERR_WRONG_TIMING = 17
    ERR_NO_NEED_OPERATE = 18
    ERR_CORRUPTION = 19
    ERR_TRY_AGAIN = 20
    ERR_CLUSTER_NOT_FOUND = 21
    ERR_CLUSTER_ALREADY_EXIST = 22
    ERR_APP_NOT_EXIST = 23
    ERR_APP_EXIST = 24
    ERR_APP_DROPPED = 25
    ERR_BUSY_CREATING = 26
    ERR_BUSY_DROPPING = 27
    ERR_EXPIRED = 28
    ERR_LOCK_ALREADY_EXIST = 29
    ERR_HOLD_BY_OTHERS = 30
    ERR_RECURSIVE_LOCK = 31
    ERR_NO_OWNER = 32
    ERR_NODE_ALREADY_EXIST = 33
    ERR_INCONSISTENT_STATE = 34
    ERR_ARRAY_INDEX_OUT_OF_RANGE = 35
    ERR_DIR_NOT_EMPTY = 36
    ERR_PATH_NOT_FOUND = 37
    ERR_PATH_ALREADY_EXIST = 38
    ERR_ADDRESS_ALREADY_USED = 39
    ERR_STATE_FREEZED = 40
    ERR_LOCAL_APP_FAILURE = 41
    ERR_BIND_IOCP_FAILED = 42
    ERR_NETWORK_INIT_FAILED = 43
    ERR_FORWARD_TO_OTHERS = 44
    ERR_OBJECT_EXIST = 45
    ERR_NO_NEED_LEARN = 46
    ERR_LEARN_FILE_FAILED = 47
    ERR_GET_LEARN_STATE_FAILED = 48
    ERR_INVALID_VERSION = 49
    ERR_INGESTION_FAILED = 50
    ERR_CAPACITY_EXCEEDED = 51
    ERR_CHILD_REGISTERED = 52
    ERR_PARENT_PARTITION_MISUSED = 53
    ERR_CHILD_NOT_READY = 54
    ERR_DISK_INSUFFICIENT = 55
    ERR_SPLITTING = 56
    ERR_RDB_CORRUPTION = 57
    ERR_DISK_IO_ERROR = 58
    ERR_RANGER_POLICIES_NO_NEED_UPDATE = 59
    ERR_RANGER_PARSE_ACL = 60
    ERR_ACL_DENY = 61
    ERR_DUP_EXIST = 62
    ERR_CHECKSUM_FAILED = 63
    # duplication failover drill: the table is fenced while its dup
    # drains to the follower before the flip. RETRYABLE — the client's
    # backoff rides out the drain and lands on the flipped follower
    # (or surfaces the fence to the operator at its op deadline)
    ERR_DUP_FENCED = 64
    # follower-read bounce: a secondary declined a consistency-levelled
    # read because its beacon lease lapsed or its committed decree is
    # outside the op's staleness bound. RETRYABLE — the client re-sends
    # ONLY the bounced ops to the primary (the routing table is still
    # correct, so no config refresh is burned on the retry)
    ERR_STALE_REPLICA = 65
    # multi-tenant QoS: the op's tenant is over its capacity-unit
    # budget (server/tenancy.py token buckets fed by the CU
    # accounting) and no idle headroom is available to borrow.
    # RETRYABLE — the client's jittered backoff rides out the bucket
    # refill; like ERR_BUSY/ERR_STALE_REPLICA it burns NO config
    # refresh (the routing table is correct, the tenant is just hot)
    ERR_CU_OVERBUDGET = 66


class StorageStatus(enum.IntEnum):
    """Per-request storage status codes surfaced in rrdb responses.

    Parity: rocksdb::Status::Code as used by the reference's handlers
    (src/server/pegasus_server_impl.cpp:418 on_get returns Status::code()).
    """

    OK = 0
    NOT_FOUND = 1
    CORRUPTION = 2
    NOT_SUPPORTED = 3
    INVALID_ARGUMENT = 4
    IO_ERROR = 5
    INCOMPLETE = 7
    TRY_AGAIN = 13


def rocksdb_status(ok: bool) -> int:
    return int(StorageStatus.OK if ok else StorageStatus.NOT_FOUND)


class PegasusError(Exception):
    """Framework exception carrying an ErrorCode."""

    def __init__(self, code: ErrorCode, message: str = ""):
        self.code = code
        super().__init__(f"{code.name}: {message}" if message else code.name)


class StorageCorruptionError(RuntimeError):
    """On-disk bytes failed an integrity check (block crc32, index crc,
    bad magic): carries the file path so the node can map the failure to
    the owning replica and quarantine it. Subclasses RuntimeError so
    paths that have no corruption policy still degrade to their generic
    ERR_INVALID_STATE handling; the stub's client gates catch THIS type
    first and surface typed ERR_CHECKSUM_FAILED (parity: rocksdb
    Status::Corruption surfacing through the replica's disk-error
    handler, replica/replica_disk_monitor + pegasus_event_listener)."""

    def __init__(self, path: str, detail: str = ""):
        self.path = path
        self.detail = detail
        super().__init__(f"{path}: {detail}" if detail else path)
