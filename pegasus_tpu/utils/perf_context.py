"""PerfContext: a cheap per-op cost vector for the data plane.

The slow log (utils/latency_tracer.py) and the trace spans
(utils/tracing.py) answer WHERE time went; nothing answered WHY an op
cost what it cost — how many runs were considered, how many the
sidecars pruned, how many blocks were actually decoded versus served
from cache, how many rows each kernel mask evaluated versus kept, and
which device class the placement policy routed the kernels to. This is
the RocksDB PerfContext/IOStatsContext layer for this engine: one
mutable counter vector per op (or per batched flush — the batch IS the
op on the coalesced paths), threaded ambient through the serving
thread so the storage layer can tick it without plumbing an argument
through every call.

Design rules, in order:

- OFF must be nearly free. The hot-path hook is one thread-local
  attribute read + a truthiness check (the same discipline as
  tracing.annotate); `start()` returns None when the
  ``[pegasus.perfctx] enabled`` kill switch is off, so nothing is ever
  pushed and every hook sees None. The bench `perfctx_overhead` phase
  gates contexts-ENABLED within 2% of hard-off.
- ON must stay cheap: fields are plain ints on a __slots__ object
  (`pc.blocks_decoded += 1`), and batched paths accumulate locals in
  their loops and add once per flush, exactly like the metric
  counters they mirror.
- Field names are REGISTERED (perf_field below) with a metric kind so
  tools/metrics_lint.py lints them with the same sanitizer and
  kind-conflict rules as real metric registrations — a perf field
  named like an existing metric of a different kind, or a name the
  Prometheus sanitizer would rewrite, fails the tier-1 lint gate.

Contexts attach to slow-log entries (SlowQueryLog picks up the bound
or ambient context) and to trace spans (`span.tags["perf"]`), so
`shell trace <id>` and `shell explain --from-trace <id>` show counts,
not just durations.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from pegasus_tpu.utils.flags import FLAGS, define_flag

define_flag("pegasus.perfctx", "enabled", True,
            "collect per-op PerfContext cost vectors on the read/scan/"
            "write paths (kill switch; bench-gated <=2% overhead)",
            mutable=True)

# (name, kind) registrations — metrics_lint scans the perf_field(...)
# call sites statically, so every name below rides the same drift gate
# as the real metric registrations
FIELD_DEFS: List[Tuple[str, str]] = []


def perf_field(name: str, kind: str = "counter") -> str:
    """Register one PerfContext field (name must be a string literal at
    the call site — the linter reads the source, not this list)."""
    FIELD_DEFS.append((name, kind))
    return name


# -- the cost vector -------------------------------------------------------
# counters: how much work the op did
_COUNTER_FIELDS = (
    perf_field("ops", "counter"),               # requests in the flush
    perf_field("keys_resolved", "counter"),     # unique keys located
    perf_field("runs_considered", "counter"),   # L0 tables + L1 runs
    perf_field("bloom_pruned", "counter"),      # bloom said "absent"
    perf_field("phash_pruned", "counter"),      # phash said "absent"
    perf_field("phash_located", "counter"),     # phash gave (block,slot)
    perf_field("row_cache_hit", "counter"),
    perf_field("row_cache_miss", "counter"),
    perf_field("block_cache_hit", "counter"),
    perf_field("blocks_decoded", "counter"),    # cold block loads
    perf_field("blocks_planned", "counter"),    # blocks a scan planned
    perf_field("bytes_read", "counter"),        # on-disk bytes fetched
    perf_field("bytes_decoded", "counter"),     # materialized after codec
    perf_field("rows_evaluated", "counter"),    # rows under kernel masks
    perf_field("rows_survived", "counter"),     # rows after all masks
    perf_field("expired_rows", "counter"),      # TTL-dropped
    perf_field("overlay_hits", "counter"),      # memtable/L0 answers
    perf_field("bytes_returned", "counter"),    # key+value bytes out
    # scan pushdown (ops/pushdown.py): rows the server-side value
    # filter dropped before they could ship, and rows folded into a
    # server-side partial aggregate instead of being returned
    perf_field("pushdown_rows_pruned", "counter"),
    perf_field("rows_aggregated", "counter"),
    # resident mesh serving (parallel/mesh_resident.py): partitions whose
    # blocks this op's waves answered from the stacked SPMD program
    perf_field("mesh_partitions", "counter"),
)
# gauges: per-op measurements
_GAUGE_FIELDS = (
    # the group-commit flush-window wait (append_plog -> plog_durable;
    # fed on the WRITE apply path — read flushes report 0 here because
    # transports don't stamp per-message enqueue times today)
    perf_field("queue_wait_ms", "gauge"),
    perf_field("predicted_kernel_ms", "gauge"),  # placement cost model
    perf_field("measured_kernel_ms", "gauge"),
    perf_field("mesh_wave_ms", "gauge"),  # resident-mesh dispatch wall
)

FIELDS: Tuple[str, ...] = _COUNTER_FIELDS + _GAUGE_FIELDS


class PerfContext:
    """One op's (or one batched flush's) cost vector."""

    __slots__ = ("op", "placement", "served_by", "tenant") + FIELDS

    def __init__(self, op: str = "") -> None:
        self.op = op
        # device | host-XLA | native | numpy | mesh — which compute class
        # the placement policy routed this op's kernels to ("" = no
        # kernel; "mesh" = the resident whole-table SPMD program)
        self.placement = ""
        # primary | secondary — which replica role answered this read
        # ("" = not a consistency-routed read, e.g. a write flush)
        self.served_by = ""
        # the QoS tenant this op was billed to ("" = untenanted
        # background work) — slow-log entries and spans carry it, so
        # `shell explain`/`shell timeline` answer "which tenant"
        self.tenant = ""
        for f in _COUNTER_FIELDS:
            setattr(self, f, 0)
        for f in _GAUGE_FIELDS:
            setattr(self, f, 0.0)

    def to_dict(self) -> Dict[str, Any]:
        """The FULL fixed vector (zeros included): solo and batched
        slow-log entries stay field-set-comparable by construction, and
        a field added here reaches every surface at once."""
        d: Dict[str, Any] = {"op": self.op, "placement": self.placement,
                             "served_by": self.served_by,
                             "tenant": self.tenant}
        for f in _COUNTER_FIELDS:
            d[f] = getattr(self, f)
        for f in _GAUGE_FIELDS:
            d[f] = round(getattr(self, f), 3)
        return d

    def nonzero(self) -> Dict[str, Any]:
        """Compact view (rendering): only the fields that moved."""
        return {k: v for k, v in self.to_dict().items()
                if v not in (0, 0.0, "", None)}


# -- ambient threading -----------------------------------------------------

_tls = threading.local()


def enabled() -> bool:
    return bool(FLAGS.get("pegasus.perfctx", "enabled"))


def start(op: str) -> Optional[PerfContext]:
    """A fresh context when collection is on, else None. The caller
    activates it (or stores it in its batch state) explicitly."""
    return PerfContext(op) if enabled() else None


def current() -> Optional[PerfContext]:
    """The ambient context (None when none active / collection off).
    The hot-path hook: one thread-local attr read + a list check."""
    st = getattr(_tls, "stack", None)
    return st[-1] if st else None


def push(pc: PerfContext) -> None:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    st.append(pc)


def pop(pc: PerfContext) -> None:
    st = getattr(_tls, "stack", None)
    if st and st[-1] is pc:
        st.pop()
    elif st and pc in st:  # defensive: unwind past a mispaired frame
        st.remove(pc)


def merge_span_perf(tags: Dict[str, Any], pc: "PerfContext") -> None:
    """Fold `pc` into a span's perf tag. A batched carrier RPC serves
    MANY partitions under ONE dispatch span — each partition's flush
    context must ACCUMULATE (counters sum, timings add), not
    overwrite, or the trace keeps only the last partition's costs."""
    d = pc.to_dict()
    prev = tags.get("perf")
    if prev is None:
        tags["perf"] = d
        return
    for f in _COUNTER_FIELDS:
        prev[f] += d[f]
    for f in _GAUGE_FIELDS:
        prev[f] = round(prev[f] + d[f], 3)
    if not prev.get("placement"):
        prev["placement"] = d["placement"]
    elif d["placement"] and d["placement"] != prev["placement"]:
        prev["placement"] = "mixed"
    # same accumulate-don't-overwrite rule for which replica answered:
    # a carrier mixing primary- and secondary-served slots says so
    if not prev.get("served_by"):
        prev["served_by"] = d["served_by"]
    elif d["served_by"] and d["served_by"] != prev["served_by"]:
        prev["served_by"] = "mixed"
    # and for the billed tenant — a transport flush coalescing several
    # tenants' reads reports "mixed", never silently the last one
    if not prev.get("tenant"):
        prev["tenant"] = d["tenant"]
    elif d["tenant"] and d["tenant"] != prev["tenant"]:
        prev["tenant"] = "mixed"


class activate:
    """Context manager: make `pc` ambient (no-op for None)."""

    __slots__ = ("_pc",)

    def __init__(self, pc: Optional[PerfContext]) -> None:
        self._pc = pc

    def __enter__(self) -> Optional[PerfContext]:
        if self._pc is not None:
            push(self._pc)
        return self._pc

    def __exit__(self, *exc) -> None:
        if self._pc is not None:
            pop(self._pc)
