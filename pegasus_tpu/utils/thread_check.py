"""Replica access-discipline checkers (race detection, SURVEY §5.2).

Parity: utils/thread_access_checker.h — the reference asserts each
replica is only ever touched from its pinned worker thread
(replica_2pc.cpp:115). Our runtime serializes replica access under the
node lock (TCP dispatcher + timer threads) or a single sim thread, so
the translated invariant is NO CONCURRENT ENTRY: two threads inside a
replica's mutating sections at once means a missing lock, and the
checker turns that silent race into a loud failure at the exact site.

Overhead is two attribute writes and an integer compare per guarded
section — cheap enough to stay on in production, like the reference's
checker in debug builds but without needing a special build.
"""

from __future__ import annotations

import threading


class SerialAccessChecker:
    """Asserts mutating sections never run concurrently.

    Usage:
        self._access = SerialAccessChecker("replica 1.3")
        ...
        with self._access:
            <mutating section>

    Re-entrant from the owning thread (a guarded method may call another
    guarded method); any second THREAD entering while one is inside
    raises RuntimeError naming both threads.
    """

    __slots__ = ("name", "_owner", "_depth")

    def __init__(self, name: str) -> None:
        self.name = name
        self._owner: int | None = None
        self._depth = 0

    def __enter__(self) -> "SerialAccessChecker":
        me = threading.get_ident()
        owner = self._owner
        if owner is not None and owner != me:
            raise RuntimeError(
                f"concurrent access to {self.name}: thread {me} entered "
                f"while thread {owner} is inside — a lock is missing "
                f"(single-writer discipline, replica_2pc.cpp:115)")
        self._owner = me
        self._depth += 1
        return self

    def __exit__(self, *exc) -> None:
        self._depth -= 1
        if self._depth == 0:
            self._owner = None


class ThreadAccessChecker:
    """Strict pinned-thread form (parity: thread_access_checker.h
    verbatim): every check() must come from the SAME thread for the
    object's lifetime. For objects genuinely owned by one thread (sim
    loop internals, per-connection parser state)."""

    __slots__ = ("name", "_ident")

    def __init__(self, name: str) -> None:
        self.name = name
        self._ident: int | None = None

    def check(self) -> None:
        me = threading.get_ident()
        if self._ident is None:
            self._ident = me
        elif self._ident != me:
            raise RuntimeError(
                f"{self.name} accessed from thread {me} but owned by "
                f"thread {self._ident}")
