"""Partition-sharded predicate evaluation over a jax Mesh.

The reference's parallelism axes (SURVEY §2.6) map to the device mesh as:

- hash partitioning ("dp"): a table's partitions are the natural shard
  dimension — partition p's record blocks live on device p % dp. The
  reference fans scans out across partitions via unordered scanners
  (src/include/pegasus/client.h:1164); here the fan-out IS the mesh axis.
- request batching ("sp"): within one partition's block, the record-batch
  dimension shards across the second mesh axis — the "long dimension"
  (SURVEY §5.7: record-batch length plays the role sequence length plays
  in ML workloads; predicates are elementwise over records, so batch
  sharding needs no halo exchange; only the final count reduction crosses
  devices via psum over both axes).

The stacked layout is [P, B, K] uint8 keys + [P, B] columns, sharded
PartitionSpec("dp", "sp", None). One jitted program evaluates scan
predicates for every partition at once and psum-reduces global match
counts over ICI — replacing the reference's per-partition scalar loops
with a single SPMD program.
"""

from __future__ import annotations

import functools
import warnings
from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pegasus_tpu.ops.predicates import FilterSpec
from pegasus_tpu.ops.record_block import RecordBlock


class PartitionMesh(NamedTuple):
    mesh: Mesh
    dp: int  # partition-parallel axis size
    sp: int  # record-batch-parallel axis size


def make_mesh(n_devices: Optional[int] = None, dp: Optional[int] = None,
              devices: Optional[Sequence] = None) -> PartitionMesh:
    """2D mesh (dp, sp) over the available devices; dp defaults to all.

    Pass `devices` to build over an explicit device set (e.g. the
    host-platform CPU devices the tunnel watchdog falls back to). On a
    single-device host any requested dp degrades to a (1, 1) mesh with
    a warning instead of raising — solo-dev boxes must never crash the
    import path just because dp defaulted to a multi-device shape.
    """
    if devices is None:
        devices = jax.devices()[:n_devices] if n_devices else jax.devices()
    devices = list(devices)
    n = len(devices)
    if dp is None:
        dp = n
    if n == 1 and dp != 1:
        warnings.warn(f"single-device host: degrading mesh dp={dp} to a "
                      f"(1, 1) mesh", RuntimeWarning, stacklevel=2)
        dp = 1
    if n % dp:
        raise ValueError(f"{n} devices not divisible by dp={dp}")
    sp = n // dp
    arr = np.asarray(devices).reshape(dp, sp)
    return PartitionMesh(Mesh(arr, axis_names=("dp", "sp")), dp, sp)


class StackedBlocks(NamedTuple):
    """P partitions × B records, padded columnar — a pytree of arrays."""

    keys: jax.Array         # uint8[P, B, K]
    key_len: jax.Array      # int32[P, B]
    hashkey_len: jax.Array  # int32[P, B]
    expire_ts: jax.Array    # uint32[P, B]
    valid: jax.Array        # bool[P, B]
    pidx: jax.Array         # uint32[P] partition index per row


def stack_blocks(blocks: Sequence[RecordBlock],
                 pidx: Optional[Sequence[int]] = None) -> StackedBlocks:
    """Stack per-partition RecordBlocks (equal capacity/width) to [P, ...]."""
    if not blocks:
        raise ValueError("no blocks")
    caps = {(b.capacity, b.key_width) for b in blocks}
    if len(caps) > 1:
        raise ValueError(f"blocks must share shape, got {caps}")
    if pidx is None:
        pidx = list(range(len(blocks)))
    return StackedBlocks(
        keys=jnp.asarray(np.stack([np.asarray(b.keys) for b in blocks])),
        key_len=jnp.asarray(np.stack([np.asarray(b.key_len) for b in blocks])),
        hashkey_len=jnp.asarray(
            np.stack([np.asarray(b.hashkey_len) for b in blocks])),
        expire_ts=jnp.asarray(
            np.stack([np.asarray(b.expire_ts) for b in blocks])),
        valid=jnp.asarray(np.stack([np.asarray(b.valid) for b in blocks])),
        pidx=jnp.asarray(np.asarray(pidx, dtype=np.uint32)),
    )


def _scan_step(stacked: StackedBlocks, now, sort_pattern, sort_pattern_len,
               partition_version, partition_allowed,
               sort_filter_type: int, validate_hash: bool):
    """The sharded 'step': per-record keep masks + global aggregates.

    Reuses the SAME predicate program as the single-device path
    (_scan_block_predicate) by flattening [P, B] -> [P*B] and passing a
    per-record pidx vector, so the two paths cannot drift. Elementwise
    over records; the only cross-device communication is the final global
    reductions, which jit lowers to psums over the mesh.

    `partition_allowed` is bool[P]: False for partitions whose ownership
    check must reject everything (partition_version < 0 or
    pidx > partition_version — parity with scan_block_predicate's
    invalid-state gate).
    """
    from pegasus_tpu.ops.predicates import _scan_block_predicate

    p, b, k = stacked.keys.shape
    pidx_rows = jnp.repeat(stacked.pidx, b)
    no_pattern = jnp.zeros_like(sort_pattern)
    masks = _scan_block_predicate(
        stacked.keys.reshape(p * b, k),
        stacked.key_len.reshape(p * b),
        stacked.hashkey_len.reshape(p * b),
        stacked.expire_ts.reshape(p * b),
        stacked.valid.reshape(p * b),
        now, no_pattern, jnp.int32(0), sort_pattern, sort_pattern_len,
        pidx_rows, partition_version,
        hash_filter_type=0, sort_filter_type=sort_filter_type,
        validate_hash=validate_hash)
    expired = masks.expired.reshape(p, b)
    keep = masks.keep.reshape(p, b) & partition_allowed[:, None]

    total_kept = keep.sum()
    total_expired = expired.sum()
    per_partition_kept = keep.sum(axis=1)
    return keep, total_kept, total_expired, per_partition_kept


def sharded_scan_step(pmesh: PartitionMesh, stacked: StackedBlocks, now: int,
                      sort_filter: Optional[FilterSpec] = None,
                      partition_version: int = -1,
                      validate_hash: bool = False):
    """Place the stacked blocks on the mesh and run one sharded scan step.

    Returns (keep[P, B] sharded, total_kept, total_expired, per_partition
    kept counts). Shardings: data P("dp", "sp"), reductions replicated.
    """
    sort_filter = sort_filter or FilterSpec.none()
    mesh = pmesh.mesh
    data_sharding = NamedSharding(mesh, P("dp", "sp"))
    key_sharding = NamedSharding(mesh, P("dp", "sp", None))
    pid_sharding = NamedSharding(mesh, P("dp"))

    placed = StackedBlocks(
        keys=jax.device_put(stacked.keys, key_sharding),
        key_len=jax.device_put(stacked.key_len, data_sharding),
        hashkey_len=jax.device_put(stacked.hashkey_len, data_sharding),
        expire_ts=jax.device_put(stacked.expire_ts, data_sharding),
        valid=jax.device_put(stacked.valid, data_sharding),
        pidx=jax.device_put(stacked.pidx, pid_sharding),
    )

    # invalid-ownership-state gate, host-side (parity with
    # scan_block_predicate: pv < 0 or pidx > pv rejects the partition)
    pidx_np = np.asarray(stacked.pidx)
    if validate_hash and partition_version < 0:
        allowed = np.zeros(len(pidx_np), dtype=bool)
    elif validate_hash:
        allowed = pidx_np <= partition_version
    else:
        allowed = np.ones(len(pidx_np), dtype=bool)
    allowed = jax.device_put(jnp.asarray(allowed), pid_sharding)

    step = _jitted_scan_step(mesh, sort_filter.filter_type, validate_hash)
    return step(placed, jnp.uint32(now), sort_filter.pattern,
                sort_filter.pattern_len,
                jnp.uint32(max(partition_version, 0) & 0xFFFFFFFF), allowed)


@functools.lru_cache(maxsize=64)
def _jitted_scan_step(mesh: Mesh, sort_filter_type: int, validate_hash: bool):
    """One compiled program per (mesh, statics) — repeated steps hit the
    jit cache instead of re-tracing."""
    data_sharding = NamedSharding(mesh, P("dp", "sp"))
    pid_sharding = NamedSharding(mesh, P("dp"))
    return jax.jit(
        functools.partial(_scan_step, sort_filter_type=sort_filter_type,
                          validate_hash=validate_hash),
        out_shardings=(data_sharding, NamedSharding(mesh, P()),
                       NamedSharding(mesh, P()), pid_sharding),
    )
