"""Device-mesh parallelism for multi-partition batch work."""

from pegasus_tpu.parallel.partition_mesh import (
    PartitionMesh,
    make_mesh,
    sharded_scan_step,
)

# mesh_resident (the resident SPMD serving layer) is imported lazily by
# its call sites — importing this package must stay cheap for tools that
# only want the mesh shapes.
