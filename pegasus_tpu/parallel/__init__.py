"""Device-mesh parallelism for multi-partition batch work."""

from pegasus_tpu.parallel.partition_mesh import (
    PartitionMesh,
    make_mesh,
    sharded_scan_step,
)
