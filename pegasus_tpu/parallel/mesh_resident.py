"""Resident mesh serving: a table's record blocks live STACKED on the
device mesh and one SPMD program answers every partition's scan wave.

The per-partition serving path (scan_coordinator.stacked_block_eval,
partition_server._pushdown_aggregate_page) evaluates predicates in
per-chunk device programs — one dispatch per (key_width, capacity)
flavor per wave, per partition for aggregates. On a mesh the same work
is ONE program: each partition's blocks are a [B] row-slab of a
[P, B, K] resident image sharded PartitionSpec("dp", "sp"), refreshed
incrementally at flush/compaction publish, and a single jitted dispatch
returns

- the static keep mask for every partition (bit-packed on device — the
  device->host link is the scarce resource),
- per-partition [live, pre-value-filter, expired] counts (psum shapes:
  count and sum aggregates never touch rows), and
- per-partition value sums as four uint16 lanes in uint32 accumulators
  (jax x64 is disabled; lane-linearity recombines to sum mod 2^64
  exactly for up to 65536 resident rows per partition).

top_k / sample stay psum-free: the device mask all-gathers to the host
edge and the existing AggState folds the surviving rows in block order,
so results are byte-identical to the host arm by construction.

Placement: ops/placement grows a third "mesh" verdict —
mesh_wave_pays() weighs one mesh round against the host's per-chunk
dispatches — and the PR 15 drift auditor judges the prediction under
the "mesh" class like any other.

Tunnel safety: every dispatch runs under a TunnelWatchdog (bounded
deadline, consecutive-failure trip). A trip rebuilds the mesh over the
host-platform CPU devices (xla_force_host_platform_device_count gives
8 simulated devices without hardware); a trip while already on the CPU
mesh disables mesh serving entirely, degrading to today's host
kernels. A wedged tunnel can therefore delay one wave, never hang one.
"""

from __future__ import annotations

import functools
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from pegasus_tpu.utils.flags import FLAGS, define_flag
from pegasus_tpu.utils.metrics import METRICS

define_flag("pegasus.mesh", "serving_enabled", True,
            "route whole-table scan waves and pushdown aggregates to the "
            "resident device mesh when the placement model says it pays",
            mutable=True)
define_flag("pegasus.mesh", "dispatch_deadline_s", 30.0,
            "watchdog bound on one mesh dispatch (compile included); an "
            "overrun counts one consecutive tunnel failure", mutable=True)

_NODE = METRICS.entity("storage", "node")
_MESH_DISPATCH = _NODE.counter("mesh_dispatch_count")
_MESH_FALLBACK = _NODE.counter("mesh_fallback_count")
_TUNNEL_WEDGED = _NODE.gauge("tunnel_wedged")
# compaction-filter offload (the LUDA shape): whole-table drop-mask
# dispatches vs attempts that had to fall back to the host filter
# stage, plus the publish-refresh split — survivor-gather reuse vs
# full slab rebuild — that proves a mesh-filtered compaction never
# pays the predicate work twice
_COMPACT_MESH_DISPATCH = _NODE.counter("compact_mesh_dispatch_count")
_COMPACT_MESH_FALLBACK = _NODE.counter("compact_mesh_fallback_count")
_REFRESH_REUSE = _NODE.counter("mesh_refresh_reuse_count")
_REFRESH_REBUILD = _NODE.counter("mesh_refresh_rebuild_count")

_MASK64 = (1 << 64) - 1

# sum lanes are uint16 values accumulated in uint32: exact while
# rows_per_partition * 65535 < 2^32, i.e. up to 65536 resident rows
MAX_RESIDENT_ROWS = 65536

STACK_CHUNK = 16  # host chunk size (scan_coordinator) — cost-model input


def _servable_filters():
    from pegasus_tpu.ops.predicates import (
        FT_MATCH_ANYWHERE, FT_MATCH_POSTFIX, FT_MATCH_PREFIX, FT_NO_FILTER)
    return frozenset((FT_NO_FILTER, FT_MATCH_ANYWHERE, FT_MATCH_PREFIX,
                      FT_MATCH_POSTFIX))


def _tag_ckey(tag) -> Optional[Tuple[str, int]]:
    """Extract the (run_path, block_offset) cache key every wave caller
    embeds in its tag — bare, or as the tag's last element."""
    if isinstance(tag, tuple):
        if (len(tag) == 2 and isinstance(tag[0], str)
                and isinstance(tag[1], int)):
            return tag
        last = tag[-1] if tag else None
        if (isinstance(last, tuple) and len(last) == 2
                and isinstance(last[0], str) and isinstance(last[1], int)):
            return last
    return None


def _pattern_operands(pattern: bytes):
    """Raw numpy (buf[width], len) pattern operands — width bucketed so
    pattern length changes don't retrace the program. Deliberately NOT
    FilterSpec.make: that cache commits arrays to the ambient default
    device, which may not belong to the mesh."""
    from pegasus_tpu.ops.record_block import next_bucket

    width = next_bucket(max(1, len(pattern)))
    buf = np.zeros(width, dtype=np.uint8)
    if pattern:
        buf[:len(pattern)] = np.frombuffer(pattern, dtype=np.uint8)
    return buf, np.int32(len(pattern))


# -- the one program -------------------------------------------------------

def _mesh_step(keys, key_len, hashkey_len, expire_ts, valid, present, lanes,
               hash_lo,
               hash_pattern, hash_pattern_len, sort_pattern, sort_pattern_len,
               pidx, partition_version, allowed, now, extra, *,
               hash_filter_type: int, sort_filter_type: int,
               validate_hash: bool, with_sum: bool):
    """Whole-table predicate + aggregate step over the [P, B, K] image.

    Reuses _static_block_predicate by flattening [P, B] -> [P*B] with a
    per-row pidx vector (exactly the partition_mesh._scan_step contract)
    so the mesh and single-device paths cannot drift. `allowed` is the
    host-computed reject-all ownership gate per slot; `extra` carries the
    value-filter mask (all-ones when absent); `present` flags real rows
    inside the padded slab. `hash_lo` is the slab-staged per-record key
    hash (computed ONCE at refresh): validation is a compare against the
    resident column, never a per-wave re-hash of every key byte.
    """
    import jax.numpy as jnp

    from pegasus_tpu.ops.predicates import _static_block_predicate, ttl_expired

    p, b, k = keys.shape
    static = _static_block_predicate(
        keys.reshape(p * b, k), key_len.reshape(p * b),
        hashkey_len.reshape(p * b), valid.reshape(p * b),
        hash_pattern, hash_pattern_len, sort_pattern, sort_pattern_len,
        jnp.repeat(pidx, b), partition_version,
        hash_filter_type=hash_filter_type,
        sort_filter_type=sort_filter_type, validate_hash=validate_hash,
        hash_lo=hash_lo.reshape(p * b), use_hash_lo=True)
    static = static.reshape(p, b) & allowed[:, None]
    alive = ~ttl_expired(expire_ts, now)
    considered = static & alive       # survivors before the value filter
    live = considered & extra
    packed = jnp.packbits(static, axis=1)
    counts = jnp.stack([
        live.sum(axis=1, dtype=jnp.int32),
        considered.sum(axis=1, dtype=jnp.int32),
        (present & ~alive).sum(axis=1, dtype=jnp.int32),
    ], axis=1)
    if with_sum:
        lane_sums = (lanes * live[:, :, None].astype(jnp.uint32)
                     ).sum(axis=1, dtype=jnp.uint32)
    else:
        lane_sums = jnp.zeros((p, 4), jnp.uint32)
    return packed, counts, lane_sums


@functools.lru_cache(maxsize=64)
def _mesh_program(mesh, hash_filter_type: int, sort_filter_type: int,
                  validate_hash: bool, with_sum: bool):
    """One compiled whole-table program per (mesh, statics) — a flush
    generation re-dispatches with new operands, it does not re-trace."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    rep = NamedSharding(mesh, P())
    return jax.jit(
        functools.partial(_mesh_step, hash_filter_type=hash_filter_type,
                          sort_filter_type=sort_filter_type,
                          validate_hash=validate_hash, with_sum=with_sum),
        out_shardings=(rep, rep, rep))


# the compaction-filter twin: one compiled program per (mesh, ruleset
# CONTENT, statics). Rulesets are config-sync-delivered objects, so the
# cache keys on ops/compaction._ops_key — re-delivering the same JSON
# reuses the executable instead of leaking one per delivery. A manual
# OrderedDict because parsed Operation tuples are not hashable.
_COMPACT_PROGRAMS: "OrderedDict[tuple, object]" = OrderedDict()
_COMPACT_PROGRAM_CAP = 16


def _mesh_compact_program(mesh, operations, validate_hash: bool,
                          want_ets: bool):
    from pegasus_tpu.ops.compaction import _ops_key, mesh_compact_step

    key = (mesh, _ops_key(operations), bool(validate_hash),
           bool(want_ets))
    prog = _COMPACT_PROGRAMS.get(key)
    if prog is not None:
        _COMPACT_PROGRAMS.move_to_end(key)
        return prog
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    rep = NamedSharding(mesh, P())
    prog = jax.jit(
        functools.partial(mesh_compact_step, operations=operations,
                          validate_hash=bool(validate_hash),
                          want_ets=bool(want_ets)),
        out_shardings=(rep, rep) if want_ets else (rep,))
    _COMPACT_PROGRAMS[key] = prog
    while len(_COMPACT_PROGRAMS) > _COMPACT_PROGRAM_CAP:
        _COMPACT_PROGRAMS.popitem(last=False)
    return prog


# -- watchdog --------------------------------------------------------------

class TunnelWatchdog:
    """Bounded-deadline guard around every mesh dispatch.

    Each dispatch runs on its own daemon thread; the caller waits at most
    the deadline. An overrun or raising dispatch counts one CONSECUTIVE
    failure (any success resets the streak); `trip_after` in a row trips
    the tunnel: the wedged gauge goes up and the owner rebuilds on CPU
    devices or disables mesh serving. The wedged thread is abandoned
    (daemon) — it can never queue new waves behind itself.
    """

    def __init__(self, owner=None, deadline_s: Optional[float] = None,
                 trip_after: int = 2):
        self.owner = owner
        self.deadline_s = deadline_s  # None: pegasus.mesh dispatch flag
        self.trip_after = trip_after
        self.failures = 0       # consecutive
        self.trips = 0
        self.dispatches = 0
        self._lock = threading.Lock()

    def _deadline(self) -> float:
        if self.deadline_s is not None:
            return float(self.deadline_s)
        return float(FLAGS.get("pegasus.mesh", "dispatch_deadline_s"))

    def run(self, fn):
        """fn() under the dispatch deadline; the result, or None on
        timeout/error (one consecutive failure noted)."""
        box: Dict[str, Any] = {}
        done = threading.Event()

        def _worker():
            try:
                box["out"] = fn()
            except BaseException as exc:  # a dying dispatch is a failure
                box["err"] = exc
            finally:
                done.set()

        threading.Thread(target=_worker, daemon=True,
                         name="mesh-dispatch").start()
        if not done.wait(self._deadline()) or "err" in box:
            self._note_failure()
            return None
        with self._lock:
            self.failures = 0
            self.dispatches += 1
        return box.get("out")

    def _note_failure(self) -> None:
        _MESH_FALLBACK.increment()
        with self._lock:
            self.failures += 1
            tripped = self.failures >= self.trip_after
            if tripped:
                self.failures = 0
        if tripped:
            self.trip()

    def trip(self) -> None:
        self.trips += 1
        _TUNNEL_WEDGED.set(1.0)
        if self.owner is not None:
            self.owner._on_trip()

    def recover(self) -> None:
        with self._lock:
            self.failures = 0
        _TUNNEL_WEDGED.set(0.0)


# -- resident state --------------------------------------------------------

class _Slab:
    """One partition's host-side columnar image: every L1 block of its
    store concatenated, in sorted-run block order (the order the host
    aggregate arm folds in — byte-identity depends on it)."""

    __slots__ = ("server", "lsm_id", "generation", "n_rows", "width",
                 "keys", "key_len", "hashkey_len", "expire_ts", "valid",
                 "hash_lo", "flags", "segments", "lanes", "hdr")

    def __init__(self, server, lsm_id: int, generation: int):
        self.server = server
        self.lsm_id = lsm_id
        self.generation = generation
        self.n_rows: Optional[int] = None  # None: oversized / unservable
        self.width = 32
        self.keys = None
        self.key_len = None
        self.hashkey_len = None
        self.expire_ts = None
        self.valid = None
        self.hash_lo = None
        self.flags = None  # uint8[n] tombstone flags — host-only
        #                    column so the survivor-gather refresh can
        #                    replay the write stage's flags==0 check
        #                    without re-reading any block
        self.segments: List[tuple] = []  # (ckey, blk, start, n)
        self.lanes = None                # uint32[n, 4] — built on demand
        self.hdr = 0

    def ensure_lanes(self) -> None:
        if self.lanes is not None or not self.n_rows:
            self.lanes = self.lanes if self.lanes is not None else \
                np.zeros((self.n_rows or 0, 4), np.uint32)
            return
        from pegasus_tpu.ops.pushdown import values_as_u64

        lanes = np.zeros((self.n_rows, 4), np.uint32)
        for _ckey, blk, start, n in self.segments:
            vals = values_as_u64(blk.value_heap, blk.value_offs, self.hdr,
                                 np.arange(n))
            for j in range(4):
                lanes[start:start + n, j] = (
                    (vals >> np.uint64(16 * j)) & np.uint64(0xFFFF)
                ).astype(np.uint32)
        self.lanes = lanes


def _build_slab(server) -> _Slab:
    from pegasus_tpu.base.value_schema import header_length
    from pegasus_tpu.ops.record_block import block_from_columns

    lsm = server.engine.lsm
    slab = _Slab(server, id(lsm), lsm.generation)
    slab.hdr = header_length(server.data_version)
    entries = []  # (ckey, blk, n)
    total = 0
    width = 32
    for run in list(lsm.l1_runs):
        for idx, bm in enumerate(run.blocks):
            blk = run.read_block(idx)
            n = int(len(blk.expire_ts))
            entries.append(((run.path, bm.offset), blk, n))
            total += n
            width = max(width, int(blk.keys.shape[1]))
    if total > MAX_RESIDENT_ROWS:
        return slab  # n_rows stays None: partition too large to reside
    slab.n_rows = total
    slab.width = width
    slab.keys = np.zeros((total, width), np.uint8)
    slab.key_len = np.zeros(total, np.int32)
    slab.hashkey_len = np.zeros(total, np.int32)
    slab.expire_ts = np.zeros(total, np.uint32)
    slab.valid = np.zeros(total, bool)
    slab.hash_lo = np.zeros(total, np.uint32)
    slab.flags = np.zeros(total, np.uint8)
    start = 0
    for ckey, blk, n in entries:
        nb = block_from_columns(blk.keys, blk.key_len, blk.expire_ts)
        slab.keys[start:start + n, :nb.keys.shape[1]] = nb.keys[:n]
        slab.key_len[start:start + n] = nb.key_len[:n]
        slab.hashkey_len[start:start + n] = nb.hashkey_len[:n]
        slab.expire_ts[start:start + n] = nb.expire_ts[:n]
        slab.valid[start:start + n] = nb.valid[:n]
        # the per-record key hash is immutable alongside the keys, so it
        # resides WITH them: one batched crc64 pass per slab build (or
        # the SST's own column when carried) and every later wave
        # validates by compare instead of re-hashing the key bytes
        if blk.hash_lo is not None:
            slab.hash_lo[start:start + n] = np.asarray(
                blk.hash_lo, np.uint32)[:n]
        else:
            slab.hash_lo[start:start + n] = _slab_hash_lo(nb, n)
        if blk.flags is not None:
            slab.flags[start:start + n] = np.asarray(
                blk.flags, np.uint8)[:n]
        slab.segments.append((ckey, blk, start, n))
        start += n
    return slab


class _LazyBlock:
    """Segment proxy for a survivor-refreshed slab: the slab's columns
    were gathered host-side, so the underlying block bytes are only
    needed if a later aggregate fold / value-mask touches this segment
    — then the run is read once, on demand, exactly like _build_slab
    would have."""

    __slots__ = ("_run", "_idx", "_blk")

    def __init__(self, run, idx: int):
        self._run = run
        self._idx = idx
        self._blk = None

    def __getattr__(self, name):
        blk = object.__getattribute__(self, "_blk")
        if blk is None:
            run = object.__getattribute__(self, "_run")
            idx = object.__getattribute__(self, "_idx")
            blk = run.read_block(idx)
            object.__setattr__(self, "_blk", blk)
        return getattr(blk, name)


def _survivor_slab(server, slab0: Optional[_Slab],
                   pending: Optional[tuple]) -> Optional[_Slab]:
    """Refresh one partition's slab from the drop masks its own
    mesh-filtered compaction computed: gather the surviving rows out of
    the OLD slab columns instead of re-reading (and re-hashing) every
    published block. Returns the new slab, or None when anything about
    the publish doesn't match the stashed masks — interleaved flush,
    geometry drift, merge-path compaction — in which case the caller
    does the full rebuild (always safe).

    Verification is structural, not trusting: the new L1 runs' block
    metas must align 1:1 — count AND first key — with the nonzero
    survivor sets the masks predict (bulk_compact_rewrite emits one
    output block per surviving input block, in order), so a publish
    produced by anything other than exactly these masks rebuilds."""
    if pending is None or slab0 is None:
        return None
    p_slab, masks, _want_ets = pending
    lsm = server.engine.lsm
    if (p_slab is not slab0 or slab0.n_rows is None
            or slab0.flags is None
            or slab0.lsm_id != id(lsm)
            or lsm.generation != slab0.generation + 1
            or len(lsm.memtable) or lsm.l0):
        return None
    # survivors per old segment: THE survivor definition, shared with
    # bulk_compact_rewrite's transform
    from pegasus_tpu.storage.lsm import survivor_mask

    surv = []  # (src_rows, ets_rows)
    for ckey, _blk, start, n in slab0.segments:
        m = masks.get(ckey)
        if m is None:
            return None
        drop, ets_new = m
        keep = survivor_mask(drop, slab0.flags[start:start + n])
        kept = np.flatnonzero(keep)
        if kept.size == 0:
            continue
        src = start + kept
        ets_rows = (np.asarray(ets_new)[kept] if ets_new is not None
                    else slab0.expire_ts[src])
        surv.append((src, ets_rows))
    new_entries = [(run, idx, bm) for run in list(lsm.l1_runs)
                   for idx, bm in enumerate(run.blocks)]
    if len(surv) != len(new_entries):
        return None
    slab = _Slab(server, id(lsm), lsm.generation)
    slab.hdr = slab0.hdr
    total = sum(int(src.size) for src, _e in surv)
    slab.n_rows = total
    slab.width = slab0.width
    all_src = (np.concatenate([src for src, _e in surv])
               if surv else np.zeros(0, np.int64))
    slab.keys = slab0.keys[all_src]
    slab.key_len = slab0.key_len[all_src]
    slab.hashkey_len = slab0.hashkey_len[all_src]
    slab.valid = slab0.valid[all_src]
    slab.hash_lo = slab0.hash_lo[all_src]
    slab.flags = slab0.flags[all_src]
    slab.expire_ts = (np.concatenate([e for _s, e in surv])
                      if surv else np.zeros(0, np.uint32)
                      ).astype(np.uint32, copy=False)
    if slab0.lanes is not None:
        # value payloads survive a TTL-header patch untouched (the
        # u64 lanes read past the header), so gathered lanes stay exact
        slab.lanes = slab0.lanes[all_src]
    start = 0
    for (src, _ets), (run, idx, bm) in zip(surv, new_entries):
        n = int(src.size)
        if int(bm.count) != n:
            return None
        first = src[0]
        if bytes(slab0.keys[first, :int(slab0.key_len[first])]) \
                != bm.first_key:
            return None
        slab.segments.append(((run.path, bm.offset),
                              _LazyBlock(run, idx), start, n))
        start += n
    return slab


def _slab_hash_lo(nb, n: int) -> np.ndarray:
    """uint32[n] pegasus key-hash low lane from a padded key matrix, one
    vectorized crc64 pass. The hashed region always starts at byte 2:
    the hashkey, or (empty hashkey) the sort key, which then also begins
    at offset 2 — predicates.host_key_hash_lo's rule on columnar rows."""
    from pegasus_tpu.base.crc import crc64_batch

    if n == 0:
        return np.zeros(0, np.uint32)
    mat = np.ascontiguousarray(nb.keys[:n, 2:])
    hkl = nb.hashkey_len[:n]
    lens = np.where(hkl > 0, hkl, np.maximum(nb.key_len[:n] - 2, 0))
    return (crc64_batch(mat, lens.astype(np.int32), start=0)
            & np.uint64(0xFFFFFFFF)).astype(np.uint32)


class _Stack:
    """The device-resident [P, B, K] image of one table + its segment
    index. Immutable once built; a refresh swaps in a new one."""

    __slots__ = ("pmesh", "P", "B", "K", "keys", "key_len", "hashkey_len",
                 "expire_ts", "valid", "present", "hash_lo", "pidx",
                 "pidx_np", "slots", "index", "ones_extra", "rows_total",
                 "batch_bytes", "_lanes", "_extra_cache")

    def lanes_dev(self):
        if self._lanes is None:
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            arr = np.zeros((self.P, self.B, 4), np.uint32)
            for slot, (_pidx, slab) in enumerate(self.slots):
                slab.ensure_lanes()
                arr[slot, :slab.n_rows] = slab.lanes
            self._lanes = jax.device_put(
                arr, NamedSharding(self.pmesh.mesh, P("dp", "sp", None)))
        return self._lanes

    def extra_dev(self, vf):
        """The value-filter mask as a [P, B] operand; reuses the server's
        cached per-block masks so the pruned accounting matches the host
        arm bit for bit."""
        if vf is None:
            return self.ones_extra
        hit = self._extra_cache.get(vf)
        if hit is not None:
            return hit
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        arr = np.zeros((self.P, self.B), bool)
        for slot, (_pidx, slab) in enumerate(self.slots):
            for ckey, blk, start, n in slab.segments:
                arr[slot, start:start + n] = np.asarray(
                    slab.server._value_mask(ckey, blk, vf))[:n]
        dev = jax.device_put(
            arr, NamedSharding(self.pmesh.mesh, P("dp", "sp")))
        if len(self._extra_cache) >= 8:
            self._extra_cache.clear()
        self._extra_cache[vf] = dev
        return dev


def _build_stack(pmesh, slabs: List[Tuple[int, _Slab]]) -> _Stack:
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    dp = pmesh.dp
    n_slots = len(slabs)
    p_pad = max(dp, ((n_slots + dp - 1) // dp) * dp)
    max_rows = max(1, max(s.n_rows for _, s in slabs))
    b = 8
    while b < max_rows:
        b <<= 1
    k = max(32, max(s.width for _, s in slabs))

    keys = np.zeros((p_pad, b, k), np.uint8)
    key_len = np.zeros((p_pad, b), np.int32)
    hashkey_len = np.zeros((p_pad, b), np.int32)
    expire_ts = np.zeros((p_pad, b), np.uint32)
    valid = np.zeros((p_pad, b), bool)
    present = np.zeros((p_pad, b), bool)
    hash_lo = np.zeros((p_pad, b), np.uint32)
    pidx = np.zeros(p_pad, np.uint32)

    st = _Stack()
    st.index = {}
    st.slots = []
    st.rows_total = 0
    for slot, (part_idx, slab) in enumerate(slabs):
        n = slab.n_rows
        keys[slot, :n, :slab.keys.shape[1]] = slab.keys
        key_len[slot, :n] = slab.key_len
        hashkey_len[slot, :n] = slab.hashkey_len
        expire_ts[slot, :n] = slab.expire_ts
        valid[slot, :n] = slab.valid
        present[slot, :n] = True
        hash_lo[slot, :n] = slab.hash_lo
        pidx[slot] = part_idx
        for ckey, _blk, start, seg_n in slab.segments:
            st.index[ckey] = (slot, start, seg_n)
        st.slots.append((part_idx, slab))
        st.rows_total += n

    mesh = pmesh.mesh
    key_sh = NamedSharding(mesh, P("dp", "sp", None))
    col_sh = NamedSharding(mesh, P("dp", "sp"))
    pid_sh = NamedSharding(mesh, P("dp"))
    st.pmesh = pmesh
    st.P, st.B, st.K = p_pad, b, k
    st.keys = jax.device_put(keys, key_sh)
    st.key_len = jax.device_put(key_len, col_sh)
    st.hashkey_len = jax.device_put(hashkey_len, col_sh)
    st.expire_ts = jax.device_put(expire_ts, col_sh)
    st.valid = jax.device_put(valid, col_sh)
    st.present = jax.device_put(present, col_sh)
    st.hash_lo = jax.device_put(hash_lo, col_sh)
    st.pidx = jax.device_put(pidx, pid_sh)
    st.pidx_np = pidx
    st.ones_extra = jax.device_put(np.ones((p_pad, b), bool), col_sh)
    # same accounting the host wave auditor uses: key bytes + the 9
    # bytes/record of length/expiry columns
    st.batch_bytes = sum(
        int(s.keys.size) + 9 * int(s.n_rows) for _, s in slabs)
    st._lanes = None
    st._extra_cache = {}
    return st


class _TableResident:
    """One table's attachment record: its servers, per-partition slabs,
    and the current stacked device image."""

    def __init__(self, app_id: int):
        self.app_id = app_id
        self.servers: Dict[int, Any] = {}
        self.dirty: set = set()
        self.slabs: Dict[int, _Slab] = {}
        self.stack: Optional[_Stack] = None
        # pidx -> (slab, {ckey: (drop, ets|None)}, want_ets): the drop
        # masks a mesh-filtered compaction served, stashed until its
        # publish lands so the refresh can survivor-gather instead of
        # re-reading every block (the compaction already paid the
        # predicate work once)
        self.pending: Dict[int, tuple] = {}

    def refresh(self, owner: "MeshServing", pmesh) -> bool:
        """Rebuild ONLY the slabs whose store changed (publish-marked
        dirty, generation bump, or engine swap), restack if anything
        did. A dirty partition whose own mesh-filtered compaction just
        published reuses the stashed survivor masks (gather, no block
        reads); everything else takes the full rebuild. Returns whether
        the device image changed."""
        changed = False
        for pidx in sorted(self.servers):
            server = self.servers[pidx]
            lsm = server.engine.lsm
            slab = self.slabs.get(pidx)
            if (slab is None or pidx in self.dirty
                    or slab.lsm_id != id(lsm)
                    or slab.generation != lsm.generation):
                new_slab = _survivor_slab(server, slab,
                                          self.pending.pop(pidx, None))
                if new_slab is not None:
                    self.slabs[pidx] = new_slab
                    owner.refresh_reuses += 1
                    _REFRESH_REUSE.increment()
                else:
                    self.slabs[pidx] = _build_slab(server)
                    owner.slab_builds += 1
                    if slab is not None:  # a REFRESH, not first attach
                        owner.refresh_rebuilds += 1
                        _REFRESH_REBUILD.increment()
                changed = True
        self.dirty.clear()
        for pidx in list(self.slabs):
            if pidx not in self.servers:
                del self.slabs[pidx]
                changed = True
        if changed or (self.stack is None and self.slabs):
            slabs = [(pidx, self.slabs[pidx])
                     for pidx in sorted(self.slabs)]
            if slabs and all(s.n_rows is not None for _, s in slabs):
                self.stack = _build_stack(pmesh, slabs)
                owner.stack_builds += 1
            else:
                self.stack = None  # some partition exceeds residency
            changed = True
        return changed


# -- the serving layer -----------------------------------------------------

class MeshServing:
    """Singleton mesh-serving registry: explicit per-server attach, one
    resident stack per table, one program dispatch per wave."""

    def __init__(self):
        self._lock = threading.RLock()
        self._tables: Dict[int, _TableResident] = {}
        self._index: Dict[tuple, tuple] = {}  # ckey -> (tres, slot, start, n)
        self._pmesh = None
        self._mesh_failed = False
        self._force_cpu = False
        self.disabled = False
        self.watchdog = TunnelWatchdog(self)
        self.wave_dispatches = 0
        self.agg_dispatches = 0
        self.host_waves = 0
        self.slab_builds = 0
        self.stack_builds = 0
        self.compact_dispatches = 0
        self.compact_mask_serves = 0
        self.refresh_reuses = 0
        self.refresh_rebuilds = 0
        self._agg_cache: Dict[tuple, dict] = {}
        # (params, ckey) -> (drop, ets|None): per-BLOCK mask slices from
        # whole-table compaction dispatches. Keyed by run path + block
        # offset (immutable file content), so sibling partitions
        # compacting in the same epoch second reuse ONE dispatch even
        # across the restacks their interleaved publishes trigger.
        self._compact_cache: Dict[tuple, tuple] = {}

    # -- lifecycle ---------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return (not self.disabled and bool(self._tables)
                and bool(FLAGS.get("pegasus.mesh", "serving_enabled")))

    def attach(self, server) -> None:
        """Opt one partition server into mesh serving. Grouped per table
        (app_id); subscribes to the server's publish fan-out so flush and
        compaction installs mark exactly that partition dirty."""
        with self._lock:
            tres = self._tables.get(server.app_id)
            if tres is None:
                tres = self._tables[server.app_id] = _TableResident(
                    server.app_id)
            tres.servers[server.pidx] = server
            tres.dirty.add(server.pidx)
            listeners = getattr(server, "publish_listeners", None)
            if listeners is not None:
                app_id, pidx = server.app_id, server.pidx

                def _on_publish(_live_paths, _self=self, _a=app_id, _p=pidx):
                    _self.note_publish(_a, _p)

                listeners.append(_on_publish)

    def note_publish(self, app_id: int, pidx: int) -> None:
        with self._lock:
            tres = self._tables.get(app_id)
            if tres is not None and pidx in tres.servers:
                tres.dirty.add(pidx)
                self._agg_cache.clear()

    def reset(self) -> None:
        """Full detach — test/bench isolation hook. Stale publish hooks on
        previously attached servers no-op via the note_publish guard."""
        with self._lock:
            self._tables.clear()
            self._index.clear()
            self._agg_cache.clear()
            self._compact_cache.clear()
            self._pmesh = None
            self._mesh_failed = False
            self._force_cpu = False
            self.disabled = False
            self.watchdog = TunnelWatchdog(self)
            self.wave_dispatches = self.agg_dispatches = 0
            self.host_waves = 0
            self.slab_builds = self.stack_builds = 0
            self.compact_dispatches = self.compact_mask_serves = 0
            self.refresh_reuses = self.refresh_rebuilds = 0
        _TUNNEL_WEDGED.set(0.0)

    def note_host_wave(self) -> None:
        self.host_waves += 1

    # -- mesh / refresh ----------------------------------------------------

    def _mesh_or_none(self):
        with self._lock:
            if self._pmesh is not None:
                return self._pmesh
            if self._mesh_failed:
                return None
            try:
                import jax

                from pegasus_tpu.parallel.partition_mesh import make_mesh

                if self._force_cpu:
                    devs = jax.local_devices(backend="cpu")
                    self._pmesh = make_mesh(devices=devs)
                else:
                    self._pmesh = make_mesh()
            except Exception:
                self._mesh_failed = True
                return None
            return self._pmesh

    def _on_trip(self) -> None:
        """Watchdog verdict: the tunnel is wedged. Fall back to a mesh
        over the host-platform CPU devices; if we already ARE on CPU
        devices, the SPMD path itself is sick — disable mesh serving and
        let the host kernels carry (they never stopped working)."""
        with self._lock:
            self._agg_cache.clear()
            platform = None
            if self._pmesh is not None:
                try:
                    platform = self._pmesh.mesh.devices.flat[0].platform
                except Exception:
                    platform = None
            if self._pmesh is None or platform == "cpu" or self._force_cpu:
                self.disabled = True
                return
            self._force_cpu = True
            self._pmesh = None
            self._mesh_failed = False
            self._index.clear()
            for tres in self._tables.values():
                tres.stack = None
                tres.dirty.update(tres.servers)

    def ensure_current(self) -> bool:
        """Refresh every attached table's resident image (incremental:
        only publish-dirty / generation-bumped partitions restage)."""
        pmesh = self._mesh_or_none()
        if pmesh is None:
            return False
        with self._lock:
            changed = False
            for tres in self._tables.values():
                changed |= tres.refresh(self, pmesh)
            if changed:
                self._index = {}
                for tres in self._tables.values():
                    st = tres.stack
                    if st is not None:
                        for ckey, loc in st.index.items():
                            self._index[ckey] = (tres,) + loc
                self._agg_cache.clear()
            return True

    # -- dispatch ----------------------------------------------------------

    def _run_program(self, stack: _Stack, validate: bool, pv: int,
                     filter_key, now: int, extra, with_sum: bool):
        """One watchdogged whole-table dispatch. Returns
        (measured_s, (packed, counts, lane_sums)) numpy, or None."""
        hft, hfp, sft, sfp = filter_key
        hpat, hlen = _pattern_operands(hfp)
        spat, slen = _pattern_operands(sfp)
        if validate and pv < 0:
            allowed = np.zeros(stack.P, bool)
        elif validate:
            allowed = stack.pidx_np <= np.uint32(max(pv, 0))
        else:
            allowed = np.ones(stack.P, bool)
        lanes = stack.lanes_dev() if with_sum else None
        prog = _mesh_program(stack.pmesh.mesh, int(hft), int(sft),
                             bool(validate), bool(with_sum))
        pv_op = np.uint32(max(pv, 0) & 0xFFFFFFFF)
        now_op = np.uint32(now)

        def _call():
            import jax

            out = prog(stack.keys, stack.key_len, stack.hashkey_len,
                       stack.expire_ts, stack.valid, stack.present, lanes,
                       stack.hash_lo,
                       hpat, hlen, spat, slen, stack.pidx, pv_op, allowed,
                       now_op, extra)
            return jax.device_get(out)

        t0 = time.perf_counter()
        out = self.watchdog.run(_call)
        if out is None:
            return None
        return time.perf_counter() - t0, out

    def _audit(self, perf_ctxs, partitions: int, predicted_s: float,
               measured_s: float) -> None:
        from pegasus_tpu.server.workload import DRIFT
        from pegasus_tpu.utils import perf_context as perf

        DRIFT.note("mesh", predicted_s, measured_s)
        ctxs = [pc for pc in perf_ctxs if pc is not None]
        ambient = perf.current()
        if ambient is not None and all(pc is not ambient for pc in ctxs):
            ctxs.append(ambient)
        for pc in ctxs:
            pc.placement = "mesh"
            pc.predicted_kernel_ms += predicted_s * 1000.0
            pc.measured_kernel_ms += measured_s * 1000.0
            pc.mesh_partitions += partitions
            pc.mesh_wave_ms += measured_s * 1000.0

    def try_wave(self, blocks, validate: bool, pv: int, filter_key=None,
                 perf_ctxs=()) -> Optional[list]:
        """Serve one stacked wave from the resident image: ONE dispatch
        for every (tag, block) regardless of flavor mix. Returns
        [(tag, static_keep bool[n])] in input order, or None to decline
        (the host chunk path then runs unchanged)."""
        if not self.enabled:
            return None
        from pegasus_tpu.ops.predicates import FT_NO_FILTER

        fkey = tuple(filter_key) if filter_key else (
            FT_NO_FILTER, b"", FT_NO_FILTER, b"")
        servable = _servable_filters()
        if fkey[0] not in servable or fkey[2] not in servable:
            self.host_waves += 1
            return None
        if not self.ensure_current():
            self.host_waves += 1
            return None
        with self._lock:
            resolved = []
            tres0 = None
            batch_bytes = 0
            flavor_counts: Dict[tuple, int] = {}
            for tag, dev, bpidx in blocks:
                ckey = _tag_ckey(tag)
                hit = self._index.get(ckey) if ckey is not None else None
                if hit is None:
                    self.host_waves += 1
                    return None
                tres, slot, start, n = hit
                if tres0 is None:
                    tres0 = tres
                elif tres is not tres0:  # one table per resident program
                    self.host_waves += 1
                    return None
                if int(tres.stack.pidx_np[slot]) != int(bpidx):
                    self.host_waves += 1
                    return None
                resolved.append((tag, slot, start, n))
                batch_bytes += (int(dev.keys.size)
                                + 9 * int(dev.expire_ts.size))
                flavor = (int(dev.keys.shape[-1]), int(dev.keys.shape[0]))
                flavor_counts[flavor] = flavor_counts.get(flavor, 0) + 1
            stack = tres0.stack

            from pegasus_tpu.ops import placement

            n_programs = sum((c + STACK_CHUNK - 1) // STACK_CHUNK
                             for c in flavor_counts.values())
            if not placement.mesh_wave_pays(n_programs, batch_bytes):
                self.host_waves += 1
                return None

            res = self._run_program(stack, validate, pv, fkey, now=0,
                                    extra=stack.ones_extra, with_sum=False)
            if res is None:  # watchdog declined — host kernels carry
                self.host_waves += 1
                return None
            measured_s, (packed, _counts, _lanes) = res

        static = np.unpackbits(np.asarray(packed), axis=1).astype(bool)
        predicted_s = placement.predict_kernel_seconds("mesh", batch_bytes)
        _MESH_DISPATCH.increment()
        self.wave_dispatches += 1
        partitions = len({slot for _t, slot, _s, _n in resolved})
        self._audit(perf_ctxs, partitions, predicted_s, measured_s)
        return [(tag, static[slot, start:start + n])
                for tag, slot, start, n in resolved]

    def try_aggregate(self, server, req, pd, validate: bool, filter_key,
                      now: int, perf_ctx=None) -> Optional[dict]:
        """Answer one partition's whole-range pushdown aggregate from the
        table-wide resident dispatch. The dispatch is cached per (image,
        predicate, now): the first partition of a table pays one program,
        its siblings read their slot of the same result. Returns a dict
        (agg_state, pruned, expired, rows_evaluated, partitions, wave
        timings) or None to decline."""
        if not self.enabled:
            return None
        try:
            iter_budget = int(FLAGS.get("pegasus.server",
                                        "rocksdb_max_iteration_count") or 0)
        except KeyError:
            iter_budget = 0
        with self._lock:
            tres = self._tables.get(server.app_id)
        if tres is None or tres.servers.get(server.pidx) is not server:
            return None
        if server.engine.lsm.sorted_runs() is None:
            return None  # memtable / L0 overlay: host merge path handles
        fkey = tuple(filter_key)
        servable = _servable_filters()
        if fkey[0] not in servable or fkey[2] not in servable:
            return None
        if not self.ensure_current():
            return None
        from pegasus_tpu.ops import placement
        from pegasus_tpu.ops.predicates import host_alive_mask
        from pegasus_tpu.ops.pushdown import AggState

        with self._lock:
            stack = tres.stack
            if stack is None:
                return None
            slab = tres.slabs.get(server.pidx)
            slot = None
            for s, (part_idx, sl) in enumerate(stack.slots):
                if part_idx == server.pidx and sl is slab:
                    slot = s
                    break
            if slot is None or slab is None or slab.n_rows is None:
                return None
            if 0 < iter_budget < slab.n_rows:
                return None  # the host arm would PAGE this range: the
                #               paging protocol (partial rides the scan
                #               context, ships on the final page) must
                #               stay observable, so the mesh declines
            if slab.generation != server.engine.lsm.generation:
                return None  # raced a publish mid-call: host arm serves
            pv = int(server.partition_version)
            vf = pd.value_filter
            with_sum = pd.aggregate == "sum"
            cache_key = (id(stack), bool(validate), pv, fkey, vf, int(now),
                         with_sum)
            hit = self._agg_cache.get(cache_key)
            wave_ms = predicted_ms = measured_ms = 0.0
            if hit is None:
                # one mesh round vs one host wave per attached partition
                if not placement.mesh_wave_pays(max(1, len(stack.slots)),
                                                stack.batch_bytes):
                    return None
                extra = stack.extra_dev(vf)
                res = self._run_program(stack, validate, pv, fkey, now,
                                        extra, with_sum)
                if res is None:
                    return None
                measured_s, (packed, counts, lane_sums) = res
                lanes = np.asarray(lane_sums, dtype=np.uint64)
                totals = [int(lanes[s, 0] + (lanes[s, 1] << np.uint64(16))
                              + (lanes[s, 2] << np.uint64(32))
                              + (lanes[s, 3] << np.uint64(48))) & _MASK64
                          for s in range(stack.P)]
                hit = {
                    "static": np.unpackbits(np.asarray(packed),
                                            axis=1).astype(bool),
                    "counts": np.asarray(counts),
                    "totals": totals,
                }
                if len(self._agg_cache) >= 16:
                    self._agg_cache.clear()
                self._agg_cache[cache_key] = hit
                predicted_s = placement.predict_kernel_seconds(
                    "mesh", stack.batch_bytes)
                _MESH_DISPATCH.increment()
                self.agg_dispatches += 1
                from pegasus_tpu.server.workload import DRIFT

                DRIFT.note("mesh", predicted_s, measured_s)
                wave_ms = measured_ms = measured_s * 1000.0
                predicted_ms = predicted_s * 1000.0
            counts = hit["counts"]
            live_n = int(counts[slot, 0])
            considered = int(counts[slot, 1])
            expired = int(counts[slot, 2])
            partitions = len(stack.slots)

        state = AggState(pd)
        if pd.aggregate == "count":
            state.count = live_n
        elif pd.aggregate == "sum":
            state.count = live_n
            state.total = hit["totals"][slot]
        else:  # top_k / sample: all-gathered mask, host-edge fold in the
            # exact block order the host arm uses
            static_row = hit["static"][slot]
            for ckey, blk, start, n in slab.segments:
                keep = static_row[start:start + n] \
                    & host_alive_mask(blk.expire_ts, now)[:n]
                if vf is not None:
                    keep = keep & np.asarray(
                        server._value_mask(ckey, blk, vf))[:n]
                sel = np.flatnonzero(keep)
                state.fold_columnar(sel, heap=blk.value_heap,
                                    value_offs=blk.value_offs,
                                    hdr=slab.hdr, key_at=blk.key_at)
        return {
            "agg_state": state,
            "folded": live_n,
            "pruned": considered - live_n,
            "expired": expired,
            "rows_evaluated": int(slab.n_rows),
            "partitions": partitions,
            "wave_ms": wave_ms,
            "predicted_ms": predicted_ms,
            "measured_ms": measured_ms,
        }

    # -- compaction filter offload (the LUDA shape) ------------------------

    def _compact_params(self, now, default_ttl, partition_version,
                        validate, operations, want_ets) -> tuple:
        from pegasus_tpu.ops.compaction import _ops_key

        return (int(now) & 0xFFFFFFFF, int(default_ttl) & 0xFFFFFFFF,
                int(max(partition_version, 0)) & 0xFFFFFFFF,
                bool(validate), _ops_key(operations), bool(want_ets))

    def _compact_masks_from_cache(self, params, entries):
        """{(run, idx): (drop, ets|None)} for every entry, or None if
        any block's mask isn't cached under these filter params."""
        out = {}
        for run, i, bm in entries:
            m = self._compact_cache.get((params, (run.path, bm.offset)))
            if m is None:
                return None
            out[(run, i)] = m
        return out

    def _stash_pending(self, tres, pidx: int, lsm, params,
                       want_ets: bool) -> None:
        """Record the served masks against the partition's CURRENT slab
        so the publish this compaction is about to do can refresh
        residency by survivor-gather instead of a full rebuild."""
        slab = tres.slabs.get(pidx)
        if (slab is None or slab.n_rows is None
                or slab.lsm_id != id(lsm)
                or slab.generation != lsm.generation):
            return
        masks = {}
        for ckey, _blk, _start, _n in slab.segments:
            m = self._compact_cache.get((params, ckey))
            if m is None:
                return
            masks[ckey] = m
        tres.pending[pidx] = (slab, masks, want_ets)

    def try_compact_masks(self, lsm, entries, now, default_ttl, pidx,
                          partition_version, validate, operations,
                          want_ets: bool, n_windows: int = 1
                          ) -> Optional[dict]:
        """Serve one bulk compaction's FILTER stage from the resident
        image: ONE whole-table SPMD dispatch computes the drop masks
        (and rewritten-TTL column) for ALL of the table's partitions,
        and each sibling partition compacting under the same filter
        params in the same epoch second reads its blocks' slices from
        the per-ckey cache — table-wide compaction pays one dispatch,
        not one per partition per window.

        `entries` is lsm.bulk_compact_entries(); returns
        {(run, idx): (drop bool[n], new_ets uint32[n]|None)} covering
        every entry, or None to decline — gate says host wins, blocks
        not resident, store raced a publish, or the watchdog tripped
        mid-dispatch (the trip->CPU-mesh->host ladder then applies to
        the NEXT compaction; this one falls back to the host filter
        stage, byte-identical by construction)."""
        if not self.enabled or not entries:
            return None
        pidx = int(pidx)
        params = self._compact_params(now, default_ttl,
                                      partition_version, validate,
                                      operations, want_ets)
        with self._lock:
            tres = None
            for t in self._tables.values():
                srv = t.servers.get(pidx)
                if srv is not None and srv.engine.lsm is lsm:
                    tres = t
                    break
            if tres is None:
                return None
            got = self._compact_masks_from_cache(params, entries)
            if got is not None:  # a sibling's dispatch covered us
                self.compact_mask_serves += 1
                self._stash_pending(tres, pidx, lsm, params, want_ets)
                return got
        if not self.ensure_current():
            _COMPACT_MESH_FALLBACK.increment()
            return None
        from pegasus_tpu.ops import placement

        with self._lock:
            got = self._compact_masks_from_cache(params, entries)
            if got is not None:  # raced a sibling mid-refresh
                self.compact_mask_serves += 1
                self._stash_pending(tres, pidx, lsm, params, want_ets)
                return got
            stack = tres.stack
            slab = tres.slabs.get(pidx)
            if (stack is None or slab is None or slab.n_rows is None
                    or slab.lsm_id != id(lsm)
                    or slab.generation != lsm.generation):
                _COMPACT_MESH_FALLBACK.increment()
                return None
            for run, i, bm in entries:
                hit = stack.index.get((run.path, bm.offset))
                if hit is None or int(stack.pidx_np[hit[0]]) != pidx:
                    _COMPACT_MESH_FALLBACK.increment()
                    return None
            n_slots = max(1, len(stack.slots))
            mask_bytes = stack.P * (stack.B // 8)
            if want_ets:
                mask_bytes += 4 * stack.P * stack.B
            # one whole-table dispatch amortizes over every attached
            # partition's windows; a solo small compaction (one window,
            # one partition) honestly stays on the host filter stage
            if not placement.mesh_compact_pays(
                    max(1, int(n_windows)) * n_slots,
                    stack.batch_bytes, mask_bytes):
                return None
            prog = _mesh_compact_program(stack.pmesh.mesh, operations,
                                         bool(validate), bool(want_ets))
            if validate:
                allowed = stack.pidx_np <= np.uint32(params[2])
            else:
                allowed = np.ones(stack.P, bool)
            now_op = np.uint32(params[0])
            ttl_op = np.uint32(params[1])
            pv_op = np.uint32(params[2])

            def _call():
                import jax

                return jax.device_get(prog(
                    stack.keys, stack.key_len, stack.hashkey_len,
                    stack.expire_ts, stack.present, stack.hash_lo,
                    stack.pidx, allowed, now_op, ttl_op, pv_op))

            t0 = time.perf_counter()
            out = self.watchdog.run(_call)
            if out is None:  # overrun/error: this compaction goes host
                _COMPACT_MESH_FALLBACK.increment()
                return None
            measured_s = time.perf_counter() - t0
            drop_all = np.unpackbits(np.asarray(out[0]), axis=1,
                                     count=stack.B).astype(bool)
            ets_all = np.asarray(out[1]) if want_ets else None
            if len(self._compact_cache) > 65536:
                self._compact_cache.clear()
            for slot, (_part_idx, sl) in enumerate(stack.slots):
                for ckey, _blk, start, seg_n in sl.segments:
                    drop = np.ascontiguousarray(
                        drop_all[slot, start:start + seg_n])
                    ets = (np.ascontiguousarray(
                        ets_all[slot, start:start + seg_n])
                        if want_ets else None)
                    self._compact_cache[(params, ckey)] = (drop, ets)
            predicted_s = placement.predict_mesh_compact_seconds(
                stack.batch_bytes, mask_bytes)
            from pegasus_tpu.server.workload import DRIFT

            DRIFT.note("mesh_compact", predicted_s, measured_s)
            _COMPACT_MESH_DISPATCH.increment()
            self.compact_dispatches += 1
            self.compact_mask_serves += 1
            self._stash_pending(tres, pidx, lsm, params, want_ets)
            return self._compact_masks_from_cache(params, entries)

    # -- observability -----------------------------------------------------

    def status(self) -> Dict[str, Any]:
        with self._lock:
            waves = self.wave_dispatches + self.host_waves
            n_dev, platform = 0, None
            if self._pmesh is not None:
                devs = list(self._pmesh.mesh.devices.flat)
                n_dev = len(devs)
                platform = devs[0].platform if devs else None
            return {
                "enabled": self.enabled,
                "disabled": self.disabled,
                "tables": len(self._tables),
                "devices": n_dev,
                "platform": platform,
                "mesh_dispatch_count": int(_MESH_DISPATCH.value()),
                "mesh_fallback_count": int(_MESH_FALLBACK.value()),
                "tunnel_wedged": bool(_TUNNEL_WEDGED.value()),
                "wave_dispatches": self.wave_dispatches,
                "agg_dispatches": self.agg_dispatches,
                "host_waves": self.host_waves,
                "mesh_verdict_share": (round(self.wave_dispatches / waves, 3)
                                       if waves else 0.0),
                "slab_builds": self.slab_builds,
                "stack_builds": self.stack_builds,
                "compact_mesh_dispatch_count":
                    int(_COMPACT_MESH_DISPATCH.value()),
                "compact_mesh_fallback_count":
                    int(_COMPACT_MESH_FALLBACK.value()),
                "mesh_refresh_reuse_count": int(_REFRESH_REUSE.value()),
                "mesh_refresh_rebuild_count":
                    int(_REFRESH_REBUILD.value()),
                "compact_dispatches": self.compact_dispatches,
                "compact_mask_serves": self.compact_mask_serves,
                "refresh_reuses": self.refresh_reuses,
                "refresh_rebuilds": self.refresh_rebuilds,
                "watchdog": {
                    "deadline_s": self.watchdog._deadline(),
                    "consecutive_failures": self.watchdog.failures,
                    "trips": self.watchdog.trips,
                    "dispatches": self.watchdog.dispatches,
                },
            }


MESH_SERVING = MeshServing()
