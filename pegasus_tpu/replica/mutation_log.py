"""Private mutation log (per-replica WAL of mutations).

Parity: src/replica/mutation_log.h:70,416 — the decree-ordered private
log: every prepared mutation is appended before it can be acked, the log
replays on boot to rebuild the prepare list, learning reads ranges back
out (mutation_log.h:231), and GC drops everything at or below the durable
(flushed-to-storage) decree (mutation_log.h:213).

Frame format: the shared framed-log codec (storage/framed_log.py —
[u32 len][u32 crc32][encoded mutation]), same torn-tail recovery
contract as the storage WAL.

Group commit: `append(mu, flush=False)` stages a frame in the append
buffer without making it OS-visible; the node-level plog batcher
(replica/group_commit.py) later calls `commit_window()` ONCE per
transport flush window — one flush (and at most one fsync) covers every
mutation staged across all partitions in the window, and acks are
released only after it returns, so the appended-before-acked contract
is unchanged. Readers (learning, duplication tailing, GC) call through
`_ensure_flushed` so a buffered tail is never invisible to them.
"""

from __future__ import annotations

import os

from pegasus_tpu.storage.vfs import (
    fsync_dir,
    fsync_file,
    open_data_file,
    repair_truncate,
)
import struct
from typing import Iterable, Iterator, List, Optional, Tuple

from pegasus_tpu.replica.mutation import Mutation
from pegasus_tpu.storage.framed_log import iter_frames, pack_frame


class MutationLog:
    def __init__(self, path: str) -> None:
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # one pass: find the valid tail AND the max decree (the decree sits
        # at a fixed offset in the mutation header — no full decode needed)
        valid_end, self.max_decree = self._scan(path)
        if valid_end is not None:
            repair_truncate(path, valid_end)
        self._f = open_data_file(path, "ab")
        # frames written but not yet flushed to the OS (group commit);
        # readers flush before reopening the file
        self._buffered = False
        # bumped whenever the file is rewritten (gc): readers holding byte
        # offsets must restart from 0 when the generation changes
        self.generation = 0

    @staticmethod
    def _scan(path: str) -> tuple[Optional[int], int]:
        """Returns (truncate_to | None-if-clean, max_decree)."""
        if not os.path.exists(path):
            return None, 0
        with open_data_file(path, "rb") as f:
            data = f.read()
        max_decree = 0
        pos = 0
        for payload, end in iter_frames(data):
            (decree,) = struct.unpack_from("<Q", payload, 8)
            max_decree = max(max_decree, decree)
            pos = end
        return (pos if pos < len(data) else None), max_decree

    def append(self, mu: Mutation, sync: bool = False,
               flush: bool = True) -> None:
        """Append one mutation. `flush=False` stages the frame in the
        append buffer for a later `commit_window()` (group commit) —
        the caller owns NOT acking until that commit happens."""
        self._f.write(pack_frame(mu.encode()))
        if flush:
            self._f.flush()
            if sync:
                fsync_file(self._f)
        else:
            self._buffered = True
        self.max_decree = max(self.max_decree, mu.decree)

    def append_batch(self, mus: Iterable[Mutation],
                     sync: bool = False) -> None:
        """Append many mutations as one buffered write + one flush (and
        at most one fsync) — the storage WAL's append_batch shape."""
        frames = []
        for mu in mus:
            frames.append(pack_frame(mu.encode()))
            self.max_decree = max(self.max_decree, mu.decree)
        if not frames:
            return
        self._f.write(b"".join(frames))
        self._f.flush()
        self._buffered = False
        if sync:
            fsync_file(self._f)

    def commit_window(self, sync: bool = False) -> None:
        """Make every buffered append durable: one flush, one optional
        fsync, shared by all frames staged since the last commit."""
        self._f.flush()
        self._buffered = False
        if sync:
            fsync_file(self._f)

    def _ensure_flushed(self) -> None:
        """Readers reopen the file by path; a buffered tail must reach
        the OS first or they would serve a stale prefix."""
        if self._buffered:
            self._f.flush()
            self._buffered = False

    @staticmethod
    def replay(path: str) -> Iterator[Mutation]:
        if not os.path.exists(path):
            return
        with open_data_file(path, "rb") as f:
            data = f.read()
        for payload, _end in iter_frames(data):
            yield Mutation.decode(payload)

    def read_range(self, start_decree: int,
                   end_decree: Optional[int] = None) -> List[Mutation]:
        """Mutations with start_decree <= decree <= end_decree (learning:
        LT_LOG ships these, replica_learn.cpp:483-508). The log may hold
        multiple entries per decree (ballot changes); the highest-ballot
        one wins, matching replay semantics."""
        self._ensure_flushed()
        best: dict[int, Mutation] = {}
        for mu in self.replay(self.path):
            if mu.decree < start_decree:
                continue
            if end_decree is not None and mu.decree > end_decree:
                continue
            cur = best.get(mu.decree)
            if cur is None or mu.ballot >= cur.ballot:
                best[mu.decree] = mu
        return [best[d] for d in sorted(best)]

    def read_tail(self, offset: int) -> "List[Tuple[Mutation, int]]":
        """Incremental read: (mutation, end_offset) pairs for frames
        starting at byte `offset` (parity: load_from_private_log tails the
        log instead of re-reading it). Per-frame offsets let a consumer
        stop mid-batch WITHOUT skipping unprocessed frames — it resumes
        from the last frame it actually consumed. Callers re-tail from 0
        when `generation` changes."""
        self._ensure_flushed()
        with open_data_file(self.path, "rb") as f:
            f.seek(offset)
            data = f.read()
        return [(Mutation.decode(payload), offset + end)
                for payload, end in iter_frames(data)]

    def gc(self, durable_decree: int) -> None:
        """Drop everything <= durable_decree.

        Crash-safe: the kept tail is written to a temp file, fsynced, and
        os.replace()d over the log (then the directory is fsynced so the
        rename is durable). Truncating the live file first would lose the
        retained tail on a crash mid-rewrite — the uncommitted prepare
        window and the mutations duplication has not yet shipped (the gc
        floor is held back precisely to preserve those).
        """
        self._ensure_flushed()
        keep = [mu for mu in self.replay(self.path)
                if mu.decree > durable_decree]
        tmp = self.path + ".gc.tmp"
        with open_data_file(tmp, "wb") as f:
            for mu in keep:
                f.write(pack_frame(mu.encode()))
            f.flush()
            fsync_file(f)
        # replace first, swap the append handle after: if the replace
        # raises, self._f still appends to the live (un-gc'd) log instead
        # of being left closed and wedging every later append
        os.replace(tmp, self.path)
        try:
            fsync_dir(os.path.dirname(self.path))
        finally:
            self._f.close()
            self._f = open_data_file(self.path, "ab")
            self.generation += 1

    def close(self) -> None:
        self._ensure_flushed()
        self._f.close()
