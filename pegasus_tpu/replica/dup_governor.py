"""Duplication-shipping governor: AIMD backpressure for geo-replication
catch-up.

Taurus (PAPERS.md) shows log-shipping replication must be batched AND
flow-controlled to survive real links; RESYSTANCE shows unmanaged
background transfer wrecking foreground latency. This is the dup twin of
the PR 8 CompactionGovernor, closed from the FOLLOWER side: every
`dup_apply_batch` ack carries the follower node's foreground-pressure
counters (the PR 2 `deadline_expired_count` + `read_shed_count` pair),
and the source node's governor turns growth into a multiplicative
backoff of the ship-window byte budget. Catch-up therefore slows BEFORE
the follower sheds its own foreground load, recovers multiplicatively
once acks come back quiet, and never throttles below a forward-progress
floor — the duplicator always loads at least one mutation per tick, so
catch-up cannot stall however hard the link is squeezed (a stalled dup
pins the log-GC floor forever, which eventually hurts more than the
bandwidth it frees).

One governor per NODE (all of a stub's dup sessions share the WAN
egress), clocked on the stub's sim clock so seeded schedules replay.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

from pegasus_tpu.utils.flags import FLAGS, define_flag
from pegasus_tpu.utils.metrics import METRICS

define_flag("pegasus.dup", "ship_max_mbps", 0.0,
            "hard duplication-shipping bandwidth cap in MB/s; 0 = "
            "uncapped until follower pressure engages the AIMD backoff",
            mutable=True)
define_flag("pegasus.dup", "ship_min_mbps", 0.25,
            "floor the follower-pressure backoff never throttles below "
            "— catch-up must keep making forward progress (the window "
            "additionally always carries at least one mutation, so a "
            "zero byte budget cannot stall shipping)", mutable=True)
define_flag("pegasus.dup", "ship_governor", True,
            "enable AIMD backpressure on duplication shipping fed by "
            "the follower pressure counters riding each batch ack",
            mutable=True)
define_flag("pegasus.dup", "ship_feedback_interval_s", 1.0,
            "minimum seconds between multiplicative recovery steps on "
            "quiet acks (backoff reacts to every pressure growth "
            "immediately; recovery is paced)", mutable=True)


class DupGovernor:
    """Per-node ship-budget pacer. The duplicator asks `window_budget()`
    before loading a ship window and reports `note_shipped()` wire
    bytes; acks feed `on_follower_pressure()`."""

    RECOVER_FACTOR = 1.5
    UNCAP_FACTOR = 2.0

    def __init__(self, node: str,
                 clock: Optional[Callable[[], float]] = None) -> None:
        self._clock = clock or time.monotonic
        # MB/s currently enforced; 0 = uncapped. Like the compaction
        # governor, an OPERATOR cap (ship_max_mbps) is permanent while a
        # PRESSURE-engaged cap recovers back to uncapped.
        self._throttle_mbps = 0.0
        self._engaged_at_mbps = 0.0
        self._tokens = 0.0
        self._tok_t = self._clock()
        self._recover_t = self._clock()
        # last observed cumulative pressure per follower node
        self._pressure: Dict[str, int] = {}
        # measured recent ship rate (1s windows -> gauge)
        self._win_t = self._clock()
        self._win_bytes = 0
        self._rate_bps = 0.0
        ent = METRICS.entity("duplication", node, {"node": node})
        self._g_throttle = ent.gauge("dup_throttle_mbps")
        self._g_rate = ent.gauge("dup_ship_bytes_per_s")
        self._c_backoff = ent.counter("dup_backoff_count")

    @staticmethod
    def enabled() -> bool:
        return bool(FLAGS.get("pegasus.dup", "ship_governor"))

    # ---- feedback (rides every dup_apply_batch ack) --------------------

    def on_follower_pressure(self, follower: str,
                             counters: Optional[dict]) -> None:
        if not counters or not self.enabled():
            return
        total = (int(counters.get("deadline_expired", 0))
                 + int(counters.get("read_shed", 0)))
        prev = self._pressure.get(follower)
        self._pressure[follower] = total
        if prev is None:
            return
        now = self._clock()
        min_mbps = float(FLAGS.get("pegasus.dup", "ship_min_mbps"))
        max_mbps = float(FLAGS.get("pegasus.dup", "ship_max_mbps"))
        if total > prev:
            # the follower is shedding/expiring foreground work: halve
            # the allowance (engaging a cap at half the measured recent
            # ship rate when previously uncapped)
            cur = self._throttle_mbps
            if cur == 0:
                cur = max(self._rate_bps / 1e6, min_mbps * 2)
                self._engaged_at_mbps = cur
            self._throttle_mbps = max(cur / 2, min_mbps)
            self._c_backoff.increment()
            self._g_throttle.set(self._throttle_mbps)
            self._recover_t = now
            return
        # quiet ack: multiplicative recovery, paced to the feedback
        # interval so a burst of acks does not undo a backoff at once
        cur = self._throttle_mbps
        if cur == 0:
            return
        if now - self._recover_t < float(
                FLAGS.get("pegasus.dup", "ship_feedback_interval_s")):
            return
        self._recover_t = now
        cur *= self.RECOVER_FACTOR
        if max_mbps > 0:
            self._throttle_mbps = min(cur, max_mbps)
        elif self._engaged_at_mbps > 0 and \
                cur >= self._engaged_at_mbps * self.UNCAP_FACTOR:
            self._throttle_mbps = 0.0  # fully recovered: uncap
            self._engaged_at_mbps = 0.0
        else:
            self._throttle_mbps = cur
        self._g_throttle.set(self._throttle_mbps)

    # ---- budget (asked once per dup tick per session) ------------------

    def window_budget(self) -> Optional[int]:
        """Bytes the next ship window may load; None = uncapped. The
        CALLER applies the forward-progress floor (a window always
        carries at least one mutation, whatever this returns)."""
        if not self.enabled():
            return None
        max_mbps = float(FLAGS.get("pegasus.dup", "ship_max_mbps"))
        if self._throttle_mbps == 0 and max_mbps > 0:
            self._throttle_mbps = max_mbps  # operator cap always on
        rate = self._throttle_mbps
        if rate <= 0:
            return None
        now = self._clock()
        bps = rate * 1e6
        # token bucket with a 1s burst allowance; the floor mutation may
        # drive tokens negative (an envelope is atomic) — debt is capped
        # so one oversized window cannot stall shipping for minutes
        self._tokens = min(self._tokens + (now - self._tok_t) * bps,
                           bps * 1.0)
        self._tok_t = now
        return max(0, int(self._tokens))

    def note_shipped(self, nbytes: int) -> None:
        now = self._clock()
        bps = max(self._throttle_mbps, 0.001) * 1e6
        self._tokens = max(self._tokens - nbytes, -bps * 2.0)
        self._win_bytes += nbytes
        dt = now - self._win_t
        if dt >= 1.0:
            self._rate_bps = self._win_bytes / dt
            self._g_rate.set(int(self._rate_bps))
            self._win_t = now
            self._win_bytes = 0

    # ---- observability --------------------------------------------------

    def status(self) -> dict:
        return {
            "throttle_mbps": round(self._throttle_mbps, 3),
            "ship_bytes_per_s": int(self._rate_bps),
            "backoff_count": self._c_backoff.value(),
            "followers_observed": sorted(self._pressure),
        }
