"""Node-level write flush window: plog group commit + prepare fan-out
aggregation.

The write-side twin of the read coordinator's flush window. A replica
stub opens a window around each message dispatch (and the transport's
batch-drain hands it whole runs of queued client writes); while the
window is open:

- **plog group commit**: every partition's `MutationLog.append` stages
  its frame in the log's append buffer instead of flushing per
  mutation. When the window closes, each dirty log gets ONE flush (and,
  in `fsync` mode, ONE fsync) covering every mutation staged in the
  window — the Taurus-style batch-hardening shape (PAPERS.md,
  arXiv:2506.20010) applied to the private log. Acks and prepare sends
  registered via `after_durable` run only after that shared
  flush/fsync, so the appended-before-acked durability contract
  (mutation_log.py) is unchanged: a crash mid-window loses only
  mutations nobody was ever acked for, and the torn-tail scan recovers
  the valid prefix.

- **prepare fan-out aggregation**: consecutive prepares (and prepare
  acks) destined for the same peer queue here instead of going out as
  one message per mutation per partition; the window close ships one
  `prepare_batch` / `prepare_batch_ack` message per (peer, kind)
  carrying (gpid, payload) items for every partition that prepared in
  the window — cutting the per-write message count on the secondary
  path by the window's coalescing factor.

Sync modes (`[pegasus.replica] plog_sync_mode`):
- "flush": one OS flush per window (the pre-group-commit durability
  level — survives process crash — amortized across the window);
- "fsync": one shared fsync per window (power-loss durable, ~1 fsync
  per window instead of one per mutation);
- "always": legacy per-append fsync, no deferral (the strictest and
  slowest mode; windows still aggregate prepares).

Outside a window (replicas driven directly, e.g. unit tests or bench
loaders) every call falls through to the immediate legacy behavior.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Tuple

from pegasus_tpu.utils.flags import FLAGS, define_flag

define_flag("pegasus.replica", "plog_sync_mode", "flush",
            "private-log durability per group-commit window: 'flush' "
            "(one OS flush per window), 'fsync' (one shared fsync per "
            "window), 'always' (fsync every append, no deferral)",
            mutable=True)

# message kinds the window aggregates per destination peer; everything
# else (group checks, learn traffic, config) keeps solo sends
_AGGREGATED = {"prepare": "prepare_batch",
               "prepare_ack": "prepare_batch_ack"}


class WriteFlushWindow:
    """One per node (replica stub). Reentrant: nested dispatches share
    the outermost window; the flush runs when the last level exits."""

    def __init__(self, net, node_name: str, metrics) -> None:
        self.net = net
        self.node = node_name
        self._depth = 0
        self._flushing = False
        # MutationLogs with buffered frames this window, insertion order
        self._dirty: Dict[int, object] = {}
        self._staged = 0  # mutations staged this window (metric)
        self._pending: List[Callable[[], None]] = []
        # (dst, solo_kind) -> [(gpid, payload)]
        self._agg: Dict[Tuple[str, str], list] = {}
        self._group_commit_size = metrics.percentile("group_commit_size")
        self._fsync_count = metrics.counter("plog_fsync_count")
        self._prepare_batch_size = metrics.percentile("prepare_batch_size")

    # ---- window lifecycle ---------------------------------------------

    @property
    def active(self) -> bool:
        return self._depth > 0 or self._flushing

    def __enter__(self) -> "WriteFlushWindow":
        self._depth += 1
        return self

    def __exit__(self, *exc) -> None:
        self._depth -= 1
        if self._depth == 0:
            self._flush()

    # ---- plog group commit --------------------------------------------

    def append(self, log, mu) -> None:
        """Stage a mutation into `log` under this window's shared
        flush/fsync; immediate legacy append when no window is open."""
        mode = FLAGS.get("pegasus.replica", "plog_sync_mode")
        if not self.active or mode == "always":
            log.append(mu, sync=(mode in ("always", "fsync")))
            if mode in ("always", "fsync"):
                self._fsync_count.increment()
            return
        log.append(mu, flush=False)
        self._dirty[id(log)] = log
        self._staged += 1

    def after_durable(self, fn: Callable[[], None]) -> None:
        """Run `fn` once every mutation staged so far is durable (at
        window close, after the shared flush/fsync); immediately when no
        window is open (nothing is buffered then)."""
        if not self.active:
            fn()
        else:
            self._pending.append(fn)

    def wal_flush_deferred(self) -> bool:
        """True while a window is open: the apply path may leave its
        engine-WAL frame in the IO buffer instead of flushing per
        decree. Under replication the engine WAL is redundant with the
        private log — the plog's GC floor is the SST-flushed decree, so
        every decree the WAL could recover is also replayed (and
        recommitted through the reprepare/group-check path) from the
        plog, which hardened BEFORE any ack left this window. The
        reference makes the same call by running rocksdb with its WAL
        disabled under replication; here the frames ride the buffer
        until it fills or the memtable flush truncates the file."""
        return self.active

    # ---- prepare fan-out aggregation ----------------------------------

    def queue_replica_msg(self, dst: str, msg_type: str, gpid,
                          payload) -> bool:
        """Divert an aggregatable replica message into the window's
        per-peer batch; False = caller sends solo. Each item captures
        its own trace context at queue time — a prepare_batch carries
        many partitions' 2PC legs, each on its OWN trace, so the
        context must travel per item, not per carrier message."""
        if not self.active or msg_type not in _AGGREGATED:
            return False
        from pegasus_tpu.server.tenancy import current as current_tenant
        from pegasus_tpu.utils.tracing import current_ctx

        # the ambient QoS tenant travels per item too (replica.client_
        # write re-binds it around the deferred fan-out), so a receiving
        # node's per-leg spans answer "whose write was this" even though
        # the carrier coalesces many tenants' 2PC legs
        self._agg.setdefault((dst, msg_type), []).append(
            (gpid, payload, current_ctx(), current_tenant()))
        return True

    # ---- flush ---------------------------------------------------------

    def _flush(self) -> None:
        self._flushing = True
        mode = FLAGS.get("pegasus.replica", "plog_sync_mode")
        sync = mode == "fsync"
        try:
            # loop: after-durable callbacks commit/apply mutations and
            # drain write queues, which can stage NEW appends and acks
            # into the same window — they harden in a follow-up pass
            # before their own callbacks run
            while self._dirty or self._pending:
                logs = list(self._dirty.values())
                self._dirty.clear()
                staged, self._staged = self._staged, 0
                for log in logs:
                    log.commit_window(sync=sync)
                    if sync:
                        self._fsync_count.increment()
                if staged:
                    self._group_commit_size.set(staged)
                cbs = self._pending
                self._pending = []
                for cb in cbs:
                    try:
                        cb()
                    except Exception:  # noqa: BLE001 - one failing
                        # write must not strand its window neighbors'
                        # acks (the solo path confined the blast radius
                        # to the one write that raised; so does this)
                        import traceback

                        traceback.print_exc()
        finally:
            self._flushing = False
            # ship aggregated fan-out even if a commit_window raised
            # above — staged prepares must never sit until an
            # unrelated later window closes
            agg, self._agg = self._agg, {}
            for (dst, kind), items in agg.items():
                self._prepare_batch_size.set(len(items))
                if len(items) == 1:
                    gpid, payload, ctx, _tenant = items[0]
                    self.net.send(self.node, dst, "replica", {
                        "gpid": gpid, "type": kind, "payload": payload,
                        "trace": ctx})
                else:
                    # trace: None suppresses ambient stamping — the
                    # carrier spans MANY traces (one per item ctx); a
                    # single carrier-level context would be a lie
                    self.net.send(self.node, dst, _AGGREGATED[kind],
                                  {"items": items, "trace": None})
