"""PrepareList: the sliding commit window of in-flight mutations.

Parity: src/replica/prepare_list.h:56,82 — a decree-indexed window
[last_committed+1, last_committed+capacity]; prepare() admits mutations
in decree order (same-decree re-prepare with a higher ballot replaces),
commit() advances last_committed and hands mutations to the apply
callback. Commit modes mirror the reference (prepare_list.cpp:100,132):

- COMMIT_TO_DECREE_HARD: commit everything <= d; gaps are fatal (used on
  secondaries following the primary's piggy-backed commit point).
- COMMIT_ALL_READY: commit the maximal contiguous prefix (used on the
  primary as acks arrive).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from pegasus_tpu.replica.mutation import Mutation

COMMIT_TO_DECREE_HARD = 0
COMMIT_ALL_READY = 1
COMMIT_TO_DECREE_SOFT = 2


class PrepareList:
    def __init__(self, last_committed: int, capacity: int,
                 committer: Callable[[Mutation], None]) -> None:
        self._mutations: Dict[int, Mutation] = {}
        self._last_committed = last_committed
        self._capacity = capacity
        self._committer = committer
        self._ready: set[int] = set()  # decrees acked/ready to commit

    @property
    def last_committed_decree(self) -> int:
        return self._last_committed

    def max_decree(self) -> int:
        return max(self._mutations, default=self._last_committed)

    def count(self) -> int:
        return len(self._mutations)

    def get_mutation_by_decree(self, decree: int) -> Optional[Mutation]:
        return self._mutations.get(decree)

    def prepare(self, mu: Mutation) -> None:
        if mu.decree <= self._last_committed:
            return  # already committed; stale re-send
        if mu.decree > self._last_committed + self._capacity:
            raise ValueError(
                f"decree {mu.decree} beyond window "
                f"(last_committed={self._last_committed}, "
                f"capacity={self._capacity})")
        existing = self._mutations.get(mu.decree)
        if existing is not None and existing.ballot > mu.ballot:
            return  # keep the higher-ballot mutation
        self._mutations[mu.decree] = mu

    def mark_ready(self, decree: int) -> None:
        """Primary side: all replicas acked this decree."""
        if decree > self._last_committed:
            self._ready.add(decree)

    def commit(self, decree: int, mode: int) -> int:
        """Returns the number of mutations committed."""
        n = 0
        if mode in (COMMIT_TO_DECREE_HARD, COMMIT_TO_DECREE_SOFT):
            while self._last_committed < decree:
                d = self._last_committed + 1
                mu = self._mutations.pop(d, None)
                if mu is None:
                    if mode == COMMIT_TO_DECREE_SOFT:
                        return n  # stop at the first gap (mid-learn state)
                    raise RuntimeError(
                        f"commit gap at decree {d} (target {decree})")
                self._last_committed = d
                self._ready.discard(d)
                self._committer(mu)
                n += 1
            return n
        if mode == COMMIT_ALL_READY:
            while (self._last_committed + 1) in self._ready:
                d = self._last_committed + 1
                mu = self._mutations.pop(d)
                self._last_committed = d
                self._ready.discard(d)
                self._committer(mu)
                n += 1
            return n
        raise ValueError(f"unknown commit mode {mode}")

    def reset(self, last_committed: int) -> None:
        """Drop everything and restart the window (post-learn, parity:
        reset_prepare_list_after_replay)."""
        self._mutations.clear()
        self._ready.clear()
        self._last_committed = last_committed
