"""ReplicaStub: one replica-server node hosting many partition replicas.

Parity: src/replica/replica_stub.{h,cpp} — a node owns all its `Replica`
instances, routes gpid-addressed messages to them (the rDSN layer-2
interception, src/runtime/service_engine.cpp:163), creates replicas on
meta config proposals, reports its stored replicas in config-sync, and
runs the failure-detector client side (beacons to meta).

All inter-node traffic is enveloped as ("replica", {gpid, type, payload})
so one network address serves every partition on the node.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Optional, Tuple

from pegasus_tpu.replica.replica import PartitionStatus, Replica, ReplicaConfig

Gpid = Tuple[int, int]  # (app_id, partition_index)


class _GpidTransport:
    """Binds a replica's sends to its node + gpid envelope."""

    def __init__(self, net, node_name: str, gpid: Gpid) -> None:
        self._net = net
        self._node = node_name
        self._gpid = gpid

    def send(self, _src: str, dst: str, msg_type: str, payload) -> None:
        self._net.send(self._node, dst, "replica", {
            "gpid": self._gpid, "type": msg_type, "payload": payload})


class ReplicaStub:
    def __init__(self, name: str, data_dir: str, net,
                 clock: Optional[Callable[[], float]] = None,
                 sim_clock: Optional[Callable[[], float]] = None) -> None:
        self.name = name
        self.data_dir = data_dir
        self.net = net
        self.clock = clock
        # FD timeline clock (sim time); defaults to the wall clock
        self.sim_clock = sim_clock or clock or (lambda: 0.0)
        self.replicas: Dict[Gpid, Replica] = {}
        self.meta_addr: Optional[str] = None
        self._last_beacon_ack = float("-inf")
        net.register(name, self.on_message)
        # load existing replica dirs (parity: replica_stub boot scan,
        # replica_stub.cpp:594 load_replicas); each dir carries a
        # .replica_info with its real partition_count
        if os.path.isdir(data_dir):
            for entry in sorted(os.listdir(data_dir)):
                parts = entry.split(".")
                if len(parts) == 2 and all(p.isdigit() for p in parts):
                    gpid = (int(parts[0]), int(parts[1]))
                    info_path = os.path.join(data_dir, entry, ".replica_info")
                    partition_count = 1
                    if os.path.exists(info_path):
                        import json
                        with open(info_path) as f:
                            partition_count = json.load(f)["partition_count"]
                    self._open_replica(gpid, partition_count)

    def close(self) -> None:
        for r in self.replicas.values():
            r.close()

    # ---- replica management -------------------------------------------

    def _replica_dir(self, gpid: Gpid) -> str:
        return os.path.join(self.data_dir, f"{gpid[0]}.{gpid[1]}")

    def _open_replica(self, gpid: Gpid, partition_count: int) -> Replica:
        r = self.replicas.get(gpid)
        if r is None:
            import json
            rdir = self._replica_dir(gpid)
            os.makedirs(rdir, exist_ok=True)
            info_path = os.path.join(rdir, ".replica_info")
            if not os.path.exists(info_path):
                with open(info_path, "w") as f:
                    json.dump({"app_id": gpid[0], "pidx": gpid[1],
                               "partition_count": partition_count}, f)
            r = Replica(self.name, rdir,
                        _GpidTransport(self.net, self.name, gpid),
                        app_id=gpid[0], pidx=gpid[1],
                        partition_count=partition_count, clock=self.clock)
            r.on_learn_completed = (
                lambda learner, g=gpid: self._notify_learn_completed(g, learner))
            r.on_replication_error = (
                lambda member, decree, g=gpid:
                self._notify_replication_error(g, member))
            self.replicas[gpid] = r
        return r

    def get_replica(self, gpid: Gpid) -> Optional[Replica]:
        return self.replicas.get(gpid)

    # ---- message routing ----------------------------------------------

    def on_message(self, src: str, msg_type: str, payload) -> None:
        if msg_type == "replica":
            gpid = tuple(payload["gpid"])
            r = self.replicas.get(gpid)
            if r is None and payload["type"] == "add_learner":
                # a learner replica is born from the add-learner flow
                # (parity: on_add_learner creates the potential secondary)
                r = self._open_replica(
                    gpid, payload["payload"].get("partition_count", 1))
            if r is not None:
                r.on_message(src, payload["type"], payload["payload"])
            return
        if msg_type == "config_proposal":
            self._on_config_proposal(src, payload)
            return
        if msg_type == "add_learner_cmd":
            self._on_add_learner_cmd(src, payload)
            return
        if msg_type == "update_app_envs":
            self._on_update_app_envs(src, payload)
            return
        if msg_type == "beacon_ack":
            self._last_beacon_ack = self.sim_clock()
            return
        if msg_type == "config_sync_reply":
            self._on_config_sync_reply(src, payload)
            return
        if msg_type == "client_write":
            self._on_client_write(src, payload)
            return
        if msg_type == "client_read":
            self._on_client_read(src, payload)
            return
        raise ValueError(f"stub {self.name}: unknown message {msg_type}")

    # ---- client request path (parity: replica_stub read/write dispatch,
    # replica_stub.cpp:1100 + replica.cpp:386 gates) -------------------

    def lease_valid(self) -> bool:
        """Worker-side self-fencing: a node whose FD lease lapsed must stop
        serving BEFORE meta's grace expires (failure_detector.h:79-121) —
        otherwise a partitioned primary would serve stale reads after its
        partition was reassigned."""
        from pegasus_tpu.meta.failure_detector import worker_lease_valid

        return worker_lease_valid(self._last_beacon_ack, self.sim_clock())

    def _on_client_write(self, src: str, payload: dict) -> None:
        from pegasus_tpu.replica.mutation import WriteOp
        from pegasus_tpu.replica.replica import PartitionStatus
        from pegasus_tpu.utils.errors import ErrorCode

        gpid = tuple(payload["gpid"])
        rid = payload["rid"]
        r = self.replicas.get(gpid)
        if (r is None or r.status != PartitionStatus.PRIMARY
                or not self.lease_valid()):
            self.net.send(self.name, src, "client_write_reply", {
                "rid": rid, "err": int(ErrorCode.ERR_INVALID_STATE),
                "results": []})
            return
        gate = r.server._hash_gate(payload.get("partition_hash"))
        if gate:
            self.net.send(self.name, src, "client_write_reply", {
                "rid": rid, "err": gate, "results": []})
            return
        ops = [WriteOp(op, req) for op, req in payload["ops"]]

        def reply(results) -> None:
            self.net.send(self.name, src, "client_write_reply", {
                "rid": rid, "err": int(ErrorCode.ERR_OK),
                "results": results})

        try:
            r.client_write(ops, reply)
        except (RuntimeError, ValueError):
            self.net.send(self.name, src, "client_write_reply", {
                "rid": rid, "err": int(ErrorCode.ERR_INVALID_STATE),
                "results": []})

    def _on_client_read(self, src: str, payload: dict) -> None:
        """Dispatch a read op to the partition's storage app through the
        replica gate (parity: replica_stub::on_client_read
        replica_stub.cpp:1100 -> replica::on_client_read replica.cpp:386 ->
        storage_serverlet dispatch, common/storage_serverlet.h:52).

        payload: {gpid, rid, op, args, partition_hash?}; the reply carries
        `err` (framework routing error space) and `result` (the storage
        handler's return value — storage status codes live inside it).
        """
        from pegasus_tpu.replica.replica import PartitionStatus
        from pegasus_tpu.utils.errors import ErrorCode

        gpid = tuple(payload["gpid"])
        rid = payload["rid"]
        op = payload.get("op", "get")
        r = self.replicas.get(gpid)
        if (r is None or r.status != PartitionStatus.PRIMARY
                or not self.lease_valid()):
            self.net.send(self.name, src, "client_read_reply", {
                "rid": rid, "err": int(ErrorCode.ERR_INVALID_STATE),
                "result": None})
            return
        ph = payload.get("partition_hash")
        args = payload.get("args")
        srv = r.server
        # split staleness gate for EVERY read op (scanner paging ops carry
        # ph=None — their context was validated at get_scanner time)
        gate = srv._hash_gate(ph)
        if gate:
            self.net.send(self.name, src, "client_read_reply", {
                "rid": rid, "err": gate, "result": None})
            return
        try:
            if op == "get":
                result = srv.on_get(args, partition_hash=ph)
            elif op == "ttl":
                result = srv.on_ttl(args, partition_hash=ph)
            elif op == "multi_get":
                result = srv.on_multi_get(args)
            elif op == "batch_get":
                result = srv.on_batch_get(args)
            elif op == "sortkey_count":
                result = srv.on_sortkey_count(args)
            elif op == "get_scanner":
                result = srv.on_get_scanner(args)
            elif op == "scan":
                result = srv.on_scan(args)
            elif op == "clear_scanner":
                result = srv.on_clear_scanner(args)
            else:
                self.net.send(self.name, src, "client_read_reply", {
                    "rid": rid,
                    "err": int(ErrorCode.ERR_HANDLER_NOT_FOUND),
                    "result": None})
                return
        except ValueError:
            # bad request arguments: permanent, NOT retryable — the client
            # must surface it, not burn retries refreshing its config
            self.net.send(self.name, src, "client_read_reply", {
                "rid": rid, "err": int(ErrorCode.ERR_INVALID_PARAMETERS),
                "result": None})
            return
        except RuntimeError:
            self.net.send(self.name, src, "client_read_reply", {
                "rid": rid, "err": int(ErrorCode.ERR_INVALID_STATE),
                "result": None})
            return
        self.net.send(self.name, src, "client_read_reply", {
            "rid": rid, "err": int(ErrorCode.ERR_OK), "result": result})

    def _on_config_proposal(self, src: str, payload: dict) -> None:
        """Meta assigns a configuration (parity: on_config_proposal,
        replica_stub.cpp:2487 -> replica_config.cpp)."""
        gpid = tuple(payload["gpid"])
        config = ReplicaConfig(payload["ballot"], payload["primary"],
                               list(payload["secondaries"]))
        r = self._open_replica(gpid, payload.get("partition_count", 1))
        r.assign_config(config)

    def _on_add_learner_cmd(self, src: str, payload: dict) -> None:
        """Meta tells the primary to pull in a learner (parity: config
        proposal ADD_SECONDARY -> primary starts the learn flow)."""
        gpid = tuple(payload["gpid"])
        r = self.replicas.get(gpid)
        if r is not None and r.status == PartitionStatus.PRIMARY:
            r.add_learner(payload["learner"])

    def _on_update_app_envs(self, src: str, payload: dict) -> None:
        """Meta propagates table envs (parity: config-sync env delivery)."""
        for gpid, r in self.replicas.items():
            if gpid[0] == payload["app_id"]:
                r.server.update_app_envs(payload["envs"])

    # ---- notifications to meta ----------------------------------------

    def _notify_learn_completed(self, gpid: Gpid, learner: str) -> None:
        if self.meta_addr is not None:
            self.net.send(self.name, self.meta_addr, "learn_completed", {
                "gpid": gpid, "learner": learner})

    def _notify_replication_error(self, gpid: Gpid, member: str) -> None:
        if self.meta_addr is not None:
            self.net.send(self.name, self.meta_addr, "replication_error", {
                "gpid": gpid, "member": member})

    # ---- config sync (parity: the pull-reconciliation protocol —
    # replica_stub.cpp:944-954 query_configuration_by_node,
    # idl/meta_admin.thrift:103-115 stored_replicas/gc_replicas,
    # meta/meta_service.cpp:793) ----------------------------------------

    def config_sync(self) -> None:
        """Timer: report stored replicas; meta replies with this node's
        authoritative configs plus replicas to garbage-collect. Pull-based
        reconciliation is how replicas converge after meta-side
        reconfiguration that happened while this node was unreachable."""
        if self.meta_addr is None:
            return
        stored = [{"gpid": gpid, "ballot": r.config.ballot,
                   "partition_count": r.server.partition_count}
                  for gpid, r in self.replicas.items()]
        self.net.send(self.name, self.meta_addr, "config_sync", {
            "node": self.name, "stored": stored})

    def _on_config_sync_reply(self, src: str, payload: dict) -> None:
        import shutil

        for entry in payload["configs"]:
            gpid = tuple(entry["gpid"])
            r = self._open_replica(gpid, entry["partition_count"])
            r.assign_config(ReplicaConfig(entry["ballot"], entry["primary"],
                                          list(entry["secondaries"])))
            if entry.get("envs"):
                r.server.update_app_envs(entry["envs"])
        for gpid in payload.get("gc", []):
            gpid = tuple(gpid)
            r = self.replicas.pop(gpid, None)
            if r is not None:
                r.close()
                shutil.rmtree(self._replica_dir(gpid), ignore_errors=True)

    # ---- failure detector (worker side) -------------------------------

    def send_beacon(self) -> None:
        """Parity: the FD beacon ping (failure_detector.h:79) — called on a
        timer by the owner/sim."""
        if self.meta_addr is not None:
            self.net.send(self.name, self.meta_addr, "beacon",
                          {"node": self.name})
