"""ReplicaStub: one replica-server node hosting many partition replicas.

Parity: src/replica/replica_stub.{h,cpp} — a node owns all its `Replica`
instances, routes gpid-addressed messages to them (the rDSN layer-2
interception, src/runtime/service_engine.cpp:163), creates replicas on
meta config proposals, reports its stored replicas in config-sync, and
runs the failure-detector client side (beacons to meta).

All inter-node traffic is enveloped as ("replica", {gpid, type, payload})
so one network address serves every partition on the node.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Optional, Tuple

from pegasus_tpu.replica.replica import (
    PartitionStatus,
    Replica,
    ReplicaBusyError,
    ReplicaConfig,
)
from pegasus_tpu.server import tenancy
from pegasus_tpu.server.tenancy import TENANTS
from pegasus_tpu.utils.errors import StorageCorruptionError

Gpid = Tuple[int, int]  # (app_id, partition_index)


class _GpidTransport:
    """Binds a replica's sends to its node + gpid envelope. Prepares
    and prepare acks divert into the node's write flush window while
    one is open, so a window's worth of per-partition 2PC traffic to
    one peer collapses into a single prepare_batch/prepare_batch_ack
    message (group_commit.WriteFlushWindow)."""

    def __init__(self, net, node_name: str, gpid: Gpid,
                 window=None) -> None:
        self._net = net
        self._node = node_name
        self._gpid = gpid
        self._window = window

    def send(self, _src: str, dst: str, msg_type: str, payload) -> None:
        if (self._window is not None
                and self._window.queue_replica_msg(
                    dst, msg_type, self._gpid, payload)):
            return
        self._net.send(self._node, dst, "replica", {
            "gpid": self._gpid, "type": msg_type, "payload": payload})


class ReplicaStub:
    def __init__(self, name: str, data_dir, net,
                 clock: Optional[Callable[[], float]] = None,
                 sim_clock: Optional[Callable[[], float]] = None,
                 cluster_id: int = 1) -> None:
        """`data_dir`: one path or a list of paths (multi-disk layout —
        parity: fs_manager dir_nodes; replicas place on the least-loaded
        disk)."""
        from pegasus_tpu.replica.fs_manager import FsManager

        self.name = name
        dirs = [data_dir] if isinstance(data_dir, str) else list(data_dir)
        self.fs = FsManager(dirs)
        self.data_dir = dirs[0]
        if os.environ.get("PEGASUS_ENCRYPT_AT_REST") == "1":
            # at-rest encryption (parity: FLAGS_encrypt_data_at_rest +
            # kms_key_provider): each data dir becomes an encryption
            # zone keyed by one per-server data key, wrapped by the
            # KMS root and stored beside the data it protects
            from pegasus_tpu.security.kms import (
                KeyProvider, LocalKmsClient, root_key_from_env)
            from pegasus_tpu.storage.efile import enable_encryption

            root = root_key_from_env()
            if root is None:
                # fail LOUDLY: a silent built-in fallback root would let
                # a cluster believe its disks are protected while the
                # key sits in the source tree
                raise RuntimeError(
                    "PEGASUS_ENCRYPT_AT_REST=1 requires PEGASUS_KMS_"
                    "ROOT_KEY (hex) or PEGASUS_KMS_ROOT_KEY_FILE")
            kms = LocalKmsClient(root)
            # ONE data key per server, shared by all its data dirs:
            # disk-migrate raw-copies files between dirs, which must
            # stay decryptable at the destination; the wrapped key is
            # replicated to every dir so no single disk is a key SPOF
            provider = KeyProvider.for_dirs(dirs, kms)
            for d in dirs:
                enable_encryption(d, provider)
            self._encryption_dirs = list(dirs)
        self.net = net
        self.clock = clock
        # FD timeline clock (sim time); defaults to the wall clock
        self.sim_clock = sim_clock or clock or (lambda: 0.0)
        self._start_clock = self.sim_clock()
        if sim_clock is not None:
            # the QoS governor's CU buckets must refill in VIRTUAL
            # seconds under sim — a compressed schedule burns hours of
            # virtual time in wall milliseconds, so wall-clocked
            # buckets would never refill. Same timebase threading as
            # scrub_tick/health_tick; the registry is process-global
            # (like METRICS) and sim nodes share one loop, so the last
            # node's clock is everyone's clock.
            TENANTS.set_clock(self.sim_clock)
        self.replicas: Dict[Gpid, Replica] = {}
        # the meta group (parity: failure_detector_multimaster — workers
        # beacon the whole group; only the leader acts, followers forward)
        self.meta_addrs: list = []
        self.meta_addr: Optional[str] = None
        # (gpid, dupid) -> ClusterDuplicator on this node's primaries
        self._dup_sessions: Dict = {}
        # this node's cluster identity (timetag cluster bits + the
        # duplication origin-echo filter); distinct per geo-replicated
        # cluster so master-master topologies don't ping-pong writes
        self.cluster_id = cluster_id
        # AIMD backpressure for dup catch-up shipping (all sessions on
        # this node share the WAN egress budget)
        from pegasus_tpu.replica.dup_governor import DupGovernor

        self.dup_governor = DupGovernor(name, clock=self.sim_clock)
        # long-op dedup: a meta tick re-sends commands until done arrives;
        # a second copy of an in-flight backup/ingest must be ignored
        self._backup_inflight: set = set()
        self._ingest_inflight: set = set()
        # parent gpid -> split session state (see _split_advance)
        self._split_sessions: Dict[Gpid, dict] = {}
        # remote-command verb registry (parity: command_manager.h:52)
        from pegasus_tpu.utils.command_manager import CommandManager

        self.commands = CommandManager()
        self._register_default_commands()
        # file-transfer service (parity: src/nfs/ — learning/migration
        # file copies between hosts); shared_fs=True means checkpoint
        # paths are locally reachable (onebox/sim) and transfers are
        # bypassed
        from pegasus_tpu.replica.file_transfer import TransferServer

        # cluster auth secret (None = auth disabled); parity:
        # security/negotiation + ranger table ACLs
        self.auth_secret: Optional[str] = None
        self._negotiation = None  # lazy NegotiationServer (needs secret)
        self.shared_fs = True
        self.transfer = TransferServer(net, name, self.fs.data_dirs)
        self._fetch_sessions: Dict = {}
        self._last_beacon_ack = float("-inf")
        # node-level write flush window: plog group commit (one shared
        # flush/fsync per dispatch window across every partition) +
        # prepare fan-out aggregation; metrics live on the node's
        # "write" entity next to the transport's read-shed counters
        from pegasus_tpu.replica.group_commit import WriteFlushWindow
        from pegasus_tpu.utils.metrics import METRICS

        self.write_metrics = METRICS.entity("write", name)
        self.write_window = WriteFlushWindow(net, name, self.write_metrics)
        # storage-integrity observability + the background scrubber
        # (parity: the disk-error/scrub counters the reference keeps on
        # its server entity; the scrub itself is this repo's analogue
        # of rocksdb background verification)
        from pegasus_tpu.storage.scrub import ReplicaScrubber

        storage_ent = METRICS.entity("storage", "node")
        self._quarantine_count = storage_ent.counter(
            "replica_quarantine_count")
        self._disk_io_errors = storage_ent.counter("disk_io_error_count")
        # split-fence observability: writes rejected ERR_SPLITTING while
        # a parent drains its tail (the hash-gate's misroute twin lives
        # on the same entity, incremented in PartitionServer._hash_gate)
        self._split_fence_rejects = storage_ent.counter(
            "split_fence_reject_count")
        # failover-drill fence observability: client writes rejected
        # typed ERR_DUP_FENCED while a table drains its duplication
        self._dup_fence_rejects = storage_ent.counter(
            "dup_fence_reject_count")
        # follower-read observability (per-table twins live on each
        # partition's "replica" entity): reads answered by a SECONDARY
        # under its beacon lease, reads bounced typed ERR_STALE_REPLICA,
        # and the subset of bounces caused by a lapsed lease
        self._follower_reads = storage_ent.counter("follower_read_count")
        self._stale_bounces = storage_ent.counter("stale_bounce_count")
        self._lease_rejects = storage_ent.counter(
            "read_lease_reject_count")
        self.scrubber = ReplicaScrubber(
            lambda: self.replicas, self._on_scrub_corruption,
            clock=self.sim_clock)
        # node-scoped foreground-pressure twins of the transport's
        # process-wide "rpc"/"dispatch" counters: the stub's own gates
        # (deadline fast-fail, injected shedding) count HERE, so sim
        # clusters sharing one process registry still attribute
        # pressure to the node that felt it
        self.node_rpc_metrics = METRICS.entity("rpc", name,
                                               {"node": name})
        self._node_read_shed = self.node_rpc_metrics.counter(
            "read_shed_count")
        self._node_deadline_expired = self.node_rpc_metrics.counter(
            "deadline_expired_count")
        self._beacon_age_gauge = self.node_rpc_metrics.gauge(
            "beacon_ack_age_s")
        # sustained-shed injection point for incident drills (the PR 2
        # chaos surface): `FAIL_POINTS.cfg("stub_read_shed:<node>", ...)`
        # makes THIS node's read gate shed with ERR_BUSY
        self._shed_fp_name = f"stub_read_shed:{name}"
        # chaos surface for lease-expiry fencing:
        # `FAIL_POINTS.cfg("fd::beacon_drop:<node>", ...)` drops THIS
        # node's outgoing FD beacons so a test can lapse one secondary's
        # read lease deterministically (seeded like every fail point)
        self._beacon_drop_fp_name = f"fd::beacon_drop:{name}"
        # flight recorder + health watchdog (utils/timeseries, utils/
        # health): fixed-cadence ring capture over this node's metric
        # entities, rules journaling typed events, digest riding
        # config-sync to the meta ClusterHealth machine
        from pegasus_tpu.utils.health import HealthEngine
        from pegasus_tpu.utils.timeseries import FlightRecorder

        self.recorder = FlightRecorder(
            name, clock=self.clock or self.sim_clock,
            owns=self._owns_entity)
        self.health = HealthEngine(name, self.recorder)
        net.register(name, self.on_message)
        batch_reg = getattr(net, "register_batch", None)
        if batch_reg is not None:
            # transport flush-window hook: a consecutive run of queued
            # client reads delivers as ONE batch, and its point ops
            # (get/ttl/multi_get(sort keys)/batch_get) serve through the
            # cross-partition read coordinator in one flush
            batch_reg(name, "client_read", self._on_client_read_batch)
            # and a consecutive run of queued client writes shares ONE
            # group-commit window (solo writes over TCP coalesce their
            # plog hardening + prepare fan-out without client changes)
            batch_reg(name, "client_write", self._on_client_write_window)
        # load existing replica dirs across every data dir (parity:
        # replica_stub boot scan, replica_stub.cpp:594 load_replicas per
        # disk); each dir carries a .replica_info with its partition_count
        for gpid, rdir in self.fs.scan_replicas().items():
            info_path = os.path.join(rdir, ".replica_info")
            partition_count = 1
            if os.path.exists(info_path):
                import json
                with open(info_path) as f:
                    partition_count = json.load(f)["partition_count"]
            try:
                self._open_replica(gpid, partition_count)
            except (StorageCorruptionError, OSError) as e:
                # a replica whose store fails its integrity checks at
                # boot must not take the whole node down: retire it to
                # trash and let the guardian re-learn it onto us (the
                # node will report it missing at the next config_sync)
                self._quarantine_count.increment()
                if isinstance(e, OSError):
                    self._disk_io_errors.increment()
                    self.fs.note_io_error(rdir, e)
                self.replicas.pop(gpid, None)
                try:
                    self.fs.trash_replica(gpid)
                except OSError:
                    pass

    def _register_default_commands(self) -> None:
        """The node's built-in control verbs (parity: the verbs replicas
        register with command_manager — slow-query dumps, replica info,
        metrics; invoked via shell remote_command, commands.h:111)."""
        from pegasus_tpu.replica.replica import PartitionStatus

        def slow_query_dump(args):
            clear = "clear" in args
            out = []
            for gpid, r in sorted(self.replicas.items()):
                # one shared log per replica; the name prefix tells the
                # request class apart
                for rep in r.server.slow_log.dump(clear=clear):
                    kind = ("write" if rep.get("name", "").startswith(
                        "write.") else "read")
                    out.append(dict(rep, gpid=list(gpid), kind=kind))
            return sorted(out, key=lambda d: -d.get("total_ms", 0))

        def replica_info(_args):
            return [{"gpid": list(gpid),
                     "status": PartitionStatus(r.status).name,
                     "ballot": r.config.ballot,
                     "last_committed": r.last_committed_decree,
                     "last_prepared": r.last_prepared_decree(),
                     "partition_count": r.server.partition_count}
                    for gpid, r in sorted(self.replicas.items())]

        def metrics_dump(args):
            from pegasus_tpu.utils.metrics import METRICS

            return METRICS.snapshot(args[0] if args else None)

        def flush_all(_args):
            n = 0
            for r in self.replicas.values():
                if r.server.engine.flush():
                    n += 1
            return f"flushed {n} replicas"

        self.commands.register(
            "slow-query-dump", slow_query_dump,
            "dump recent slow requests (arg 'clear' empties the ring)")
        self.commands.register(
            "replica.info", replica_info,
            "list hosted replicas with roles and decrees")
        self.commands.register("metrics", metrics_dump,
                               "metrics snapshot [entity_type]")
        self.commands.register("flush", flush_all,
                               "flush every hosted replica's memtable")

        def task_profiler(args):
            from pegasus_tpu.utils.profiler import PROFILER

            return PROFILER.control(args)

        self.commands.register(
            "task-profiler", task_profiler,
            "per-task-code profiler toollet: enable|disable|clear|dump "
            "(queue/exec latency + qps per message type)")

        def trace_dump(args):
            # the cross-node stitch's fan-out target: this node's span
            # ring (+ tail-kept traces), optionally one trace only
            from pegasus_tpu.utils import tracing

            return tracing.ring_for(self.name).dump(
                args[0] if args else None)

        def trace_list(args):
            from pegasus_tpu.utils import tracing

            limit = int(args[0]) if args else 16
            return tracing.ring_for(self.name).slow_roots(limit)

        self.commands.register(
            "trace-dump", trace_dump,
            "dump this node's spans (arg: one trace id) for stitching")
        self.commands.register(
            "trace-list", trace_list,
            "list this node's tail-kept slow trace roots [limit]")

        def fs_stats(_args):
            return self.fs.stats()

        def clean_trash(args):
            age = float(args[0]) if args else 86400.0
            return self.fs.clean_trash(age)

        def migrate(args):
            import os as _os

            app_id, pidx, dest = int(args[0]), int(args[1]), args[2]
            gpid = (app_id, pidx)
            # validate EVERYTHING before taking the replica down — a bad
            # destination must not leave the partition unserved
            if _os.path.abspath(dest) not in self.fs.data_dirs:
                raise ValueError(f"{dest} is not a managed data dir")
            r = self.replicas.get(gpid)
            if r is None:
                raise ValueError(f"replica {gpid} not hosted here")
            count = r.server.partition_count
            del self.replicas[gpid]
            r.close()
            try:
                new_dir = self.fs.migrate(gpid, dest)
            finally:
                # reopen from wherever the replica now lives — even a
                # failed copy leaves the source intact
                self._open_replica(gpid, count)
            return new_dir

        self.commands.register("fs.stats", fs_stats,
                               "per-data-dir replicas + usage")
        self.commands.register("fs.clean-trash", clean_trash,
                               "remove trashed replica dirs older than "
                               "[seconds]")
        self.commands.register(
            "replica.migrate", migrate,
            "replica.migrate <app_id> <pidx> <dest_data_dir>")

        def hotkey(args):
            """hotkey <start|query|stop> <app_id> <pidx> <read|write>
            (parity: on_detect_hotkey, pegasus_server_impl.h:470)."""
            action, app_id, pidx, kind = (args[0], int(args[1]),
                                          int(args[2]), args[3])
            r = self.replicas.get((app_id, pidx))
            if r is None:
                raise ValueError(f"replica {(app_id, pidx)} not here")
            hc = r.server.hotkey_collectors[kind]
            if action == "start":
                hc.start()
                return "started"
            if action == "stop":
                hc.stop()
                return "stopped"
            result = hc.result
            return {"state": hc.state.value,
                    "hot_key": result.decode(errors="replace")
                    if result else None}

        self.commands.register(
            "hotkey", hotkey,
            "hotkey <start|query|stop> <app_id> <pidx> <read|write>")

        def server_info(_args):
            """Parity: shell server_info / server_stat basics."""
            import pegasus_tpu

            by_status = {}
            for r in self.replicas.values():
                s = PartitionStatus(r.status).name
                by_status[s] = by_status.get(s, 0) + 1
            return {"node": self.name,
                    "version": pegasus_tpu.__version__,
                    "uptime_s": round(self.sim_clock()
                                      - self._start_clock, 1),
                    "replica_count": len(self.replicas),
                    "by_status": by_status}

        def replica_disk(_args):
            """Per-replica on-disk footprint (parity: shell app_disk —
            sst + plog bytes per hosted replica)."""
            def size_of(path):
                try:
                    return os.path.getsize(path)
                except OSError:
                    return 0  # compaction/gc raced the stat — skip

            out = []
            for gpid, r in sorted(self.replicas.items()):
                d = r.server.engine.data_dir
                sst = os.path.join(d, "sst")
                try:
                    names = os.listdir(sst)
                except OSError:
                    names = []
                sst_bytes = sum(size_of(os.path.join(sst, f))
                                for f in names)
                log_bytes = size_of(r.log.path)
                out.append({"gpid": list(gpid),
                            "status": PartitionStatus(r.status).name,
                            "sst_bytes": sst_bytes,
                            "log_bytes": log_bytes,
                            "dir": d})
            return out

        self.commands.register("server.info", server_info,
                               "node version/uptime/replica summary")
        self.commands.register("replica.disk", replica_disk,
                               "per-replica sst+plog bytes")

        def fs_health(_args):
            """Per-dir health state + error counts (parity: the
            fs_manager disk_status surface shell query_disk_info
            reads)."""
            return self.fs.health()

        def replica_scrub(args):
            """replica.scrub [app_id|status [app_id]] — no args / an
            app_id triggers a full synchronous scrub of the hosted
            replicas (of that table) and returns per-partition results;
            'status' reports the paced background scrubber's progress
            + last results without triggering anything."""
            if args and args[0] == "status":
                app_id = int(args[1]) if len(args) > 1 else None
                return self.scrubber.status(app_id)
            app_id = int(args[0]) if args else None
            for gpid, r in sorted(list(self.replicas.items())):
                if app_id is not None and gpid[0] != app_id:
                    continue
                if self.replicas.get(gpid) is r:  # not quarantined yet
                    self.scrubber.scrub_now(gpid, r)
            return self.scrubber.status(app_id)

        self.commands.register("fs.health", fs_health,
                               "per-data-dir health + io error counts")
        self.commands.register(
            "replica.scrub", replica_scrub,
            "replica.scrub [app_id | status [app_id]] — trigger a full "
            "scrub / report scrub progress+results")

        def dup_stats(_args):
            """Per-duplication shipping stats on this node (scraped by
            tools/collector.py and the shell's dup_stats verb): lag,
            inflight decree, fail_mode, shipped bytes, last error —
            plus the node governor's throttle state."""
            return {
                "node": self.name,
                "sessions": [s.stats()
                             for s in self._dup_sessions.values()],
                "governor": self.dup_governor.status(),
            }

        self.commands.register("dup.stats", dup_stats,
                               "per-duplication lag/shipping stats + "
                               "governor state")

        def fault_set(args):
            """fault.set <drop|delay> <value> [src] [dst] — live-adjust
            this node's chaos plan (installs one if absent). The WAN
            scale harness uses it to black out / heal the inter-cluster
            link mid-run without restarting nodes."""
            kind, value = args[0], float(args[1])
            src = args[2] if len(args) > 2 and args[2] else None
            dst = args[3] if len(args) > 3 and args[3] else None
            plan = getattr(self.net, "fault_plan", None)
            if plan is None:
                install = getattr(self.net, "install_fault_plan", None)
                if install is not None:
                    from pegasus_tpu.rpc.fault import FaultPlan

                    plan = FaultPlan()
                    install(plan)
            target = plan if plan is not None else self.net
            fn = getattr(target, f"set_{kind}", None)
            if fn is None:
                raise ValueError(f"no fault surface for {kind!r}")
            fn(value, src, dst)
            return "ok"

        self.commands.register(
            "fault.set", fault_set,
            "fault.set <drop|delay|duplicate> <value> [src] [dst] — "
            "live chaos-plan adjustment")

        def timeseries_dump(args):
            """timeseries-dump [entity_type [entity_id [metric
            [window_s]]]] — this node's flight-recorder ring slices
            ('' wildcards a position); the `shell timeline` fan-out
            target."""
            sel = [a if a else None for a in args[:3]]
            sel += [None] * (3 - len(sel))
            window = float(args[3]) if len(args) > 3 and args[3] else None
            return self.recorder.dump(sel[0], sel[1], sel[2], window)

        def health_status(_args):
            return self.health.status()

        def health_events(args):
            limit = int(args[0]) if args else 64
            entity_id = args[1] if len(args) > 1 and args[1] else None
            return self.health.events(limit, entity_id)

        def placement(args):
            """placement [workload [batch_bytes [n_windows]]] — the
            quantified pays/doesn't-pay offload verdict
            (ops/placement.py offload_breakdown) plus the live
            cost-model drift audit, operator-visible instead of
            PERF.md-only. The `mesh` block is the resident SPMD
            serving layer: verdict share, tunnel health, watchdog
            state. The breakdown's `compact` block is the compaction
            FILTER stage's mesh-vs-host verdict (drift class
            `mesh_compact`); pass n_windows to model a specific
            pipeline geometry instead of the default."""
            from pegasus_tpu.ops.placement import (
                compact_breakdown,
                offload_breakdown,
            )
            from pegasus_tpu.parallel.mesh_resident import MESH_SERVING
            from pegasus_tpu.server.workload import DRIFT

            workload = args[0] if args else "rules"
            batch_bytes = int(args[1]) if len(args) > 1 else 1 << 20
            bd = offload_breakdown(workload, batch_bytes)
            if len(args) > 2 and args[2]:
                bd["compact"] = compact_breakdown(
                    batch_bytes, n_windows=int(args[2]))
            return {"breakdown": bd,
                    "drift": DRIFT.status(),
                    "mesh": MESH_SERVING.status()}

        self.commands.register(
            "placement", placement,
            "offload pays/doesn't-pay verdict + cost-model drift "
            "[workload [batch_bytes]]")

        def workload_stats(args):
            """Per-hosted-replica workload shape summaries + the node
            cost-model drift (shell `workload` wire-mode fan-out)."""
            from pegasus_tpu.replica.replica import PartitionStatus
            from pegasus_tpu.server.workload import DRIFT

            app_id = int(args[0]) if args else None
            rows = []
            for gpid, r in sorted(self.replicas.items()):
                if app_id is not None and gpid[0] != app_id:
                    continue
                if r.status != PartitionStatus.PRIMARY:
                    continue
                rows.append(dict(r.server.workload.summary(),
                                 gpid=list(gpid)))
            return {"node": self.name, "partitions": rows,
                    "drift": DRIFT.status()}

        self.commands.register(
            "workload.stats", workload_stats,
            "per-replica workload shape stats + drift [app_id]")

        def perf_explain(args):
            """perf.explain <json-spec> — run one captured op on a
            hosted PRIMARY and return the explain report.
            spec: {app_id, op, hash_key, sort_key?|sort_keys?,
            batch_size?} (keys utf-8)."""
            import json as _json

            from pegasus_tpu.base.key_schema import key_hash_parts
            from pegasus_tpu.replica.replica import PartitionStatus
            from pegasus_tpu.server.explain import explain_op, op_from_spec

            spec = _json.loads(args[0])
            app_id = int(spec["app_id"])
            hk = spec.get("hash_key", "").encode()
            candidates = [
                (gpid, r) for gpid, r in sorted(self.replicas.items())
                if gpid[0] == app_id
                and r.status == PartitionStatus.PRIMARY]
            if not candidates:
                raise ValueError(f"no primary of app {app_id} here")
            if hk:
                want = (key_hash_parts(hk, b"")
                        % candidates[0][1].server.partition_count)
                owned = [(g, r) for g, r in candidates if g[1] == want]
                if not owned:
                    raise ValueError(
                        f"partition {want} of app {app_id} not here")
                _gpid, r = owned[0]
            else:
                _gpid, r = candidates[0]
            op, op_args, ph = op_from_spec(spec)
            return explain_op(r.server, op, op_args, partition_hash=ph)

        self.commands.register(
            "perf.explain", perf_explain,
            "run one captured op with a forced PerfContext and return "
            "the explain report (json spec)")

        self.commands.register(
            "timeseries-dump", timeseries_dump,
            "flight-recorder ring slices [entity_type [entity_id "
            "[metric [window_s]]]]")
        self.commands.register(
            "health.status", health_status,
            "this node's watchdog verdict: status + firing rules + "
            "ring memory")
        self.commands.register(
            "health.events", health_events,
            "this node's health-event journal [limit [entity_id]]")

        def qos_tenants(_args):
            """Per-tenant QoS governor snapshot: weight, CU budget +
            bucket level, consumed CU, shed/over-budget counts, and
            whether the brownout gate is holding this tenant (shell
            `tenants` + the collector's _tenants row read this)."""
            return TENANTS.snapshot()

        self.commands.register(
            "qos.tenants", qos_tenants,
            "per-tenant QoS snapshot: weights, CU budgets/levels, "
            "shed + over-budget counts, brownout state")

    def close(self) -> None:
        # release outstanding capture pins: a node closing mid-incident
        # must not leave the process's trace/profiler settings raised
        self.health.close()
        for r in self.replicas.values():
            r.close()
        if getattr(self, "_encryption_dirs", None):
            from pegasus_tpu.storage.efile import disable_encryption

            for d in self._encryption_dirs:
                disable_encryption(d)

    # ---- replica management -------------------------------------------

    def _replica_dir(self, gpid: Gpid) -> str:
        return self.fs.replica_dir(gpid)

    def _open_replica(self, gpid: Gpid, partition_count: int) -> Replica:
        r = self.replicas.get(gpid)
        if r is None:
            import json
            rdir = self._replica_dir(gpid)
            os.makedirs(rdir, exist_ok=True)
            info_path = os.path.join(rdir, ".replica_info")
            if not os.path.exists(info_path):
                with open(info_path, "w") as f:
                    json.dump({"app_id": gpid[0], "pidx": gpid[1],
                               "partition_count": partition_count}, f)
            r = Replica(self.name, rdir,
                        _GpidTransport(self.net, self.name, gpid,
                                       self.write_window),
                        app_id=gpid[0], pidx=gpid[1],
                        partition_count=partition_count, clock=self.clock,
                        cluster_id=self.cluster_id)
            r.plog_sink = self.write_window
            r.write_metrics = self.write_metrics
            if self.sim_clock is not None:
                # range-read time budgets must burn VIRTUAL seconds
                # under sim (read_limiter.py), same threading as
                # scrub_tick/health_tick
                sc = self.sim_clock
                r.server.clock_ns = lambda: int(sc() * 1e9)
            r.on_learn_completed = (
                lambda learner, g=gpid: self._notify_learn_completed(g, learner))
            r.on_replication_error = (
                lambda member, decree, g=gpid:
                self._notify_replication_error(g, member))
            r.shared_fs = self.shared_fs
            r.on_remote_checkpoint = (
                lambda src, payload, g=gpid:
                self._start_ckpt_fetch(g, src, payload))
            self.replicas[gpid] = r
        return r

    def get_replica(self, gpid: Gpid) -> Optional[Replica]:
        return self.replicas.get(gpid)

    # ---- storage integrity: detect -> quarantine -> repair via re-learn
    # (parity: the reference's disk-error handling —
    # replica::handle_local_failure marks the replica PS_ERROR, the
    # stub's disk monitor flags the dir, and the partition guardian
    # re-replicates; the repair channel is the learner flow) -----------

    def scrub_tick(self) -> None:
        """Timer: one paced scrub advance (storage/scrub.py). Corrupt
        blocks found here quarantine their replica exactly like a
        corrupt client read would."""
        self.scrubber.tick()

    # ---- flight recorder + health watchdog ----------------------------

    def _owns_entity(self, ent) -> bool:
        """Which registry entities this node's recorder captures. In a
        real deployment the process IS the node, but in-process sim
        clusters share ONE registry, so ownership must be explicit:
        this node's named entities, the per-process singletons (which
        are node-local once deployed), the replicas it hosts, and its
        duplication sessions."""
        et, ei = ent.entity_type, ent.entity_id
        if ei == self.name:
            return True  # write / tracing / rpc:<node> / dup governor
        if (et, ei) in (("rpc", "dispatch"), ("storage", "node"),
                        ("workload", "node")):
            # KNOWN sim artifact: these singletons are shared by every
            # in-process stub, so one node's scrub/quarantine signal
            # fires the rule on ALL sim nodes (and meta folds them all
            # as degraded). Deployed, process == node and attribution
            # is exact; node-attributable signals use the per-node rpc
            # twins above instead. ("workload", "node") carries the
            # cost-model drift gauge — per-process like the placement
            # probe it audits.
            return True
        if et == "task":
            return True  # profiler codes (process == node deployed)
        if et == "tenant":
            # QoS tenant series (server/tenancy.py) — process-global
            # like the singletons above (same sim-sharing caveat);
            # deployed, each node journals its own tenants' burn
            return True
        if et in ("replica", "workload"):
            # per-partition entities share the replica id shape
            # (app.pidx): owned when this node hosts the partition
            try:
                a, p = ei.split(".")
                return (int(a), int(p)) in self.replicas
            except ValueError:
                return False
        if et == "duplication":
            return ent.attrs.get("node") == self.name
        return False

    def health_tick(self) -> None:
        """Timer: one flight-recorder pass + one watchdog evaluation.
        The WHOLE body coalesces to the recorder cadence (the timer may
        fire far faster — sim schedules compress hours of virtual time
        into milliseconds, so per-call work here must be one clock
        read on the off-cadence path). Firing rules auto-pin deeper
        capture (trace sample ratio + profiler) until clear."""
        from pegasus_tpu.utils.profiler import PROFILER

        if not self.recorder.due():
            return
        now = self.sim_clock()
        self.beacon_ack_age()
        if PROFILER.enabled and (
                now - getattr(self, "_profiler_published_at", -1e18)
                >= 30.0):
            # keep the per-code "task" entities fresh so the recorder
            # rings (and Prometheus scrapes) see profiler stats — on
            # its OWN slower cadence: a publish re-reads every per-code
            # window, and paying that on every recorder tick made
            # compressed sim schedules (hours of virtual time) crawl
            self._profiler_published_at = now
            PROFILER.publish()
        # decay the cost-model drift gauge: a class whose kernel waves
        # stopped must age out instead of pinning the rule firing
        from pegasus_tpu.server.workload import DRIFT

        DRIFT.refresh()
        # publish each tenant's cu_ratio (consumption vs budget) so the
        # recorder ring the tenant_brownout burn-rate rule reads is
        # fresh at every evaluation
        TENANTS.refresh()
        if self.recorder.tick() is not None:
            for ev in self.health.evaluate():
                if ev.rule == "tenant_brownout":
                    # aggressor-only brownout: the rule fires per
                    # TENANT entity, so only the outlier tenant's
                    # reads start shedding — everyone else is served
                    TENANTS.set_brownout(ev.entity[1], ev.firing)

    def _on_scrub_corruption(self, gpid: Gpid, exc: Exception) -> None:
        self._on_storage_error(gpid, exc)

    def _replica_for_path(self, path: str) -> Optional[Gpid]:
        """Map a corrupt file path to the replica whose store owns it
        (batched reads span partitions; the exception names the file)."""
        p = os.path.abspath(path)
        for gpid, r in self.replicas.items():
            d = os.path.abspath(r.data_dir)
            if p == d or p.startswith(d + os.sep):
                return gpid
        return None

    def _on_storage_error(self, gpid: Optional[Gpid], exc: Exception) -> int:
        """One storage failure -> typed error code + disk-health note +
        replica quarantine. Returns the ErrorCode int the RPC reply
        should carry."""
        from pegasus_tpu.utils.errors import ErrorCode

        if isinstance(exc, StorageCorruptionError):
            code = int(ErrorCode.ERR_CHECKSUM_FAILED)
            if gpid is None:
                gpid = self._replica_for_path(exc.path)
        else:  # OSError: the disk itself is failing, mark its dir sick
            code = int(ErrorCode.ERR_DISK_IO_ERROR)
            self._disk_io_errors.increment()
            path = getattr(exc, "filename", None)
            if path is None and gpid is not None:
                r = self.replicas.get(gpid)
                if r is not None:
                    path = r.data_dir
            if path is not None:
                self.fs.note_io_error(path, exc)
        if gpid is not None:
            self._quarantine_replica(gpid, repr(exc))
        return code

    def _quarantine_replica(self, gpid: Gpid, reason: str) -> None:
        """Self-quarantine: stop serving, retire the sick store to
        trash (the boot scan ignores trash, so these bytes can never be
        reopened), drop the node caches that could still hold pre-
        corruption rows, and report to the partition guardian — which
        removes us from the membership and tops the partition back up
        by re-learning a fresh replica from a healthy peer (possibly
        onto this same node, on a healthy dir)."""
        r = self.replicas.pop(gpid, None)
        if r is None:
            return  # already quarantined (scrub + read raced)
        self._quarantine_count.increment()
        # quarantine firing mid-split: a session touching this replica
        # cannot outlive its store
        import shutil as _shutil

        sess = self._split_sessions.pop(gpid, None)
        if sess is not None:
            # the PARENT quarantined: abandon the session and reap the
            # half-built child (meta demotes us and re-drives the split
            # at the promoted primary, which re-spawns the child)
            child = self.replicas.pop(sess["child_gpid"], None)
            if child is not None:
                child.close()
            _shutil.rmtree(self._replica_dir(sess["child_gpid"]),
                           ignore_errors=True)
            # the child may already be REGISTERED at meta (session in
            # the register phase) with its config pointing at this
            # node: report it corrupted too, so meta unregisters it and
            # the re-driven split re-spawns it — otherwise the count
            # would flip onto a phantom child whose replica was just
            # reaped here (unregistered children make this a no-op)
            for meta in self._meta_targets():
                self.net.send(self.name, meta, "replica_corrupted", {
                    "gpid": sess["child_gpid"], "node": self.name,
                    "reason": reason})
        for parent_gpid, psess in self._split_sessions.items():
            if psess["child_gpid"] == gpid:
                # the half-built CHILD quarantined (its store is
                # trashed): restart the session from a fresh checkpoint
                # — resuming drain/register would replay the tail into
                # (or register) a child whose base bytes are gone
                psess["phase"] = "ckpt"
                parent = self.replicas.get(parent_gpid)
                if parent is not None:
                    parent.splitting = False  # re-fenced at drain
                break
        # no stale pre-repair bytes may serve: the node row cache drops
        # this partition NOW (install_engine/_on_store_publish re-cover
        # this when the re-learned engine installs, but the window
        # between quarantine and repair must be closed too)
        from pegasus_tpu.server.row_cache import ROW_CACHE

        ROW_CACHE.invalidate_gid(gpid)
        r.status = PartitionStatus.ERROR
        try:
            r.close()
        except (OSError, RuntimeError, ValueError):
            pass  # the store is already known-bad; closing is best-effort
        try:
            self.fs.trash_replica(gpid)
        except OSError:
            pass
        # an in-flight checkpoint fetch must die with the replica
        sess = self._fetch_sessions.pop(gpid, None)
        if sess is not None:
            sess._finished = True
        for meta in self._meta_targets():
            self.net.send(self.name, meta, "replica_corrupted", {
                "gpid": gpid, "node": self.name, "reason": reason})

    # ---- message routing ----------------------------------------------

    def on_message(self, src: str, msg_type: str, payload) -> None:
        # every dispatch runs inside the node's write flush window:
        # plog appends it causes stage under one shared flush/fsync and
        # its prepare/ack fan-out aggregates per peer, all released
        # when the (outermost) window closes
        with self.write_window:
            self._dispatch_message(src, msg_type, payload)

    def _on_client_write_window(self, items) -> None:
        """Transport flush-window delivery for writes: a consecutive
        run of queued client_write messages shares ONE group-commit
        window — one plog flush/fsync and one prepare_batch per peer
        for the whole run. Each message keeps its own dispatch span
        parented to its own carried context (the transport's batch
        drain skips the generic per-message join point)."""
        from pegasus_tpu.utils import tracing

        with self.write_window:
            for src, payload in items:
                span = tracing.start_server_span(
                    self.name, "client_write", payload.get("trace"))
                try:
                    with tracing.activate(span):
                        self._on_client_write(src, payload)
                finally:
                    if span is not None:
                        span.finish()

    def _dispatch_message(self, src: str, msg_type: str, payload) -> None:
        if msg_type == "replica":
            gpid = tuple(payload["gpid"])
            r = self.replicas.get(gpid)
            if r is None and payload["type"] == "add_learner":
                # a learner replica is born from the add-learner flow
                # (parity: on_add_learner creates the potential secondary)
                r = self._open_replica(
                    gpid, payload["payload"].get("partition_count", 1))
            if r is not None:
                try:
                    r.on_message(src, payload["type"], payload["payload"])
                except (StorageCorruptionError, OSError) as e:
                    # a SECONDARY can trip corruption too (apply-path
                    # compaction re-reads blocks, learning copies
                    # files): quarantine instead of killing the
                    # dispatcher — the primary sees the missing ack and
                    # the guardian repairs via re-learn
                    self._on_storage_error(gpid, e)
            return
        if msg_type in ("prepare_batch", "prepare_batch_ack"):
            # aggregated 2PC fan-out (group_commit): one message carries
            # (gpid, payload, trace-ctx) items for many partitions;
            # items route in order to each partition's solo handler, and
            # our own acks re-aggregate under the already-open flush
            # window. Tracing: every batched item keeps its OWN span
            # parented to its own hop context — N legs in one carrier
            # yield N spans, never N carriers
            from pegasus_tpu.utils import tracing

            kind = ("prepare" if msg_type == "prepare_batch"
                    else "prepare_ack")
            for entry in payload["items"]:
                gpid, item = entry[0], entry[1]
                ctx = entry[2] if len(entry) > 2 else None
                leg_tenant = entry[3] if len(entry) > 3 else None
                r = self.replicas.get(tuple(gpid))
                if r is None:
                    continue
                span = None
                if ctx is not None:
                    if kind == "prepare_ack":
                        tracing.on_inbound_ctx(self.name, ctx)
                    else:
                        span = tracing.start_server_span(
                            self.name, f"replica.{kind}", ctx)
                        if span is not None and leg_tenant:
                            span.tags["tenant"] = leg_tenant
                try:
                    with tracing.activate(span):
                        r.on_message(src, kind, item)
                except (StorageCorruptionError, OSError) as e:
                    self._on_storage_error(tuple(gpid), e)
                finally:
                    if span is not None:
                        span.finish()
            return
        if msg_type == "negotiate":
            # SASL-style connection auth handshake (negotiation.h:37).
            # The identity binds to the CONNECTION session id, never to
            # the frame's self-reported src (any TCP peer could forge
            # that name); identities die with their connection.
            from pegasus_tpu.security.negotiation import (
                NegotiationServer,
            )

            if not self.auth_secret:
                reply = {"stage": "fail", "reason": "auth disabled",
                         "rid": payload.get("rid")}
            else:
                if self._negotiation is None:
                    self._negotiation = NegotiationServer(
                        self.auth_secret)
                    closed = getattr(self.net, "on_session_closed",
                                     None)
                    if closed is not None:
                        closed(self._negotiation.forget_session)
                reply = self._negotiation.on_message(
                    self._peer_key(src), payload)
            self.net.send(self.name, src, "negotiate_reply", reply)
            return
        if msg_type == "config_proposal":
            self._on_config_proposal(src, payload)
            return
        if msg_type == "add_learner_cmd":
            self._on_add_learner_cmd(src, payload)
            return
        if msg_type == "update_app_envs":
            self._on_update_app_envs(src, payload)
            return
        if msg_type == "beacon_ack":
            self._last_beacon_ack = self.sim_clock()
            # ONLY the meta leader acks beacons, so the acker identifies
            # the current leader — route direct notifications
            # (learn_completed / replication_error) there, or they'd
            # keep going to a dead ex-leader after a meta failover
            self.meta_addr = src
            return
        if msg_type == "config_sync_reply":
            self._on_config_sync_reply(src, payload)
            return
        if msg_type == "backup_partition":
            self._on_backup_partition(src, payload)
            return
        if msg_type == "restore_partition":
            self._on_restore_partition(src, payload)
            return
        if msg_type == "trigger_ingest":
            self._on_trigger_ingest(src, payload)
            return
        if msg_type == "start_split":
            self._on_start_split(src, payload)
            return
        if msg_type == "detect_hotkey":
            # the elasticity controller's detect command (parity:
            # on_detect_hotkey): start both collectors on the flagged
            # partition; results flow back on the config_sync report
            gpid = tuple(payload["gpid"])
            r = self.replicas.get(gpid)
            # primaries only: client reads/writes flow through the
            # primary, so a collector started on a just-demoted node
            # would sample nothing and never finish
            if r is not None and r.status == PartitionStatus.PRIMARY:
                for hc in r.server.hotkey_collectors.values():
                    if hc.state.value in ("stopped", "finished"):
                        hc.start()
            return
        if msg_type == "dup_add":
            self._on_dup_add(src, payload)
            return
        if msg_type == "dup_remove":
            gpid = tuple(payload["gpid"])
            dup = self._dup_sessions.pop((gpid, payload["dupid"]), None)
            if dup is not None:
                r = self.replicas.get(gpid)
                if r is not None and dup in r.duplicators:
                    # unhook or the log-GC floor stays pinned forever
                    r.duplicators.remove(dup)
            return
        if msg_type == "dup_apply_batch":
            self._on_dup_apply_batch(src, payload)
            return
        if msg_type == "dup_apply_batch_ack":
            # acks to duplication envelopes this node shipped
            for dup in self._dup_sessions.values():
                if dup.on_write_reply(payload):
                    dup.tick()
                    return
            return
        if msg_type == "query_config_reply":
            for dup in self._dup_sessions.values():
                if dup.on_follower_config(payload):
                    dup.tick()
                    return
            return
        if msg_type == "client_write_reply":
            # replies to duplication-shipped writes come back to the node
            for dup in self._dup_sessions.values():
                if dup.on_write_reply(payload):
                    dup.tick()
                    return
            return
        if msg_type == "list_dir":
            self.transfer.on_list_dir(src, payload)
            return
        if msg_type == "fetch_chunk":
            self.transfer.on_fetch_chunk(src, payload)
            return
        if msg_type in ("list_dir_reply", "fetch_chunk_reply"):
            for sess in list(self._fetch_sessions.values()):
                if sess.on_reply(msg_type, payload):
                    return
            return
        if msg_type == "remote_command":
            from pegasus_tpu.utils.errors import ErrorCode

            rid = payload.get("rid")
            try:
                result = self.commands.call(payload["cmd"],
                                            payload.get("args") or [])
                err = 0
            except (KeyError, ValueError, TypeError) as e:
                result = str(e)
                err = int(ErrorCode.ERR_HANDLER_NOT_FOUND)
            self.net.send(self.name, src, "remote_command_reply", {
                "rid": rid, "err": err, "result": result})
            return
        if msg_type == "client_scan_multi":
            self._on_client_scan_multi(src, payload)
            return
        if msg_type == "client_read_batch":
            self._on_client_read_batch_rpc(src, payload)
            return
        if msg_type == "client_write_batch":
            self._on_client_write_batch(src, payload)
            return
        if msg_type == "client_write":
            self._on_client_write(src, payload)
            return
        if msg_type == "client_read":
            self._on_client_read(src, payload)
            return
        raise ValueError(f"stub {self.name}: unknown message {msg_type}")

    # ---- client request path (parity: replica_stub read/write dispatch,
    # replica_stub.cpp:1100 + replica.cpp:386 gates) -------------------

    def lease_valid(self) -> bool:
        """Worker-side self-fencing: a node whose FD lease lapsed must stop
        serving BEFORE meta's grace expires (failure_detector.h:79-121) —
        otherwise a partitioned primary would serve stale reads after its
        partition was reassigned. Follower reads lean on the SAME lease:
        it is what bounds how long a partitioned secondary can keep
        answering after the world moved on."""
        from pegasus_tpu.meta.failure_detector import worker_lease_valid

        return worker_lease_valid(self._last_beacon_ack, self.sim_clock())

    def beacon_ack_age(self) -> float:
        """Seconds since the last beacon ack, on the node's sim clock —
        the ONE number both the lease check and the `fd_beacon_miss`
        health rule consume. Stamped onto the `beacon_ack_age_s` gauge
        at every call (the recorder-cadence health_tick AND the
        replica-side lease decisions), so an incident timeline shows the
        age a read-lease rejection actually read, not a snapshot from up
        to a recorder period earlier."""
        # before the first ack the node is still joining — 0, not inf
        age = (0.0 if self._last_beacon_ack == float("-inf")
               else max(0.0, self.sim_clock() - self._last_beacon_ack))
        self._beacon_age_gauge.set(round(age, 3))
        return age

    def _deadline_expired(self, payload: dict) -> bool:
        """True when the request's end-to-end deadline already passed on
        this node's clock (the client stamps the same timebase: wall
        time over TCP, the epoch-anchored virtual clock in sim)."""
        dl = payload.get("deadline")
        return (dl is not None and self.clock is not None
                and self.clock() > dl)

    def _on_client_write(self, src: str, payload: dict) -> None:
        from pegasus_tpu.replica.mutation import WriteOp
        from pegasus_tpu.replica.replica import PartitionStatus
        from pegasus_tpu.utils.errors import ErrorCode

        gpid = tuple(payload["gpid"])
        rid = payload["rid"]
        if self._deadline_expired(payload):
            # fast-fail BEFORE the 2PC starts: an expired write has not
            # (and will not) run, so the explicit ERR_TIMEOUT reply is
            # unambiguous — safe to retry even for atomic ops
            self.net.send(self.name, src, "client_write_reply", {
                "rid": rid, "err": int(ErrorCode.ERR_TIMEOUT),
                "results": []})
            return
        r = self.replicas.get(gpid)
        if not self._client_allowed(r, payload, access="w", src=src):
            self.net.send(self.name, src, "client_write_reply", {
                "rid": rid, "err": int(ErrorCode.ERR_ACL_DENY),
                "results": []})
            return
        # CU budget gate (writes are NEVER brownout-shed — the mutation
        # path degrades last — but an over-budget tenant's writes do
        # bounce typed-retryable until refill pays the debt down)
        over = TENANTS.admit(payload.get("tenant"), kind="write")
        if over:
            self.net.send(self.name, src, "client_write_reply", {
                "rid": rid, "err": over, "results": []})
            return
        if r is not None and getattr(r, "splitting", False):
            # write fence during the split's final catch-up (parity: the
            # reference fences the parent before the count flip)
            self._split_fence_rejects.increment()
            self.net.send(self.name, src, "client_write_reply", {
                "rid": rid, "err": int(ErrorCode.ERR_SPLITTING),
                "results": []})
            return
        if self._dup_fenced(r, payload.get("ops")):
            # failover-drill fence: the table is draining its
            # duplication before the flip — typed and RETRYABLE, so an
            # in-flight client rides its backoff onto the flipped
            # follower instead of acking a write the drill would strand
            self._dup_fence_rejects.increment()
            self.net.send(self.name, src, "client_write_reply", {
                "rid": rid, "err": int(ErrorCode.ERR_DUP_FENCED),
                "results": []})
            return
        if (r is None or r.status != PartitionStatus.PRIMARY
                or getattr(r, "restoring", False)
                or not self.lease_valid()):
            self.net.send(self.name, src, "client_write_reply", {
                "rid": rid, "err": int(ErrorCode.ERR_INVALID_STATE),
                "results": []})
            return
        gate = r.server._hash_gate(payload.get("partition_hash"))
        if gate:
            self.net.send(self.name, src, "client_write_reply", {
                "rid": rid, "err": gate, "results": []})
            return
        ops = [WriteOp(op, req) for op, req in payload["ops"]]
        sgate = r.server._write_gate()
        if sgate:
            # deny/throttle rejections are STORAGE statuses per op (the
            # standalone handlers return TryAgain the same way), not
            # framework routing errors — the caller must see them, not
            # retry into them
            self.net.send(self.name, src, "client_write_reply", {
                "rid": rid, "err": int(ErrorCode.ERR_OK),
                "results": [sgate] * len(ops)})
            return

        def reply(results) -> None:
            self.net.send(self.name, src, "client_write_reply", {
                "rid": rid, "err": int(ErrorCode.ERR_OK),
                "results": results})

        try:
            # ambient tenant around the 2PC submission: client_write
            # captures it for the deferred prepare fan-out's span tags
            with tenancy.bind(TENANTS.resolve(
                    payload.get("tenant")).name):
                r.client_write(ops, reply)
            # bill the tenant ONCE, here at the accepting primary, with
            # the same per-op math the apply path uses: apply runs at
            # commit on EVERY member (no client tenant ambient there),
            # so ambient attribution would miss it — and billing each
            # member's apply would charge a tenant its replication
            # factor
            from pegasus_tpu.server.capacity_units import (
                client_write_units,
            )

            TENANTS.charge(payload.get("tenant"),
                           client_write_units(payload["ops"]))
        except ReplicaBusyError:
            # typed retryable overload: the client backs off WITHOUT a
            # config refresh (the routing is right, the queue is full)
            self.net.send(self.name, src, "client_write_reply", {
                "rid": rid, "err": int(ErrorCode.ERR_BUSY),
                "results": []})
        except (StorageCorruptionError, OSError) as e:
            # the store under this write is corrupt or its disk is
            # dying: typed reply (retryable — the client's refresh
            # lands on the healed primary after the guardian's cure),
            # then detect -> quarantine -> re-learn
            self.net.send(self.name, src, "client_write_reply", {
                "rid": rid, "err": self._on_storage_error(gpid, e),
                "results": []})
        except (RuntimeError, ValueError):
            self.net.send(self.name, src, "client_write_reply", {
                "rid": rid, "err": int(ErrorCode.ERR_INVALID_STATE),
                "results": []})

    def _on_client_write_batch(self, src: str, payload: dict) -> None:
        """Explicitly batched writes from the cluster client: one
        message carries every write op for the partitions this node
        hosts; each partition's run of batchable ops replicates as ONE
        mutation through the existing 2PC pipeline (which keeps
        coalescing via MAX_BATCH_OPS/PIPELINE_DEPTH), all inside one
        group-commit window — one plog flush/fsync and one
        prepare_batch per peer for the whole message.

        payload: {rid, auth, deadline?, groups: [(gpid, items)]} with
        items = [(ops, partition_hash, deadline), ...] and ops =
        [(op_code, request), ...] (one item = one client write, the
        shape solo client_write carries). Reply: {rid, err, result:
        [(pidx, err, [(op_err, results)])]} aligned with the request's
        groups; per-partition gate failures surface in their slot's
        err, per-op failures (deadline, hash gate, busy) in that op's
        own err, so the client retries exactly what failed. The reply
        is sent only after every op's 2PC callback resolved (acks are
        durability-gated by the group-commit window)."""
        from pegasus_tpu.replica.mutation import ATOMIC_OPS, WriteOp
        from pegasus_tpu.utils.errors import ErrorCode

        ok = int(ErrorCode.ERR_OK)
        rid = payload.get("rid")
        if self._deadline_expired(payload):
            # whole-batch deadline lapsed before any 2PC started: an
            # unambiguous typed fast-fail (nothing ran — safe to retry)
            self.net.send(self.name, src, "client_write_reply", {
                "rid": rid, "err": int(ErrorCode.ERR_TIMEOUT),
                "result": None})
            return
        from pegasus_tpu.utils import tracing

        # CU budget gate, once for the carrier (one client = one
        # tenant); accepted items bill the tenant per submitted run
        # below. Writes stay exempt from brownout shedding.
        over = TENANTS.admit(payload.get("tenant"), kind="write")
        if over:
            self.net.send(self.name, src, "client_write_reply", {
                "rid": rid, "err": over, "result": None})
            return
        wtenant = TENANTS.resolve(payload.get("tenant")).name
        from pegasus_tpu.server.capacity_units import client_write_units

        groups = payload.get("groups") or []
        slots: list = []
        # batching-seam fan-out (write side): every batched item keeps
        # its own span under the carrier's dispatch span; the shared
        # 2PC rounds (combined runs) hang off the carrier too
        carrier = tracing.current_span()
        state = {"outstanding": 0, "armed": False, "replied": False}

        def maybe_reply() -> None:
            if (state["armed"] and not state["replied"]
                    and state["outstanding"] == 0):
                state["replied"] = True
                self.net.send(self.name, src, "client_write_reply", {
                    "rid": rid, "err": ok, "result": slots})

        for gpid, items in groups:
            gpid = tuple(gpid)
            r = self.replicas.get(gpid)
            if not self._client_allowed(r, payload, access="w", src=src):
                slots.append((gpid[1], int(ErrorCode.ERR_ACL_DENY),
                              None))
                continue
            if r is not None and getattr(r, "splitting", False):
                self._split_fence_rejects.increment()
                slots.append((gpid[1], int(ErrorCode.ERR_SPLITTING),
                              None))
                continue
            if self._dup_fenced(r):
                self._dup_fence_rejects.increment()
                slots.append((gpid[1], int(ErrorCode.ERR_DUP_FENCED),
                              None))
                continue
            if (r is None or r.status != PartitionStatus.PRIMARY
                    or getattr(r, "restoring", False)
                    or not self.lease_valid()):
                slots.append((gpid[1],
                              int(ErrorCode.ERR_INVALID_STATE), None))
                continue
            item_res: list = [None] * len(items)
            slots.append((gpid[1], ok, item_res))

            def submit(spans, ops_list, replica=r, results=item_res):
                """One client_write for a combined run; its response
                list splits back per original item via the spans."""
                if not ops_list:
                    return

                def cb(res, spans=spans, results=results) -> None:
                    off = 0
                    for i, n in spans:
                        results[i] = (ok, res[off:off + n])
                        off += n
                    state["outstanding"] -= 1
                    maybe_reply()

                state["outstanding"] += 1
                try:
                    with tenancy.bind(wtenant):
                        replica.client_write(ops_list, cb)
                    # accepted: bill the tenant at the primary with the
                    # apply path's per-op math (same single-billing
                    # rationale as the solo write handler)
                    TENANTS.charge(wtenant, client_write_units(
                        [(wo.op, wo.request) for wo in ops_list]))
                except ReplicaBusyError:
                    state["outstanding"] -= 1
                    for i, _n in spans:
                        results[i] = (int(ErrorCode.ERR_BUSY), [])
                except (StorageCorruptionError, OSError) as e:
                    state["outstanding"] -= 1
                    code = self._on_storage_error(
                        (replica.server.app_id, replica.server.pidx), e)
                    for i, _n in spans:
                        results[i] = (code, [])
                except (RuntimeError, ValueError):
                    state["outstanding"] -= 1
                    for i, _n in spans:
                        results[i] = (int(ErrorCode.ERR_INVALID_STATE),
                                      [])

            # runs of batchable ops combine into one client_write (one
            # mutation); atomic ops ride alone, submission order kept
            run_spans: list = []
            run_ops: list = []
            item_spans: list = []
            for i, (raw_ops, ph, dl) in enumerate(items):
                ispan = None
                if carrier is not None:
                    # per-item span opened around THIS item's handling
                    # (gates + its submission leg), so a gated item is
                    # visibly near-zero and items keep distinct windows
                    ispan = tracing.child_of(carrier,
                                             f"op.write.{gpid[1]}")
                    item_spans.append(ispan)
                if self._deadline_expired(
                        {"deadline": dl if dl is not None
                         else payload.get("deadline")}):
                    # per-op deadline: THIS op fast-fails before its
                    # 2PC starts; its window neighbors proceed
                    item_res[i] = (int(ErrorCode.ERR_TIMEOUT), [])
                    if ispan is not None:
                        ispan.tags["gated"] = "deadline"
                        ispan.finish()
                    continue
                gate = r.server._hash_gate(ph)
                if gate:
                    item_res[i] = (gate, [])
                    if ispan is not None:
                        ispan.tags["gated"] = "hash"
                        ispan.finish()
                    continue
                sgate = r.server._write_gate()
                if sgate:
                    # deny/throttle are STORAGE statuses per op, same
                    # as the solo handler's [sgate] * len(ops) reply
                    item_res[i] = (ok, [sgate] * len(raw_ops))
                    if ispan is not None:
                        ispan.tags["gated"] = "throttle"
                        ispan.finish()
                    continue
                wos = [WriteOp(op, req) for op, req in raw_ops]
                atomic = any(wo.op in ATOMIC_OPS for wo in wos)
                if atomic or len(run_ops) + len(wos) > r.MAX_BATCH_OPS:
                    submit(run_spans, run_ops)
                    run_spans, run_ops = [], []
                if atomic:
                    submit([(i, len(wos))], wos)
                    if ispan is not None:
                        ispan.finish()  # its leg submitted inline
                else:
                    run_spans.append((i, len(wos)))
                    run_ops.extend(wos)
            submit(run_spans, run_ops)
            for sp in item_spans:
                sp.finish()  # idempotent: gated/atomic already closed
        state["armed"] = True
        maybe_reply()

    def _on_client_read(self, src: str, payload: dict) -> None:
        """Dispatch a read op to the partition's storage app through the
        replica gate (parity: replica_stub::on_client_read
        replica_stub.cpp:1100 -> replica::on_client_read replica.cpp:386 ->
        storage_serverlet dispatch, common/storage_serverlet.h:52).

        payload: {gpid, rid, op, args, partition_hash?}; the reply carries
        `err` (framework routing error space) and `result` (the storage
        handler's return value — storage status codes live inside it).
        """
        from pegasus_tpu.utils.errors import ErrorCode

        rid = payload["rid"]
        op = payload.get("op", "get")
        err, r = self._client_read_gate(payload, src)
        if err is not None:
            self.net.send(self.name, src, "client_read_reply", {
                "rid": rid, "err": err, "result": None})
            return
        ph = payload.get("partition_hash")
        args = payload.get("args")
        srv = r.server
        from pegasus_tpu.replica.replica import PartitionStatus
        from pegasus_tpu.utils import perf_context as perf
        from pegasus_tpu.utils import tracing

        served_by = ("primary" if r.status == PartitionStatus.PRIMARY
                     else "secondary")
        tenant = TENANTS.resolve(payload.get("tenant")).name
        sp = tracing.current_span()
        if sp is not None:
            sp.tags["served_by"] = served_by
            sp.tags["tenant"] = tenant
        # activate the op's cost vector HERE with served_by pre-set: the
        # storage handlers adopt the ambient context (perf.current()),
        # so explain/trace/slow-log all show which replica role answered
        pc = perf.start(f"read.{op}")
        if pc is not None:
            pc.served_by = served_by
            pc.tenant = tenant
            perf.push(pc)
        # bind the requesting tenant for the serving body: every CU the
        # storage handlers bill below flows to this tenant's budget
        _tb = tenancy.bind(tenant)
        _tb.__enter__()
        try:
            if op == "get":
                result = srv.on_get(args, partition_hash=ph)
            elif op == "ttl":
                result = srv.on_ttl(args, partition_hash=ph)
            elif op == "multi_get":
                result = srv.on_multi_get(args)
            elif op == "batch_get":
                result = srv.on_batch_get(args)
            elif op == "sortkey_count":
                result = srv.on_sortkey_count(args)
            elif op == "get_scanner":
                result = srv.on_get_scanner(args)
            elif op == "scan_batch":
                result = srv.on_get_scanner_batch(args)
            elif op == "scan":
                result = srv.on_scan(args)
            elif op == "clear_scanner":
                result = srv.on_clear_scanner(args)
            else:
                self.net.send(self.name, src, "client_read_reply", {
                    "rid": rid,
                    "err": int(ErrorCode.ERR_HANDLER_NOT_FOUND),
                    "result": None})
                return
        except ValueError:
            # bad request arguments: permanent, NOT retryable — the client
            # must surface it, not burn retries refreshing its config
            self.net.send(self.name, src, "client_read_reply", {
                "rid": rid, "err": int(ErrorCode.ERR_INVALID_PARAMETERS),
                "result": None})
            return
        except (StorageCorruptionError, OSError) as e:
            # a block failed its crc (or the disk failed the read):
            # typed retryable reply — the client's backoff + config
            # refresh lands it on the healed primary — then the replica
            # quarantines and the guardian repairs via re-learn
            self.net.send(self.name, src, "client_read_reply", {
                "rid": rid,
                "err": self._on_storage_error(tuple(payload["gpid"]), e),
                "result": None})
            return
        except RuntimeError:
            self.net.send(self.name, src, "client_read_reply", {
                "rid": rid, "err": int(ErrorCode.ERR_INVALID_STATE),
                "result": None})
            return
        finally:
            _tb.__exit__(None, None, None)
            if pc is not None:
                perf.pop(pc)
        # the committed-decree stamp is the monotonic session token: the
        # client's next `monotonic` read for this partition carries it
        # as min_decree, so no later read can observe an older prefix
        self.net.send(self.name, src, "client_read_reply", {
            "rid": rid, "err": int(ErrorCode.ERR_OK), "result": result,
            "decree": r.last_committed_decree, "served_by": served_by})

    def _client_read_gate(self, payload: dict, src: str):
        """The read path's framework gates (ACL -> primary/lease ->
        split staleness), factored so the solo handler and both batched
        point-read paths apply them identically. Returns (err, replica);
        err None means the request may reach the storage app."""
        from pegasus_tpu.replica.replica import PartitionStatus
        from pegasus_tpu.utils.errors import ErrorCode

        if self._deadline_expired(payload):
            # abandoned work: the client's end-to-end deadline lapsed,
            # so the cheapest correct answer is a typed fast-fail
            self._node_deadline_expired.increment()
            return int(ErrorCode.ERR_TIMEOUT), None
        from pegasus_tpu.utils.fail_point import fail_point

        if fail_point(self._shed_fp_name) is not None:
            # injected sustained shedding (incident drills / the seeded
            # flight-recorder scenario): same typed ERR_BUSY the real
            # dispatcher shed returns, counted on the node's rpc entity
            self._node_read_shed.increment()
            return int(ErrorCode.ERR_BUSY), None
        tenant = payload.get("tenant")
        if TENANTS.browned(tenant):
            # aggressor-only brownout: the health engine flagged THIS
            # tenant's burn rate as the outlier, so only its reads shed
            # (typed ERR_BUSY — the client backs off without a config
            # refresh); every other tenant keeps being served
            self._node_read_shed.increment()
            TENANTS.note_shed(tenant)
            return int(ErrorCode.ERR_BUSY), None
        over = TENANTS.admit(tenant, kind="read")
        if over:
            # over CU budget: typed retryable ERR_CU_OVERBUDGET — the
            # client jitter-backs-off and re-sends without refreshing
            # its config (the routing table is right; the budget isn't)
            return over, None
        gpid = tuple(payload["gpid"])
        r = self.replicas.get(gpid)
        if not self._client_allowed(r, payload, access="r", src=src):
            return int(ErrorCode.ERR_ACL_DENY), None
        if (r is None or getattr(r, "restoring", False)
                or not r.ready_to_serve()):
            return int(ErrorCode.ERR_INVALID_STATE), None
        if r.status == PartitionStatus.PRIMARY:
            if not self.lease_valid():
                return int(ErrorCode.ERR_INVALID_STATE), None
        else:
            ferr = self._follower_gate(r, payload)
            if ferr is not None:
                return ferr, None
        # split staleness gate for EVERY read op (scanner paging ops
        # carry ph=None — their context was validated at get_scanner);
        # follower-served reads keep it too: a secondary of a split
        # parent must bounce rows the flip moved, exactly like a primary
        gate = r.server._hash_gate(payload.get("partition_hash"))
        if gate:
            return gate, None
        return None, r

    def _follower_gate(self, r, payload: dict) -> Optional[int]:
        """Secondary-serving decision for one consistency-levelled read.
        Returns None when this SECONDARY may answer it, else the typed
        bounce: ERR_INVALID_STATE for ops secondaries never serve
        (linearizable — the client misrouted, refresh + go to the
        primary), ERR_STALE_REPLICA (RETRYABLE, subset-only) when the
        beacon lease lapsed or the committed watermark misses the op's
        bound — the routing table is still right, so the client re-sends
        just the bounced ops to the primary without a config refresh.

        The lease guarantee: a secondary only answers while its
        beacon-acknowledged lease (worker lease < meta grace) is live,
        so by the time meta could have reassigned the partition around a
        partitioned node, that node has ALREADY stopped serving — the
        same self-fencing clock that gates a partitioned primary."""
        from pegasus_tpu.replica.replica import PartitionStatus
        from pegasus_tpu.utils.errors import ErrorCode

        cons = payload.get("consistency")
        if r.status != PartitionStatus.SECONDARY or not cons:
            return int(ErrorCode.ERR_INVALID_STATE)
        level = cons.get("level")
        if level not in ("bounded_stale", "monotonic"):
            return int(ErrorCode.ERR_INVALID_STATE)
        # stamping the gauge HERE is the point: the health rule and this
        # lease decision read the same age on the same clock
        self.beacon_ack_age()
        if not self.lease_valid():
            self._lease_rejects.increment()
            self._stale_bounces.increment()
            r.server._lease_rejects.increment()
            r.server._stale_bounces.increment()
            return int(ErrorCode.ERR_STALE_REPLICA)
        if level == "bounded_stale":
            max_lag_ms = float(cons.get("max_lag_ms") or 0.0)
            if r.staleness_s(self.sim_clock()) * 1000.0 > max_lag_ms:
                self._stale_bounces.increment()
                r.server._stale_bounces.increment()
                return int(ErrorCode.ERR_STALE_REPLICA)
        # the monotonic session token (and any bound a bounded_stale op
        # chooses to carry): never serve below the decree the client has
        # already observed for this partition
        min_decree = int(cons.get("min_decree") or 0)
        if r.last_committed_decree < min_decree:
            self._stale_bounces.increment()
            r.server._stale_bounces.increment()
            return int(ErrorCode.ERR_STALE_REPLICA)
        self._follower_reads.increment()
        r.server._follower_reads.increment()
        return None

    def _on_client_read_batch(self, items) -> None:
        """Transport flush-window delivery: a consecutive run of queued
        client_read messages as [(src, payload)]. Point ops (get / ttl
        / multi_get with sort keys / batch_get) from the whole window
        serve through the cross-partition read coordinator in ONE
        flush; everything else falls through to the solo handler in
        arrival order."""
        from pegasus_tpu.replica.replica import PartitionStatus
        from pegasus_tpu.server.read_coordinator import (
            is_point_read,
            point_read_multi,
        )
        from pegasus_tpu.utils import tracing
        from pegasus_tpu.utils.errors import ErrorCode

        flush: list = []  # (src, payload, replica, span) past the gates
        for src, payload in items:
            op = payload.get("op", "get")
            ctx = payload.get("trace")
            if not is_point_read(op, payload.get("args")):
                # solo fallback still gets its dispatch span (the
                # transport's batch drain skipped the generic one)
                span = tracing.start_server_span(
                    self.name, "client_read", ctx)
                try:
                    with tracing.activate(span):
                        self._on_client_read(src, payload)
                finally:
                    if span is not None:
                        span.finish()
                continue
            err, r = self._client_read_gate(payload, src)
            if err is not None:
                self.net.send(self.name, src, "client_read_reply", {
                    "rid": payload.get("rid"), "err": err,
                    "result": None})
                continue
            # per-message span parented to its OWN context: a flush
            # coalesces reads from many independent traces — each op
            # keeps its span, the flush never becomes one carrier
            span = tracing.start_server_span(self.name, "client_read", ctx)
            if span is not None:
                span.tags["served_by"] = (
                    "primary" if r.status == PartitionStatus.PRIMARY
                    else "secondary")
                span.tags["tenant"] = TENANTS.resolve(
                    payload.get("tenant")).name
            flush.append((src, payload, r, span))
        if not flush:
            return
        # group by (server, tenant): the transport's flush window
        # coalesces MANY clients' reads, so one batch may mix tenants —
        # splitting the groups keeps each finish pass (where the CU
        # funnel fires) billed to exactly the tenant that asked
        groups: dict = {}
        for i, (_src, payload_i, rep, _sp) in enumerate(flush):
            tname = TENANTS.resolve(payload_i.get("tenant")).name
            groups.setdefault((id(rep.server), tname),
                              (rep.server, tname, []))[2].append(i)
        pairs = [(server, [(flush[i][1].get("op", "get"),
                            flush[i][1].get("args"),
                            flush[i][1].get("partition_hash"))
                           for i in idxs])
                 for server, _tname, idxs in groups.values()]
        tenants = [tname for _server, tname, _idxs in groups.values()]
        # NO flush-wide deadline here: members carry INDEPENDENT
        # deadlines (already gate-checked above, microseconds ago), and
        # bounding the flush by the tightest one would let a single
        # tight-deadline client abort 31 healthy neighbors into a retry
        # round-trip. The explicit batch RPC passes its deadline down
        # because there one deadline really does govern the whole batch.
        try:
            try:
                results = point_read_multi(pairs, tenants=tenants)
            except (ValueError, RuntimeError, OSError):
                # malformed op in the flush — or a corrupt block /
                # failing disk under ONE member: re-serve each solo so
                # every request gets its own precise error instead of a
                # shared one (the solo path carries the typed corruption
                # handling and quarantines exactly the sick replica)
                for src, payload, _srv, span in flush:
                    with tracing.activate(span):
                        self._on_client_read(src, payload)
                return
            for (_server, _tname, idxs), res in zip(groups.values(),
                                                    results):
                for i, result in zip(idxs, res):
                    src, payload, rep, span = flush[i]
                    # the reply rides this op's span context (tail-keep
                    # bit included) back to its client; the decree stamp
                    # feeds the client's monotonic session token
                    with tracing.activate(span):
                        self.net.send(
                            self.name, src, "client_read_reply", {
                                "rid": payload.get("rid"),
                                "err": int(ErrorCode.ERR_OK),
                                "result": result,
                                "decree": rep.last_committed_decree,
                                "served_by": (
                                    "primary" if rep.status
                                    == PartitionStatus.PRIMARY
                                    else "secondary")})
        finally:
            for _src, _payload, _srv, span in flush:
                if span is not None:
                    span.finish()

    def _on_client_read_batch_rpc(self, src: str, payload: dict) -> None:
        """Explicitly batched point reads from the cluster client: one
        message carries every point op for the partitions this node
        hosts, served through the cross-partition read coordinator.
        Reply: {rid, err, result: [(pidx, err, results)]} aligned with
        the request's groups; per-partition gate failures surface in
        their slot's err so the client re-resolves just those."""
        from pegasus_tpu.server.read_coordinator import (
            is_point_read,
            point_read_multi,
        )
        from pegasus_tpu.utils.errors import ErrorCode, PegasusError

        from pegasus_tpu.replica.replica import PartitionStatus

        rid = payload.get("rid")
        groups = payload.get("groups") or []
        # batch-wide consistency level; per-partition monotonic session
        # tokens ride as (pidx, min_decree) pairs next to it
        cons = payload.get("consistency")
        min_decrees = dict(payload.get("min_decrees") or [])
        slots: list = []
        decrees: list = []  # (pidx, committed decree) for served slots
        ok: list = []  # (slot index, replica, ops)
        for gpid, ops in groups:
            gpid = tuple(gpid)
            # validate BEFORE planning: one malformed op must fail its
            # own slot, never leave the whole node batch unreplied
            if not all(len(o) == 3 and is_point_read(o[0], o[1])
                       for o in ops):
                slots.append((gpid[1],
                              int(ErrorCode.ERR_INVALID_PARAMETERS),
                              None))
                continue
            slot_cons = cons
            if cons is not None:
                slot_cons = dict(cons, min_decree=max(
                    int(cons.get("min_decree") or 0),
                    int(min_decrees.get(gpid[1], 0))))
            err, r = self._client_read_gate(
                {"gpid": gpid, "auth": payload.get("auth"),
                 "deadline": payload.get("deadline"),
                 "tenant": payload.get("tenant"),
                 "consistency": slot_cons}, src)
            if err is not None:
                slots.append((gpid[1], err, None))
                continue
            slots.append((gpid[1], int(ErrorCode.ERR_OK), None))
            decrees.append((gpid[1], r.last_committed_decree,
                            "primary" if r.status
                            == PartitionStatus.PRIMARY else "secondary"))
            ok.append((len(slots) - 1, r, ops))
        # batching-seam fan-out: each op in the carrier gets its own
        # span parented to the CARRIER's dispatch span — N ops in one
        # carrier yield N child spans, never N carriers
        from pegasus_tpu.utils import tracing

        # one carrier = one client = ONE tenant: bind it ambient around
        # the whole coordinator call so every partition's finish pass
        # bills this tenant's budget
        tname = TENANTS.resolve(payload.get("tenant")).name
        carrier = tracing.current_span()
        op_spans: list = []
        if carrier is not None:
            carrier.tags["tenant"] = tname
            for _slot_i, rep, ops in ok:
                role = ("primary" if rep.status == PartitionStatus.PRIMARY
                        else "secondary")
                for o in ops:
                    osp = tracing.child_of(
                        carrier, f"op.{o[0]}.{rep.server.pidx}")
                    osp.tags["served_by"] = role
                    osp.tags["tenant"] = tname
                    op_spans.append(osp)
        if ok:
            try:
                with tenancy.bind(tname):
                    results = point_read_multi(
                        [(rep.server, [tuple(o) for o in ops])
                         for _i, rep, ops in ok],
                        deadline=payload.get("deadline"), clock=self.clock)
            except PegasusError:
                # the batch's deadline lapsed mid-flush: typed timeout
                # for every slot this node accepted
                for slot_i, _srv, _ops in ok:
                    slots[slot_i] = (slots[slot_i][0],
                                     int(ErrorCode.ERR_TIMEOUT), None)
            except (ValueError, TypeError, AttributeError):
                # malformed args that slipped past the shape check:
                # a definite reply, never an unreplied batch
                for slot_i, _srv, _ops in ok:
                    slots[slot_i] = (slots[slot_i][0], int(
                        ErrorCode.ERR_INVALID_PARAMETERS), None)
            except (StorageCorruptionError, OSError) as e:
                # one member's store is corrupt: its slot gets the
                # typed code (and the replica quarantines); healthy
                # neighbors get retryable INVALID_STATE — their work
                # was lost with the shared flush, not their data
                bad = (self._replica_for_path(e.path)
                       if isinstance(e, StorageCorruptionError) else None)
                code = self._on_storage_error(bad, e)
                for slot_i, rep, _ops in ok:
                    hit = bad is not None and \
                        (rep.server.app_id, rep.server.pidx) == bad
                    slots[slot_i] = (
                        slots[slot_i][0],
                        code if (hit or bad is None)
                        else int(ErrorCode.ERR_INVALID_STATE), None)
            except RuntimeError:
                for slot_i, _rep, _ops in ok:
                    slots[slot_i] = (slots[slot_i][0], int(
                        ErrorCode.ERR_INVALID_STATE), None)
            else:
                for (slot_i, _rep, _ops), res in zip(ok, results):
                    slots[slot_i] = (slots[slot_i][0],
                                     int(ErrorCode.ERR_OK), res)
            finally:
                for sp in op_spans:
                    sp.finish()
        # `decrees` travels NEXT TO the slots (pidx, decree, served_by):
        # slot shape stays (pidx, err, results) for every existing
        # consumer, and the client folds the stamps into its monotonic
        # session tokens only for slots that actually served
        self.net.send(self.name, src, "client_read_reply", {
            "rid": rid, "err": int(ErrorCode.ERR_OK), "result": slots,
            "decrees": decrees})

    def _on_config_proposal(self, src: str, payload: dict) -> None:
        """Meta assigns a configuration (parity: on_config_proposal,
        replica_stub.cpp:2487 -> replica_config.cpp)."""
        gpid = tuple(payload["gpid"])
        config = ReplicaConfig(payload["ballot"], payload["primary"],
                               list(payload["secondaries"]))
        r = self._open_replica(gpid, payload.get("partition_count", 1))
        if payload.get("restoring"):
            # created from a backup: serve NOTHING until the restore
            # lands, or a stray early write would make the idempotence
            # check misread the partition as already restored
            r.restoring = True
        if gpid not in self._split_sessions:
            # the meta-carried fence: a parent whose child registered
            # stays fenced across failovers (a local split session's own
            # fence is authoritative while it runs)
            r.splitting = bool(payload.get("splitting"))
        new_count = payload.get("partition_count", 1)
        if new_count > r.server.partition_count:
            # the split's group count flip (meta_split_service _finish):
            # routing + the stale-half predicate switch to the new count,
            # the write fence lifts, and the split session retires
            r.server.update_partition_count(new_count)
            import json as _json

            info_path = os.path.join(self._replica_dir(gpid),
                                     ".replica_info")
            with open(info_path, "w") as f:
                _json.dump({"app_id": gpid[0], "pidx": gpid[1],
                            "partition_count": new_count}, f)
            r.splitting = False
            self._split_sessions.pop(gpid, None)
        r.assign_config(config)

    def _on_add_learner_cmd(self, src: str, payload: dict) -> None:
        """Meta tells the primary to pull in a learner (parity: config
        proposal ADD_SECONDARY -> primary starts the learn flow)."""
        gpid = tuple(payload["gpid"])
        r = self.replicas.get(gpid)
        if r is not None and r.status == PartitionStatus.PRIMARY:
            r.add_learner(payload["learner"])

    def _on_update_app_envs(self, src: str, payload: dict) -> None:
        """Meta propagates table envs (parity: config-sync env delivery)."""
        for gpid, r in self.replicas.items():
            if gpid[0] == payload["app_id"]:
                # meta always sends the table's complete env map, so
                # absent keys are deletions to un-apply
                r.server.update_app_envs(payload["envs"], full_set=True)
        # tenant declarations ride table envs too (``qos.tenants``), so
        # `shell set_app_envs` re-shapes weights/budgets online without
        # a restart — the registry ignores envs without the key
        TENANTS.configure_from_envs(payload.get("envs") or {})

    # ---- meta-driven backup / restore (parity: the replica-side cold
    # backup flow, replica/replica_backup.cpp, and restore,
    # replica/replica_restore.cpp — commanded by the meta services) -----

    def _on_backup_partition(self, src: str, payload: dict) -> None:
        from pegasus_tpu.replica.replica import PartitionStatus
        from pegasus_tpu.server.backup import BackupEngine
        from pegasus_tpu.storage.block_service import block_service_for

        gpid = tuple(payload["gpid"])
        r = self.replicas.get(gpid)
        if r is None or r.status != PartitionStatus.PRIMARY:
            return  # meta's tick retries against the current primary
        if not r.ready_to_serve():
            return  # promotion window not re-committed; meta retries
        key = (gpid, payload["backup_id"])
        if key in self._backup_inflight:
            return  # meta re-sends until done; one upload is enough
        self._backup_inflight.add(key)
        # checkpoint HERE (needs engine serialization with applies);
        # the slow upload runs off the dispatcher so beacons/prepares
        # keep flowing during a large backup
        import shutil
        import tempfile

        ckpt_dir = tempfile.mkdtemp(prefix="pegbk")
        try:
            decree = r.server.checkpoint(ckpt_dir)
        except Exception:
            shutil.rmtree(ckpt_dir, ignore_errors=True)
            self._backup_inflight.discard(key)
            raise

        def upload() -> None:
            from pegasus_tpu.utils.fail_point import fail_point

            try:
                if fail_point(f"{self.name}::backup_upload") is not None:
                    # upload to the block service failed: report nothing;
                    # the meta backup tick re-commands this partition
                    # until an upload completes
                    return
                engine = BackupEngine(block_service_for(payload["root"]),
                                      payload["policy"])
                engine.upload_checkpoint(payload["backup_id"], gpid[0],
                                         gpid[1], ckpt_dir, decree)
                self.net.send(self.name, src, "backup_partition_done", {
                    "gpid": gpid, "backup_id": payload["backup_id"],
                    "decree": decree})
            finally:
                shutil.rmtree(ckpt_dir, ignore_errors=True)
                self._backup_inflight.discard(key)

        self.net.offload(upload)

    def _on_restore_partition(self, src: str, payload: dict) -> None:
        from pegasus_tpu.replica.replica import PartitionStatus
        from pegasus_tpu.server.backup import BackupEngine
        from pegasus_tpu.storage.block_service import block_service_for

        gpid = tuple(payload["gpid"])
        r = self.replicas.get(gpid)
        if r is None or r.status != PartitionStatus.PRIMARY:
            return
        if not getattr(r, "restoring", False):
            # already restored (idempotence against meta's retry timer) —
            # clients were gated until the flag cleared, so no stray
            # write can masquerade as a completed restore
            self.net.send(self.name, src, "restore_partition_done",
                          {"gpid": gpid})
            return
        engine = BackupEngine(block_service_for(payload["root"]),
                              payload["policy"])
        app_dir = r.server.engine.data_dir
        r.server.engine.close()
        new_engine = engine.restore_partition(
            payload["backup_id"], payload["src_app_id"], gpid[1], app_dir)
        r.server.install_engine(new_engine)
        r.prepare_list.reset(new_engine.last_committed_decree)
        r.restoring = False
        self.net.send(self.name, src, "restore_partition_done",
                      {"gpid": gpid})

    def _on_trigger_ingest(self, src: str, payload: dict) -> None:
        """Meta commands an ingestion: the primary replicates an
        OP_INGEST mutation through 2PC so every member ingests at the
        same decree (parity: bulk-load ingestion, replica_2pc.cpp:211)."""
        from pegasus_tpu.replica.mutation import WriteOp
        from pegasus_tpu.replica.replica import PartitionStatus
        from pegasus_tpu.rpc.codec import OP_INGEST

        from pegasus_tpu.utils.fail_point import fail_point

        gpid = tuple(payload["gpid"])
        r = self.replicas.get(gpid)
        if r is None or r.status != PartitionStatus.PRIMARY:
            return  # meta's tick retries against the current primary
        if fail_point(f"{self.name}::ingest") is not None:
            # download/ingest failure before the 2PC round: no ack; the
            # meta bulk-load tick keeps re-commanding until it succeeds
            return
        load_id = payload.get("load_id", 0)
        key = (gpid, load_id)
        if r.has_ingested(load_id):
            # the load already committed groupwide (the marker is written
            # by every member at apply, so it survives failovers); re-ack
            # WITHOUT re-ingesting — a second OP_INGEST at a later decree
            # would resurrect keys deleted since the first one
            self.net.send(self.name, src, "ingest_done",
                          {"gpid": gpid, "err": 0})
            return
        if key in self._ingest_inflight:
            return  # download/2PC still running; meta's tick re-sends

        def done(results) -> None:
            self._ingest_inflight.discard(key)
            err = results[0] if results else 0
            self.net.send(self.name, src, "ingest_done", {
                "gpid": gpid, "err": err})

        self._ingest_inflight.add(key)
        try:
            r.client_write(
                [WriteOp(OP_INGEST,
                         (payload["root"], payload["src_app"], load_id))],
                done)
        except (RuntimeError, ValueError):
            self._ingest_inflight.discard(key)

    def _on_client_scan_multi(self, src: str, payload: dict) -> None:
        """Cross-partition batched scans: one message covers every
        partition this node hosts for the table; qualifying partitions
        share ONE stacked device evaluation (scan_coordinator). Reply:
        {rid, err, result: [(pidx, [ScanResponse])]} aligned with the
        request's groups; per-partition gate failures surface as
        error responses in that partition's slot."""
        from pegasus_tpu.replica.replica import PartitionStatus
        from pegasus_tpu.server.scan_coordinator import scan_multi
        from pegasus_tpu.server.types import ScanResponse
        from pegasus_tpu.utils.errors import ErrorCode

        rid = payload.get("rid")
        groups = payload.get("groups") or []
        cons = payload.get("consistency")
        min_decrees = dict(payload.get("min_decrees") or [])
        now = None
        ok_servers = []
        slots = []
        decrees = []  # (pidx, committed decree, served_by) per served slot
        for gpid, reqs in groups:
            gpid = tuple(gpid)
            r = self.replicas.get(gpid)
            if not self._client_allowed(r, payload, access="r", src=src):
                # auth/ACL is PERMANENT — distinct from stale-primary so
                # the client doesn't burn retries re-resolving
                errs = []
                for _req in reqs:
                    resp = ScanResponse()
                    resp.error = int(ErrorCode.ERR_ACL_DENY)
                    errs.append(resp)
                slots.append((gpid[1], errs))
                continue
            gerr = None
            if (r is None or getattr(r, "restoring", False)
                    or not r.ready_to_serve()):
                gerr = int(ErrorCode.ERR_INVALID_STATE)
            elif r.status == PartitionStatus.PRIMARY:
                if not self.lease_valid():
                    gerr = int(ErrorCode.ERR_INVALID_STATE)
            else:
                # same consistency gate as the point paths: a SECONDARY
                # serves the scan slot under its lease + watermark, or
                # bounces it typed so the client re-flies JUST this slot
                slot_cons = cons
                if cons is not None:
                    slot_cons = dict(cons, min_decree=max(
                        int(cons.get("min_decree") or 0),
                        int(min_decrees.get(gpid[1], 0))))
                gerr = self._follower_gate(
                    r, {"consistency": slot_cons})
            if gerr is None:
                # same tenant gates as the point-read path: brownout
                # sheds only the flagged aggressor, the CU budget
                # bounces over-budget scans typed-retryable
                tn = payload.get("tenant")
                if TENANTS.browned(tn):
                    self._node_read_shed.increment()
                    TENANTS.note_shed(tn)
                    gerr = int(ErrorCode.ERR_BUSY)
                else:
                    gerr = TENANTS.admit(tn, kind="read") or None
            if gerr is not None:
                errs = []
                for _req in reqs:
                    resp = ScanResponse()
                    resp.error = gerr
                    errs.append(resp)
                slots.append((gpid[1], errs))
                continue
            slots.append((gpid[1], None))
            decrees.append((gpid[1], r.last_committed_decree,
                            "primary" if r.status
                            == PartitionStatus.PRIMARY else "secondary"))
            ok_servers.append((len(slots) - 1, r.server, reqs))
        if ok_servers:
            from pegasus_tpu.base.value_schema import epoch_now

            now = epoch_now()
            # one carrier = one client = one tenant: the whole stacked
            # evaluation (finish_scan_batch bills the CU there) runs
            # under the requesting tenant's ambient binding
            tname = TENANTS.resolve(payload.get("tenant")).name
            try:
                with tenancy.bind(tname):
                    results = scan_multi(
                        [(srv, reqs) for _i, srv, reqs in ok_servers],
                        now)
            except (StorageCorruptionError, OSError) as e:
                # one member's store is corrupt (a scan-path block or
                # encoded-probe crc failed): its slot gets the typed
                # code (and the replica quarantines); healthy neighbors
                # get retryable INVALID_STATE — their work was lost
                # with the shared evaluation, not their data
                bad = (self._replica_for_path(e.path)
                       if isinstance(e, StorageCorruptionError) else None)
                code = self._on_storage_error(bad, e)
                for slot_i, srv, reqs in ok_servers:
                    hit = bad is not None and \
                        (srv.app_id, srv.pidx) == bad
                    errs = []
                    for _req in reqs:
                        resp = ScanResponse()
                        resp.error = (code if (hit or bad is None)
                                      else int(ErrorCode.ERR_INVALID_STATE))
                        errs.append(resp)
                    slots[slot_i] = (slots[slot_i][0], errs)
            except ValueError as e:
                # malformed request: a DEFINITE reply, not a dropped one
                # (retrying a deterministic failure helps no one)
                for slot_i, _srv, reqs in ok_servers:
                    errs = []
                    for _req in reqs:
                        resp = ScanResponse()
                        resp.error = int(
                            ErrorCode.ERR_INVALID_PARAMETERS)
                        errs.append(resp)
                    slots[slot_i] = (slots[slot_i][0], errs)
            else:
                for (slot_i, _srv, _reqs), resps in zip(ok_servers,
                                                        results):
                    slots[slot_i] = (slots[slot_i][0], resps)
        self.net.send(self.name, src, "client_read_reply", {
            "rid": rid, "err": int(ErrorCode.ERR_OK), "result": slots,
            "decrees": decrees})

    def _peer_key(self, src: str):
        """Session-scoped peer key for negotiation state: (src,
        connection id). On the TCP transport the connection id is
        unforgeable; the sim transport (in-process, trusted) has no
        sessions and keys on the name alone."""
        current = getattr(self.net, "current_session", None)
        return (src, current() if current is not None else "")

    def _client_allowed(self, r, payload: dict,
                        access: str = "", src: str = None) -> bool:
        """Auth + table-ACL gate (parity: the ACL gate leading the client
        gate stack, replica_2pc.cpp:117 / replica.cpp:388), with the
        Ranger-style per-verb access class (access_type.h) when the
        table carries a `replica.access_policy` env. A peer that
        completed the connection negotiation (security/negotiation.py)
        may omit per-request credentials: its SESSION identity applies,
        exactly like the reference attaches the negotiated user to the
        RPC session."""
        from pegasus_tpu.security.auth import check_client

        allowed = ""
        policy = ""
        if r is not None:
            allowed = r.server.app_envs.get("replica.allowed_users", "")
            policy = r.server.app_envs.get("replica.access_policy", "")
        auth = payload.get("auth")
        if (auth is None and src is not None and self.auth_secret
                and self._negotiation is not None):
            user = self._negotiation.identity(self._peer_key(src))
            if user is not None:
                # authenticated at negotiation time; only ACLs remain
                return check_client((user, ""), None, allowed,
                                    policy=policy, access=access)
        return check_client(auth, self.auth_secret,
                            allowed, policy=policy, access=access)

    # ---- partition split (parity: replica_split_manager.h:58 — the
    # replica-side parent/child state copy + catch-up; meta owns the
    # group count flip) --------------------------------------------------

    def _on_start_split(self, src: str, payload: dict) -> None:
        from pegasus_tpu.replica.replica import PartitionStatus

        gpid = tuple(payload["gpid"])
        r = self.replicas.get(gpid)
        if r is None or r.status != PartitionStatus.PRIMARY:
            return  # meta retries against the current primary
        if gpid in self._split_sessions:
            return  # already in progress on this node
        self._split_sessions[gpid] = {
            "phase": "ckpt", "child_gpid": tuple(payload["child_gpid"]),
            "new_count": payload["new_count"], "ckpt_decree": 0,
        }
        self._split_advance(gpid)

    def split_tick(self) -> None:
        """Timer: advance split sessions (drain waits on the in-flight
        window; register re-sends until the flip proposal lands)."""
        for gpid in list(self._split_sessions):
            self._split_advance(gpid)

    def _split_advance(self, gpid: Gpid) -> None:
        import shutil

        from pegasus_tpu.replica.replica import PartitionStatus

        sess = self._split_sessions.get(gpid)
        if sess is None:
            return
        r = self.replicas.get(gpid)
        if r is None or r.status != PartitionStatus.PRIMARY:
            # lost primaryship mid-split: abandon; meta re-drives the new
            # primary. Unfence locally (a meta proposal re-fences if the
            # child did register) and reap the half-built child — it was
            # never part of any config, and leaving it would resurrect at
            # boot scan as a zombie replica
            import shutil

            if r is not None:
                r.splitting = False
            child = self.replicas.pop(sess["child_gpid"], None)
            if child is not None:
                child.close()
            shutil.rmtree(self._replica_dir(sess["child_gpid"]),
                          ignore_errors=True)
            del self._split_sessions[gpid]
            return
        child_gpid = sess["child_gpid"]
        if sess["phase"] == "ckpt":
            # phase 1 — checkpoint copy WITHOUT a write fence (bulk of the
            # data moves while writes continue). A child replica already
            # open here is a leftover from a crashed/aborted earlier
            # attempt (boot scan resurrects half-built dirs): close and
            # rebuild from a fresh checkpoint, never resume unknown bytes
            stale = self.replicas.pop(child_gpid, None)
            if stale is not None:
                stale.close()
            child_dir = self._replica_dir(child_gpid)
            shutil.rmtree(child_dir, ignore_errors=True)
            os.makedirs(os.path.join(child_dir, "app"), exist_ok=True)
            sess["ckpt_decree"] = r.server.checkpoint(
                os.path.join(child_dir, "app", "sst"))
            # phase 2 — fence writes (clients get ERR_SPLITTING, retry);
            # only the small log tail remains to move
            r.splitting = True
            sess["phase"] = "drain"
        if sess["phase"] == "drain":
            if r.last_committed_decree < r.last_prepared_decree():
                return  # in-flight window still committing; tick retries
            child = self._open_replica(child_gpid, sess["new_count"])
            # replay the post-checkpoint tail THROUGH the child's own
            # prepare/commit pipeline: the child is born with a proper
            # plog and the exact apply semantics (atomic-op determinism)
            from pegasus_tpu.replica.mutation import Mutation  # noqa: F401

            for mu in r.log.read_range(sess["ckpt_decree"] + 1,
                                       r.last_committed_decree):
                child.prepare_list.prepare(mu)
                child.log.append(mu)
            from pegasus_tpu.replica.prepare_list import (
                COMMIT_TO_DECREE_HARD,
            )

            child.prepare_list.commit(r.last_committed_decree,
                                      COMMIT_TO_DECREE_HARD)
            sess["phase"] = "register"
        if sess["phase"] == "register":
            if self.meta_addr is not None:
                self.net.send(self.name, self.meta_addr, "register_child", {
                    "gpid": gpid, "child_gpid": child_gpid,
                    "primary": self.name})
            # stays in register until the flip proposal arrives
            # (_on_config_proposal clears the session + the fence)

    def _start_ckpt_fetch(self, gpid: Gpid, primary_src: str,
                          payload: dict) -> None:
        """LT_APP checkpoint on another host: pull it via the transfer
        service, then resume the learn (parity: on_learn_reply ->
        nfs copy_remote_files -> on_copy_remote_state_completed)."""
        import shutil

        from pegasus_tpu.replica.file_transfer import FileFetchSession

        if gpid in self._fetch_sessions:
            return
        r = self.replicas.get(gpid)
        if r is None:
            return
        local = os.path.join(self._replica_dir(gpid), "learn_fetch")
        shutil.rmtree(local, ignore_errors=True)

        def done(ok: bool) -> None:
            self._fetch_sessions.pop(gpid, None)
            if ok and self.replicas.get(gpid) is r:
                r.complete_remote_learn(primary_src, payload, local)
            shutil.rmtree(local, ignore_errors=True)

        self._fetch_sessions[gpid] = FileFetchSession(
            self.net, self.name, payload["checkpoint_node"],
            payload["checkpoint_dir"], local, done)

    def transfer_tick(self) -> None:
        """Timer: re-send possibly-lost transfer requests."""
        for sess in list(self._fetch_sessions.values()):
            sess.resend()

    # ---- duplication (parity: duplication_sync_timer driving the
    # replica-side pipeline; meta owns WHICH partitions duplicate) -------

    @staticmethod
    def _dup_fenced(r, ops=None) -> bool:
        """True when the replica's table is fenced for client writes by
        a duplication failover drill (`dup.fence` app env, propagated
        through config-sync like every env). Inbound DUPLICATION writes
        are exempt — they are replication-class traffic and a fenced
        master-master peer must still drain."""
        if r is None or not r.server.app_envs.get("dup.fence"):
            return False
        if ops:
            from pegasus_tpu.rpc.codec import OP_DUP_PUT, OP_DUP_REMOVE

            if all(op in (OP_DUP_PUT, OP_DUP_REMOVE)
                   for op, _req in ops):
                return False
        return True

    def _on_dup_apply_batch(self, src: str, payload: dict) -> None:
        """Follower side of WAN-shaped shipping: decompress one
        envelope, apply its ops IN DECREE ORDER as one 2PC mutation, ack
        at the batch's max decree. The ack carries this node's
        foreground-pressure counters so the source's dup governor backs
        catch-up off before this node starts shedding its own clients.
        No deadline and no dup fence apply — replication-class traffic
        (the source's log-GC floor waits on it)."""
        from pegasus_tpu.replica.mutation import WriteOp
        from pegasus_tpu.replica.replica import PartitionStatus
        from pegasus_tpu.rpc.codec import decode_write
        from pegasus_tpu.storage.block_codec import inflate_payload
        from pegasus_tpu.utils.errors import ErrorCode
        from pegasus_tpu.utils.fail_point import fail_point
        from pegasus_tpu.utils.metrics import METRICS

        gpid = tuple(payload["gpid"])
        rid = payload["rid"]

        def reply(err) -> None:
            rpc_ent = METRICS.entity("rpc", "dispatch", {})
            self.net.send(self.name, src, "dup_apply_batch_ack", {
                "rid": rid, "err": int(err), "node": self.name,
                "max_decree": payload.get("max_decree"),
                "pressure": {
                    "deadline_expired": rpc_ent.counter(
                        "deadline_expired_count").value(),
                    "read_shed": rpc_ent.counter(
                        "read_shed_count").value(),
                }})

        fp = fail_point("dup::apply_batch")
        if fp is not None:
            # chaos/test hook: reject the envelope with a typed error
            reply(int(fp) if str(fp).isdigit()
                  else int(ErrorCode.ERR_INVALID_STATE))
            return
        r = self.replicas.get(gpid)
        if not self._client_allowed(r, payload, access="w", src=src):
            reply(ErrorCode.ERR_ACL_DENY)
            return
        if r is not None and getattr(r, "splitting", False):
            self._split_fence_rejects.increment()
            reply(ErrorCode.ERR_SPLITTING)
            return
        if (r is None or r.status != PartitionStatus.PRIMARY
                or getattr(r, "restoring", False)
                or not self.lease_valid()):
            reply(ErrorCode.ERR_INVALID_STATE)
            return
        import struct as _struct

        try:
            raw = inflate_payload(payload["blob_mode"],
                                  payload["ops_blob"],
                                  payload["raw_len"])
            ops = []
            pos = 0
            for _ in range(payload["n_ops"]):
                (length,) = _struct.unpack_from("<I", raw, pos)
                pos += 4
                op, req, end = decode_write(raw, pos)
                if end != pos + length:
                    raise ValueError("dup envelope op length mismatch")
                ops.append(WriteOp(op, req))
                pos = end
        except (ValueError, KeyError, RuntimeError,
                _struct.error) as e:
            from pegasus_tpu.rpc.transport import _RateLimitedLog

            if not hasattr(self, "_dup_decode_log"):
                self._dup_decode_log = _RateLimitedLog()
            self._dup_decode_log.log(f"dup.decode.{gpid}", e)
            reply(ErrorCode.ERR_INVALID_PARAMETERS)
            return

        def done(_results) -> None:
            reply(ErrorCode.ERR_OK)

        try:
            r.client_write(ops, done)
        except ReplicaBusyError:
            reply(ErrorCode.ERR_BUSY)
        except (StorageCorruptionError, OSError) as e:
            reply(self._on_storage_error(gpid, e))
        except (RuntimeError, ValueError):
            reply(ErrorCode.ERR_INVALID_STATE)

    def _on_dup_add(self, src: str, payload: dict) -> None:
        from pegasus_tpu.replica.duplication_cluster import (
            ClusterDuplicator,
        )
        from pegasus_tpu.replica.replica import PartitionStatus

        gpid = tuple(payload["gpid"])
        dupid = payload["dupid"]
        r = self.replicas.get(gpid)
        if r is None or r.status != PartitionStatus.PRIMARY:
            return  # meta re-sends to the current primary on its tick
        key = (gpid, dupid)
        if key in self._dup_sessions:
            self._dup_sessions[key].fail_mode = payload.get("fail_mode",
                                                            "slow")
            return

        def progress(dup_id: int, confirmed: int) -> None:
            if self.meta_addr is not None:
                self.net.send(self.name, self.meta_addr,
                              "duplication_sync", {
                                  "gpid": gpid, "dupid": dup_id,
                                  "confirmed": confirmed})

        self._dup_sessions[key] = ClusterDuplicator(
            self, gpid, dupid, payload["follower_meta"],
            payload["follower_app"],
            confirmed_decree=payload.get("confirmed", 0),
            source_cluster_id=payload.get("source_cluster_id")
            or self.cluster_id,
            on_progress=progress,
            fail_mode=payload.get("fail_mode", "slow"))

    def dup_tick(self) -> None:
        """Timer: drive every dup session (parity: duplication_sync_timer).
        Sessions whose replica lost primaryship are dropped — meta
        re-homes them on the new primary."""
        from pegasus_tpu.replica.replica import PartitionStatus

        for key in list(self._dup_sessions):
            gpid, _dupid = key
            r = self.replicas.get(gpid)
            if r is None or r.status != PartitionStatus.PRIMARY:
                dup = self._dup_sessions.pop(key)
                if r is not None and dup in r.duplicators:
                    r.duplicators.remove(dup)
                continue
            self._dup_sessions[key].tick()

    # ---- notifications to meta ----------------------------------------

    def _notify_learn_completed(self, gpid: Gpid, learner: str) -> None:
        if self.meta_addr is not None:
            self.net.send(self.name, self.meta_addr, "learn_completed", {
                "gpid": gpid, "learner": learner})

    def _notify_replication_error(self, gpid: Gpid, member: str) -> None:
        if self.meta_addr is not None:
            self.net.send(self.name, self.meta_addr, "replication_error", {
                "gpid": gpid, "member": member})

    # ---- config sync (parity: the pull-reconciliation protocol —
    # replica_stub.cpp:944-954 query_configuration_by_node,
    # idl/meta_admin.thrift:103-115 stored_replicas/gc_replicas,
    # meta/meta_service.cpp:793) ----------------------------------------

    def _meta_targets(self) -> list:
        return self.meta_addrs or ([self.meta_addr]
                                   if self.meta_addr else [])

    def config_sync(self) -> None:
        """Timer: report stored replicas; meta replies with this node's
        authoritative configs plus replicas to garbage-collect. Pull-based
        reconciliation is how replicas converge after meta-side
        reconfiguration that happened while this node was unreachable.
        The report carries each replica's full config VIEW: after a meta
        leader change lost recent updates, the new leader adopts any
        reported config with a higher ballot (replicas are the recovery
        source of truth — parity: `recover` from replica list)."""
        from pegasus_tpu.utils.metrics import METRICS

        now = self.sim_clock()
        stored = []
        for gpid, r in self.replicas.items():
            entry = {"gpid": gpid, "ballot": r.config.ballot,
                     "primary": r.config.primary,
                     "secondaries": list(r.config.secondaries),
                     "partition_count": r.server.partition_count}
            if r.status == PartitionStatus.PRIMARY:
                # elasticity detect signals ride the existing report:
                # cumulative capacity units + the hotkey detector's
                # published result, sampled on the node's clock so the
                # meta-side controller can turn them into rates
                srv = r.server
                hot = (srv.hotkey_collectors["read"].hot_hash_key()
                       or srv.hotkey_collectors["write"].hot_hash_key())
                entry["load"] = {
                    "read_cu": srv.cu.read_cu,
                    "write_cu": srv.cu.write_cu,
                    "hot_key": hot,
                    "hot_state": {
                        k: hc.state.value
                        for k, hc in srv.hotkey_collectors.items()},
                    "at": now,
                }
                # workload shape digest rides the same report (op mix,
                # batch/value sizes, scan selectivity, hot share) —
                # meta folds per table for `shell workload`
                entry["workload"] = srv.workload.summary()
            stored.append(entry)
        # foreground-pressure counters (PR 2 shed/deadline machinery):
        # the controller backs its move pacing off when these grow
        rpc_ent = METRICS.entity("rpc", "dispatch", {})
        pressure = {
            "deadline_expired": rpc_ent.counter(
                "deadline_expired_count").value(),
            "read_shed": rpc_ent.counter("read_shed_count").value(),
        }
        # compaction demand for the meta-side stagger coordinator (the
        # reply's compact_grant answers it); the same tick drives the
        # governor's pressure feedback on nodes with no compaction
        # currently paying acquire()
        from pegasus_tpu.storage.compact_governor import GOVERNOR

        GOVERNOR.poke()
        compaction = GOVERNOR.report()
        # tail-kept slow-trace summaries ride the EXISTING config-sync
        # channel so `shell traces --slow` is ONE meta call instead of a
        # cluster-wide fan-out (the full spans still fan out on demand
        # via the trace-dump verb)
        from pegasus_tpu.utils import tracing

        ring = tracing.ring_for(self.name)
        trace_report = {
            "kept": ring.kept_count.value(),
            "roots": ring.slow_roots(limit=16),
        }
        # duplication health rides the same report: per-dup lag (decrees
        # + ms), shipped bytes, error counts, last error — meta's
        # duplication_service aggregates these into cluster-wide dup
        # health (`dup_stats`) and the failover drill's drain check
        dup_report = []
        for (dgpid, _dupid), sess in list(self._dup_sessions.items()):
            dr = self.replicas.get(dgpid)
            if dr is None or dr.status != PartitionStatus.PRIMARY:
                continue
            dup_report.append(sess.stats())
        # health digest + the watchdog events since the last report ride
        # the SAME channel into the meta-side ClusterHealth machine —
        # drained ONCE, outside the target loop (every meta-group member
        # gets the identical block; only the leader acts)
        health_report = self.health.drain_report()
        # per-tenant QoS stats ride the same report so meta (and the
        # collector's cluster view) can fold tenant burn across nodes
        # without a fan-out
        tenant_report = TENANTS.snapshot()
        for meta in self._meta_targets():
            self.net.send(self.name, meta, "config_sync", {
                "node": self.name, "stored": stored,
                "pressure": pressure, "compaction": compaction,
                "dup": dup_report,
                "health": health_report,
                "tenants": tenant_report,
                # NB: key must not be "trace" — that's the wire slot
                # for the distributed-tracing context
                "trace_report": trace_report})

    def _on_config_sync_reply(self, src: str, payload: dict) -> None:
        import shutil

        if "compact_grant" in payload:
            from pegasus_tpu.storage.compact_governor import GOVERNOR

            GOVERNOR.set_cluster_grant(bool(payload["compact_grant"]))
        if "health_ack" in payload:
            # meta journaled our shipped health events up to this seq:
            # stop re-shipping them
            self.health.ack_report(int(payload["health_ack"]))
        for entry in payload["configs"]:
            gpid = tuple(entry["gpid"])
            r = self._open_replica(gpid, entry["partition_count"])
            r.assign_config(ReplicaConfig(entry["ballot"], entry["primary"],
                                          list(entry["secondaries"])))
            if "envs" in entry:
                # authoritative full set from meta — empty means ALL
                # table envs were deleted and must be un-applied
                r.server.update_app_envs(entry["envs"], full_set=True)
        for gpid in payload.get("gc", []):
            gpid = tuple(gpid)
            r = self.replicas.pop(gpid, None)
            if r is not None:
                # an in-flight checkpoint fetch must die with the replica
                # (its completion callback would resurrect a closed one)
                sess = self._fetch_sessions.pop(gpid, None)
                if sess is not None:
                    sess._finished = True
                r.close()
                # trash, don't delete: the disk cleaner ages it out
                # (parity: .gar dirs, replica/disk_cleaner.*)
                self.fs.trash_replica(gpid)

    # ---- failure detector (worker side) -------------------------------

    def send_beacon(self) -> None:
        """Parity: the FD beacon ping (failure_detector.h:79) — sent to
        every meta-group member; only the leader's FD acts."""
        from pegasus_tpu.utils.fail_point import fail_point

        if fail_point(self._beacon_drop_fp_name) is not None:
            # chaos: this node's beacon dies on the floor — no ack, so
            # its worker lease (and with it the follower-read lease)
            # lapses deterministically while meta's grace counts down,
            # exactly the partitioned-node timeline the lease must fence
            return
        for meta in self._meta_targets():
            self.net.send(self.name, meta, "beacon", {"node": self.name})
