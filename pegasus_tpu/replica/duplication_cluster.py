"""Cluster duplication: ship a partition's committed mutations to a
follower cluster's table over the network, through the follower's 2PC.

Parity: the replica-side duplication pipeline (replica_duplicator.h:79,
duplication_pipeline.h:42-76) with pegasus_mutation_duplicator.h:56 as
the shipping backend — here the backend is the wire: shipped writes are
OP_DUP_PUT / OP_DUP_REMOVE mutations sent to the follower partition's
primary (client_write), which replicates them to the follower's members
and resolves conflicts via the carried source timetags.

Confirmation discipline (the part the in-process TableShipper doesn't
need): `confirmed_decree` advances ONLY after the follower's primary
acks the write — a crash between ship and ack re-ships the same
mutations, which is safe because dup application is idempotent (same
timetag loses the `>` comparison the second time).
"""

from __future__ import annotations

import itertools
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from pegasus_tpu.base.key_schema import generate_key, key_hash
from pegasus_tpu.base.value_schema import (
    PEGASUS_EPOCH_BEGIN,
    expire_ts_from_ttl,
    generate_timetag,
)
from pegasus_tpu.replica.mutation import ATOMIC_OPS, Mutation
from pegasus_tpu.rpc.codec import (
    OP_DUP_PUT,
    OP_DUP_REMOVE,
    OP_MULTI_PUT,
    OP_MULTI_REMOVE,
    OP_PUT,
    OP_REMOVE,
)

_RIDS = itertools.count(1_000_000)

# fail_mode "skip": rejections of the same decree tolerated before the
# mutation is abandoned (each retry is a full re-resolve + re-ship round)
_FAIL_SKIP_RETRIES = 3


class ClusterDuplicator:
    """One partition's dup session on its primary's node.

    Driven by the stub: `tick()` from the dup timer; `on_write_reply` /
    `on_follower_config` from inbound messages. At most one mutation is
    in flight at a time (ordering: the follower must apply mutations in
    decree order for timetag floors to behave like the reference's
    single-channel shipping).
    """

    def __init__(self, stub, gpid: Tuple[int, int], dupid: int,
                 follower_meta: str, follower_app: str,
                 confirmed_decree: int = 0,
                 source_cluster_id: int = 1,
                 on_progress: Optional[Callable[[int, int], None]] = None,
                 fail_mode: str = "slow") -> None:
        self.stub = stub
        self.gpid = gpid
        self.dupid = dupid
        self.follower_meta = follower_meta
        self.follower_app = follower_app
        self.confirmed_decree = confirmed_decree
        self.source_cluster_id = source_cluster_id
        self.on_progress = on_progress
        # "slow": retry a rejected mutation forever (default, lossless);
        # "skip": after _FAIL_SKIP_RETRIES rejections of the SAME decree,
        # confirm past it (parity: duplication fail_mode FAIL_SKIP —
        # operator-sanctioned loss to un-wedge a stuck pipeline)
        self.fail_mode = fail_mode
        self._fail_decree: Optional[int] = None
        self._fail_count = 0
        self._fconfig: Optional[dict] = None  # follower app config
        # a FEW recent ask rids stay live: a re-ask must not discard a
        # SLOW (not lost) reply to an earlier ask — the same
        # retained-rid discipline the write path uses
        self._config_rids: "deque[int]" = deque(maxlen=4)
        self._config_ticks = 0  # ticks since the newest config ask
        # in-flight mutation: decree + outstanding write rids. rid →
        # follower pidx, so a LATE ack from a superseded ship attempt of
        # the same decree still completes that pidx (acks slower than the
        # re-drive cadence must not be discarded — that livelocks).
        self._inflight_decree: Optional[int] = None
        self._outstanding: Dict[int, int] = {}
        self._pending_pidx: set = set()
        self._redrive_decree: Optional[int] = None
        self._inflight_ticks = 0
        self._retry_limit = self.RETRY_TICKS
        self._log_offset = 0
        self._log_generation: Optional[int] = None
        replica = stub.get_replica(gpid)
        if replica is not None:
            self._log_generation = replica.log.generation
            replica.duplicators.append(self)

    # ---- follower config -----------------------------------------------

    def _request_follower_config(self) -> None:
        rid = next(_RIDS)
        self._config_rids.append(rid)
        self.stub.net.send(self.stub.name, self.follower_meta,
                           "query_config",
                           {"app_name": self.follower_app, "rid": rid})

    def on_follower_config(self, payload: dict) -> bool:
        rid = payload.get("rid")
        if rid not in self._config_rids:
            return False
        if payload["err"] == 0:
            self._config_rids.clear()
            self._fconfig = {
                "app_id": payload["app_id"],
                "partition_count": payload["partition_count"],
                "configs": payload["configs"],
            }
        else:
            # an error reply settles only ITS ask: a newer in-flight
            # ask's (possibly successful) reply must stay acceptable
            self._config_rids.remove(rid)
        return True

    # ---- shipping ------------------------------------------------------

    RETRY_TICKS = 3  # in-flight ship attempts re-drive after this many

    def tick(self) -> None:
        """Load → ship the next committed mutation (one at a time)."""
        from pegasus_tpu.replica.replica import PartitionStatus

        replica = self.stub.get_replica(self.gpid)
        if replica is None or replica.status != PartitionStatus.PRIMARY:
            return  # dup runs on the primary only (meta re-homes us)
        if self._inflight_decree is not None:
            # waiting on follower acks — but a LOST shipped write (or a
            # lost ack) must not wedge the pipeline forever: after a few
            # ticks, re-resolve and re-ship the same decree. Re-shipping
            # is safe — dup ops are idempotent on the follower (timetag
            # conflict resolution discards the stale double-apply).
            # The old rids stay registered (see _ship) and the re-drive
            # interval backs off exponentially, so a follower whose RTT
            # exceeds the base cadence converges instead of livelocking.
            self._inflight_ticks += 1
            if self._inflight_ticks < self._retry_limit:
                return
            # modest backoff cap: retained rids (below) already let a
            # slow follower converge via LATE acks, so the backoff only
            # reduces re-ship traffic — a large cap would instead gut
            # convergence under LINK LOSS, where re-drives are the only
            # recovery (seed-sweep regression on case-608)
            self._retry_limit = min(self._retry_limit * 2, 12)
            self._fconfig = None
            self._redrive_decree = self._inflight_decree
            self._inflight_decree = None
            self._inflight_ticks = 0
        if self._fconfig is None:
            # the config ask (or its reply) can be LOST: re-issue with a
            # fresh rid after a few ticks, or a single dropped message
            # wedges the whole pipeline forever (seed-sweep finding —
            # the canonical schedule never dropped this message)
            if not self._config_rids:
                self._request_follower_config()
                self._config_ticks = 0
            else:
                self._config_ticks += 1
                if self._config_ticks >= self.RETRY_TICKS:
                    self._request_follower_config()
                    self._config_ticks = 0
            return
        log = replica.log
        if log.generation != self._log_generation:
            self._log_offset = 0
            self._log_generation = log.generation
        last_committed = replica.last_committed_decree
        for mu, frame_end in log.read_tail(self._log_offset):
            if mu.decree > last_committed:
                break
            if mu.decree <= self.confirmed_decree:
                self._log_offset = frame_end
                continue
            self._ship(mu, frame_end)
            return  # one mutation in flight

    def _ship(self, mu: Mutation, frame_end: int) -> None:
        mu_now = max(0, mu.timestamp_us // 1_000_000 - PEGASUS_EPOCH_BEGIN)
        by_pidx: Dict[int, List[tuple]] = {}
        count = self._fconfig["partition_count"]
        for i, wo in enumerate(mu.ops):
            timetag = generate_timetag(mu.timestamp_us + i,
                                       self.source_cluster_id, False)
            for key, dup_op, req in self._dup_ops(wo, timetag, mu_now):
                by_pidx.setdefault(key_hash(key) % count, []).append(
                    (dup_op, req))
        if not by_pidx:
            # nothing shippable (e.g. empty mutation): confirm and move on
            self._advance(mu.decree, frame_end)
            return
        self._inflight_decree = mu.decree
        self._inflight_frame_end = frame_end
        if mu.decree != self._redrive_decree:
            self._outstanding = {}  # new decree: prior rids are dead
        self._redrive_decree = None
        self._pending_pidx = set(by_pidx)
        self._inflight_ticks = 0
        for pidx, ops in by_pidx.items():
            primary = self._fconfig["configs"][pidx]["primary"]
            if not primary:
                # follower partition unowned: drop config, retry later
                self._fconfig = None
                self._inflight_decree = None
                return
            rid = next(_RIDS)
            self._outstanding[rid] = pidx
            auth = None
            if getattr(self.stub, "auth_secret", None):
                from pegasus_tpu.security.auth import (
                    NODE_USER,
                    make_credentials,
                )

                auth = make_credentials(NODE_USER, self.stub.auth_secret)
            # deliberately NO deadline on duplication-shipped writes:
            # this is replication-class traffic (the log-GC floor waits
            # on it), so it must never be fast-failed as abandoned —
            # same exemption the dispatcher's overload shedding applies
            self.stub.net.send(self.stub.name, primary, "client_write", {
                "gpid": (self._fconfig["app_id"], pidx), "rid": rid,
                "ops": ops, "auth": auth})

    @staticmethod
    def _timetag_cluster(timetag: int) -> int:
        return (timetag >> 1) & 0x7F

    def _dup_ops(self, wo, timetag: int, mu_now: int):
        """Translate one logged write op into (key, dup_op, request)s."""
        if wo.op in (OP_DUP_PUT, OP_DUP_REMOVE):
            # a dup-tagged op is either (a) an idempotent-translated
            # LOCAL atomic (timetag minted with OUR cluster id) — ship
            # verbatim — or (b) a write RECEIVED from another cluster's
            # duplication: re-shipping those would echo master-master
            # writes back and forth forever (the reference's
            # origin-cluster filter)
            if (self._timetag_cluster(wo.request[-1])
                    == self.source_cluster_id):
                yield wo.request[0], wo.op, wo.request
            return
        if wo.op in ATOMIC_OPS:
            # unreachable on tables that enabled duplication BEFORE the
            # write (client_write idempotent-translates); mutations
            # logged before dup-add may still carry raw atomic ops —
            # those cannot ship safely (re-execution) and are skipped,
            # matching the reference's requirement that idempotence be
            # enabled before adding a duplication
            return
        if wo.op == OP_PUT:
            key, user_data, expire_ts = wo.request
            yield key, OP_DUP_PUT, (key, user_data, expire_ts, timetag)
        elif wo.op == OP_REMOVE:
            (key,) = wo.request
            yield key, OP_DUP_REMOVE, (key, timetag)
        elif wo.op == OP_MULTI_PUT:
            expire_ts = expire_ts_from_ttl(wo.request.expire_ts_seconds,
                                           now=mu_now)
            for kv in wo.request.kvs:
                key = generate_key(wo.request.hash_key, kv.key)
                yield key, OP_DUP_PUT, (key, kv.value, expire_ts, timetag)
        elif wo.op == OP_MULTI_REMOVE:
            for sk in wo.request.sort_keys:
                key = generate_key(wo.request.hash_key, sk)
                yield key, OP_DUP_REMOVE, (key, timetag)

    def on_write_reply(self, payload: dict) -> bool:
        rid = payload.get("rid")
        if rid not in self._outstanding:
            return False
        if payload["err"] != 0:
            decree = self._inflight_decree
            if self.fail_mode == "skip" and decree is not None:
                if self._fail_decree == decree:
                    self._fail_count += 1
                else:
                    self._fail_decree, self._fail_count = decree, 1
                if self._fail_count >= _FAIL_SKIP_RETRIES:
                    # operator chose loss over a wedged pipeline: confirm
                    # past the poison mutation and move on
                    self._advance(decree, self._inflight_frame_end)
                    self._fail_decree, self._fail_count = None, 0
                    self._inflight_decree = None
                    self._outstanding = {}
                    return True
            # follower rejected (failover/stale config): re-resolve and
            # re-ship the whole mutation — idempotent on the follower
            self._fconfig = None
            self._inflight_decree = None
            self._outstanding = {}
            return True
        pidx = self._outstanding.pop(rid)
        self._pending_pidx.discard(pidx)
        # an ack is PROGRESS: the link works — stop backing off AND
        # restart the re-drive clock (without resetting the tick count a
        # shrunken limit would fire a spurious re-drive next tick)
        self._retry_limit = self.RETRY_TICKS
        self._inflight_ticks = 0
        if not self._pending_pidx and self._inflight_decree is not None:
            self._advance(self._inflight_decree, self._inflight_frame_end)
            self._inflight_decree = None
            self._outstanding = {}
        return True

    def _advance(self, decree: int, frame_end: int) -> None:
        self.confirmed_decree = decree
        self._log_offset = frame_end
        if self.on_progress is not None:
            self.on_progress(self.dupid, decree)
